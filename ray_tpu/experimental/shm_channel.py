"""Cross-process mutable-object channels over shared memory.

Reference parity: the shared-memory channel substrate under Compiled
Graphs (/root/reference/python/ray/experimental/channel/
shared_memory_channel.py:151 and the mutable-object manager
src/ray/core_worker/experimental_mutable_object_manager.h:44 — a
version-stamped writable buffer with reader/writer synchronization,
transported through plasma).

TPU-host inversion: one mmap'd file per channel (under /dev/shm when
available) laid out as

    header:  magic | num_readers | closed | version | data_len | capacity
    acks:    one u64 per reader — the last version that reader consumed
    data:    capacity bytes (pickled payload)

Synchronization is lock-free: the writer waits until every ack equals
the current version (all readers consumed it), writes the payload, THEN
bumps the version; each reader waits for a version above its ack, reads,
and stores the new version into ITS OWN ack slot. Every shared word is
an aligned 8-byte slot written by exactly one side, so plain coherent
stores are enough — no futexes, no semaphores, and the payload bytes
cross processes through the page cache with zero RPC round trips.

MEMORY-ORDERING ASSUMPTION: the payload→data_len→version store order is
published with plain stores, which the reader is guaranteed to observe
in order only under TSO (x86/x86_64). On weakly-ordered hosts (ARM) a
reader could observe the bumped version before the payload bytes and
unpickle a torn buffer — creation therefore warns off-x86. TPU hosts
are x86_64, so this is the honest trade for a dependency-free seqlock;
a portable build would publish the version through a C11 atomic with
release/acquire semantics (one small C helper).
Same-host only by construction (cross-host traffic rides the RPC/object
planes); in-process endpoints should prefer experimental.channel.Channel
which passes references with no serialization at all.

Handles pickle as (path, layout) and re-open on the other side, so a
channel endpoint can ride into a process-executor actor as a plain
argument.
"""

from __future__ import annotations

import fcntl
import mmap
import os
import pickle
import struct
import tempfile
import time
from typing import Any, Optional

from .channel import ChannelClosedError

_MAGIC = 0x52545043484E4C31  # "RTPCHNL1"
_HDR = struct.Struct("<QQQQQQ")  # magic, num_readers, closed, version, data_len, capacity
_ACK = struct.Struct("<Q")
_U64 = struct.Struct("<Q")
# Byte offsets of the individually-owned header words. The single-writer
# discipline holds per WORD: magic/num_readers/capacity are written once
# at create; closed is written ONLY by close(); version and data_len ONLY
# by write(). No read-modify-write of the whole header ever happens after
# creation, so a close racing a write can neither be erased nor regress
# the version stamp.
_OFF_CLOSED = 16
_OFF_VERSION = 24
_OFF_DATA_LEN = 32


def _shm_dir() -> str:
    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


_reaped_once = False


def _reap_stale_channels(shm_dir: str) -> None:
    """Unlink channel files no live ENDPOINT holds open: every open
    channel keeps a shared flock on its file, so an exclusive
    non-blocking flock succeeding proves abandonment (creator-pid would
    be the wrong proxy — dag pipelines outlive the driver that created
    their channels, and PID namespaces lie across containers). Runs
    once per process: a SIGKILLed user must not leak tmpfs RAM forever,
    but per-creation directory scans would be pure overhead."""
    global _reaped_once
    if _reaped_once:
        return
    _reaped_once = True
    try:
        names = os.listdir(shm_dir)
    except OSError:
        return
    for name in names:
        if not name.startswith("ray_tpu_chan_"):
            continue
        path = os.path.join(shm_dir, name)
        try:
            fd = os.open(path, os.O_RDWR)
        except OSError:
            continue
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            os.close(fd)
            continue  # some endpoint somewhere holds it: live
        try:
            os.unlink(path)  # abandoned: no endpoint held the lock
        except OSError:
            pass
        finally:
            os.close(fd)  # releases the exclusive lock


class ShmChannel:
    """Single-slot, version-stamped, multi-reader channel across OS
    processes on one host. Create once (create=True), hand the object to
    readers (it pickles by path); each reader calls ``reader(i)`` for
    its dedicated ack slot."""

    _warned_weak_ordering = False

    def __init__(self, capacity: int = 1 << 20, num_readers: int = 1,
                 path: Optional[str] = None, _create: bool = True):
        if num_readers < 1:
            raise ValueError("num_readers must be >= 1")
        import platform

        machine = platform.machine().lower()
        if machine not in ("x86_64", "amd64", "i686", "i386") and (
            not ShmChannel._warned_weak_ordering
        ):
            ShmChannel._warned_weak_ordering = True
            import warnings

            warnings.warn(
                "ShmChannel's lock-free protocol assumes TSO (x86) store "
                f"ordering; on {machine} a reader may observe a torn "
                "payload. See the module docstring.",
                RuntimeWarning,
            )
        self.capacity = int(capacity)
        self.num_readers = int(num_readers)
        self._data_off = _HDR.size + _ACK.size * self.num_readers
        if _create:
            if path is None:
                shm_dir = _shm_dir()
                _reap_stale_channels(shm_dir)
                fd, self.path = tempfile.mkstemp(
                    prefix=f"ray_tpu_chan_{os.getpid()}_", dir=shm_dir
                )
            else:
                fd, self.path = os.open(path, os.O_CREAT | os.O_RDWR), path
            try:
                # lease FIRST: between mkstemp and LOCK_SH the file would
                # otherwise be visible-but-unleased, and a concurrent
                # process's sweep could reap a channel being born
                fcntl.flock(fd, fcntl.LOCK_SH)
                os.ftruncate(fd, self._data_off + self.capacity)
                self._mm = mmap.mmap(fd, self._data_off + self.capacity)
            except BaseException:
                os.close(fd)
                if path is None:
                    try:
                        os.unlink(self.path)  # half-born mkstemp file
                    except OSError:
                        pass
                raise
            _HDR.pack_into(
                self._mm, 0, _MAGIC, self.num_readers, 0, 0, 0, self.capacity
            )
        else:
            self.path = path
            fd = os.open(path, os.O_RDWR)
            try:
                fcntl.flock(fd, fcntl.LOCK_SH)  # lease before anything else
                self._mm = mmap.mmap(fd, self._data_off + self.capacity)
            except BaseException:
                os.close(fd)
                raise
            magic, nr, _, _, _, cap = _HDR.unpack_from(self._mm, 0)
            if magic != _MAGIC or nr != self.num_readers or cap != self.capacity:
                os.close(fd)
                raise ValueError(f"channel file {path!r} does not match layout")
        # the fd stays OPEN holding the shared flock: it is this
        # endpoint's liveness lease — the stale-channel reaper only
        # unlinks files on which an exclusive flock succeeds
        self._fd = fd
        self._owner = _create

    # ------------------------------------------------------------- plumbing

    def _read_header(self):
        return _HDR.unpack_from(self._mm, 0)

    def _version(self) -> int:
        return _U64.unpack_from(self._mm, _OFF_VERSION)[0]

    def _closed(self) -> bool:
        return bool(_U64.unpack_from(self._mm, _OFF_CLOSED)[0])

    def _ack(self, i: int) -> int:
        return _ACK.unpack_from(self._mm, _HDR.size + _ACK.size * i)[0]

    def _set_ack(self, i: int, version: int) -> None:
        _ACK.pack_into(self._mm, _HDR.size + _ACK.size * i, version)

    @staticmethod
    def _wait(predicate, timeout: Optional[float], what: str) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        pause = 20e-6
        while not predicate():
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"shm channel {what} timed out")
            time.sleep(pause)
            pause = min(pause * 2, 1e-3)

    # ------------------------------------------------------------------ API

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        """Publish the next version; blocks until every reader consumed
        the previous one (the reference's writer semaphore, as ack
        comparison)."""
        payload = pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > self.capacity:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}; construct with a larger capacity"
            )
        version = self._version()
        self._wait(
            lambda: self._closed()
            or all(self._ack(i) >= version for i in range(self.num_readers)),
            timeout, "write",
        )
        if self._closed():
            raise ChannelClosedError("channel is closed")
        self._mm[self._data_off : self._data_off + len(payload)] = payload
        # data first, then length, then the version stamp — each its own
        # 8-byte store: a reader that observes the new version is
        # guaranteed to see the new payload, and the `closed` word (owned
        # by close()) is never rewritten here
        _U64.pack_into(self._mm, _OFF_DATA_LEN, len(payload))
        _U64.pack_into(self._mm, _OFF_VERSION, version + 1)

    def read(self, reader_id: int = 0, timeout: Optional[float] = None) -> Any:
        """Consume the next version (each reader sees each version exactly
        once). Raises ChannelClosedError once the writer closed and every
        version was consumed."""
        if not 0 <= reader_id < self.num_readers:
            raise ValueError(f"reader_id {reader_id} out of range")
        seen = self._ack(reader_id)
        self._wait(
            lambda: self._version() > seen or self._closed(), timeout, "read"
        )
        version = self._version()
        if version <= seen:  # closed with nothing new
            raise ChannelClosedError("channel is closed")
        data_len = _U64.unpack_from(self._mm, _OFF_DATA_LEN)[0]
        value = pickle.loads(self._mm[self._data_off : self._data_off + data_len])
        self._set_ack(reader_id, version)
        return value

    def reader(self, reader_id: int) -> "ShmChannelReader":
        return ShmChannelReader(self, reader_id)

    def close(self) -> None:
        # single 8-byte store into the word only close() owns — safe
        # against a concurrent write() stamping version/data_len
        _U64.pack_into(self._mm, _OFF_CLOSED, 1)

    def release(self) -> None:
        """Drop this endpoint's liveness lease (close its fd). Called by
        unlink()/GC; safe to call twice."""
        fd, self._fd = self._fd, -1
        if fd >= 0:
            try:
                os.close(fd)
            except OSError:
                pass

    def unlink(self) -> None:
        """Remove the backing file (creator only, after all ends closed)."""
        self.release()
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __del__(self):
        try:
            self.release()
        except Exception:
            pass

    def __reduce__(self):
        return (
            _reopen_channel, (self.path, self.capacity, self.num_readers)
        )


def _reopen_channel(path: str, capacity: int, num_readers: int) -> ShmChannel:
    return ShmChannel(
        capacity=capacity, num_readers=num_readers, path=path, _create=False
    )


class ShmChannelReader:
    """A reader endpoint bound to one ack slot; picklable like the
    channel itself."""

    def __init__(self, channel: ShmChannel, reader_id: int):
        self.channel = channel
        self.reader_id = reader_id

    def read(self, timeout: Optional[float] = None) -> Any:
        return self.channel.read(self.reader_id, timeout)
