"""Mutable-object channels: versioned single-slot buffers with
reader/writer synchronization.

Reference parity: experimental mutable objects + shared-memory channels
(/root/reference/src/ray/core_worker/experimental_mutable_object_manager.h:44
— writable, version-stamped buffers gated by reader/writer semaphores —
and python/ray/experimental/channel/shared_memory_channel.py:151). They
are the zero-copy substrate under Compiled Graphs.

TPU inversion: actors in one runtime share an address space, so the
channel is a versioned slot + condition variable — literal zero-copy
(the reader gets the writer's object reference, no serialization at
all), and device arrays pass as HBM handles. The semantics match the
reference exactly: a writer blocks until every declared reader consumed
the previous version; each reader sees each version exactly once.
"""

from __future__ import annotations

import threading
from typing import Any, Optional


class ChannelClosedError(RuntimeError):
    pass


class _Sentinel:
    def __repr__(self):
        return "<channel-closed>"


_CLOSED = _Sentinel()


class Channel:
    """Single-slot, version-stamped, multi-reader channel."""

    def __init__(self, num_readers: int = 1):
        if num_readers < 1:
            raise ValueError("num_readers must be >= 1")
        self.num_readers = num_readers
        self._cond = threading.Condition()
        self._value: Any = None
        self._version = 0          # bumped on every write
        self._reads_left = 0       # readers yet to consume current version
        self._closed = False

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        """Publish the next version. Blocks until the previous version has
        been consumed by all readers (back-pressure, like the reference's
        writer semaphore)."""
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._reads_left == 0 or self._closed, timeout
            ):
                raise TimeoutError("channel write timed out (readers lagging)")
            if self._closed:
                raise ChannelClosedError("channel is closed")
            self._value = value
            self._version += 1
            self._reads_left = self.num_readers
            self._cond.notify_all()

    def read(self, last_version: int = -1, timeout: Optional[float] = None):
        """Consume the next version after `last_version`. Returns
        (value, version). Each reader must track its own cursor (a
        ChannelReader does this for you)."""
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._version > last_version and self._reads_left > 0
                or self._closed,
                timeout,
            ):
                raise TimeoutError("channel read timed out (no new version)")
            if self._closed and self._version <= last_version:
                raise ChannelClosedError("channel is closed")
            value, version = self._value, self._version
            self._reads_left -= 1
            if self._reads_left == 0:
                self._value = None  # release for GC; slot is consumable again
                self._cond.notify_all()
            return value, version

    def close(self) -> None:
        """Unblock everyone; further reads/writes raise ChannelClosedError."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed


class ChannelReader:
    """Cursor-tracking reader handle (one per consumer)."""

    def __init__(self, channel: Channel):
        self._channel = channel
        # start at the channel's current version: attach readers BEFORE the
        # first write (the DAG compiler does) or they miss in-flight values
        self._cursor = channel._version

    def read(self, timeout: Optional[float] = None) -> Any:
        value, version = self._channel.read(self._cursor, timeout)
        self._cursor = version
        return value
