"""ray_tpu.workflow — durable DAG execution (Ray Workflow equivalent).

Reference parity: python/ray/workflow — workflow_executor.py + storage-
backed step results (workflow_storage.py), resume-from-storage semantics.

Steps form a DAG via .step(...) binding; run() executes steps as runtime
tasks, persisting each result under storage/<workflow_id>/<step_id>.pkl.
Step ids are content-addressed (function name + argument structure), so
re-running the same driver code after a crash skips every step whose
result is already on disk — exactly-once-ish without a database.

Per-step robustness: `@workflow.step(max_retries=3)` re-runs a step that
raised (any exception) up to N times before the failure propagates, and
`@workflow.step(timeout_s=30)` bounds how long run() waits for the
step's result — a hung step surfaces WorkflowStepTimeout instead of
wedging the whole workflow. Both also available per-call through
`fn.options(...)`. Retry/timeout settings are not part of the step id,
so tuning them never invalidates persisted results.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import api


class WorkflowStepTimeout(TimeoutError):
    """A step exceeded its timeout_s budget; its result never arrived."""


@dataclasses.dataclass(frozen=True)
class StepNode:
    fn: Callable
    args: Tuple[Any, ...]
    kwargs: Tuple[Tuple[str, Any], ...]
    name: str
    # robustness knobs — deliberately NOT hashed into step_id, so tuning
    # them on a resumed run still reuses persisted results
    max_retries: int = 0
    timeout_s: Optional[float] = None

    @property
    def step_id(self) -> str:
        h = hashlib.sha1()
        h.update(self.name.encode())
        for a in self.args:
            h.update(
                a.step_id.encode() if isinstance(a, StepNode) else _digest(a)
            )
        for k, v in self.kwargs:
            h.update(k.encode())
            h.update(v.step_id.encode() if isinstance(v, StepNode) else _digest(v))
        return f"{self.name}-{h.hexdigest()[:12]}"


def _digest(value: Any) -> bytes:
    try:
        return hashlib.sha1(pickle.dumps(value)).digest()
    except Exception:
        return repr(value).encode()


class _StepFunction:
    def __init__(self, fn: Callable, name: Optional[str] = None,
                 max_retries: int = 0, timeout_s: Optional[float] = None):
        self._fn = fn
        self._name = name or fn.__name__
        self._max_retries = max_retries
        self._timeout_s = timeout_s

    def options(self, *, max_retries: Optional[int] = None,
                timeout_s: Optional[float] = None) -> "_StepFunction":
        """Per-call override of the step's retry/timeout settings."""
        return _StepFunction(
            self._fn, self._name,
            self._max_retries if max_retries is None else max_retries,
            self._timeout_s if timeout_s is None else timeout_s,
        )

    def step(self, *args, **kwargs) -> StepNode:
        return StepNode(
            self._fn, args, tuple(sorted(kwargs.items())), self._name,
            max_retries=self._max_retries, timeout_s=self._timeout_s,
        )

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn: Optional[Callable] = None, *, name: Optional[str] = None,
         max_retries: int = 0, timeout_s: Optional[float] = None):
    """@workflow.step decorator; build nodes with fn.step(...)."""
    if fn is None:
        return lambda f: _StepFunction(f, name, max_retries, timeout_s)
    return _StepFunction(fn, name, max_retries, timeout_s)


# ------------------------------------------------------------------ execution


class _Storage:
    def __init__(self, root: str, workflow_id: str):
        self.dir = os.path.join(os.fspath(root), workflow_id)
        os.makedirs(self.dir, exist_ok=True)

    def path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"{step_id}.pkl")

    def has(self, step_id: str) -> bool:
        return os.path.exists(self.path(step_id))

    def load(self, step_id: str) -> Any:
        with open(self.path(step_id), "rb") as f:
            return pickle.load(f)

    def save(self, step_id: str, value: Any) -> None:
        tmp = self.path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(value, f)
        os.replace(tmp, self.path(step_id))

    def completed_steps(self) -> List[str]:
        return sorted(
            f[:-4] for f in os.listdir(self.dir) if f.endswith(".pkl")
        )


def run(
    node: StepNode,
    *,
    storage: str,
    workflow_id: str = "default",
) -> Any:
    """Execute the DAG rooted at `node`; persisted steps are not re-run."""
    store = _Storage(storage, workflow_id)
    memo: Dict[str, Any] = {}  # step_id -> ObjectRef or loaded value

    def _persist_and_run(fn, step_id, store_dir, *resolved_args, **resolved_kwargs):
        result = fn(*resolved_args, **resolved_kwargs)
        s = _Storage(os.path.dirname(store_dir), os.path.basename(store_dir))
        s.save(step_id, result)
        return result

    run_step = api.remote(_persist_and_run)

    def submit(n: StepNode):
        sid = n.step_id
        if sid in memo:
            return memo[sid]
        if store.has(sid):
            memo[sid] = store.load(sid)
            return memo[sid]
        resolved_args = [submit(a) if isinstance(a, StepNode) else a for a in n.args]
        resolved_kwargs = {
            k: (submit(v) if isinstance(v, StepNode) else v) for k, v in n.kwargs
        }
        # args that are refs are resolved by the runtime before fn runs
        task = run_step
        if n.max_retries:
            task = run_step.options(
                max_retries=n.max_retries, retry_exceptions=True
            )
        ref = task.remote(n.fn, sid, store.dir, *resolved_args, **resolved_kwargs)
        if n.timeout_s is not None:
            # bound the wait HERE: downstream steps must never bind to a
            # ref that may hang forever
            from ..core.exceptions import GetTimeoutError

            try:
                value = api.get(ref, timeout=n.timeout_s)
            except GetTimeoutError:
                raise WorkflowStepTimeout(
                    f"step {sid} did not finish within {n.timeout_s}s"
                ) from None
            memo[sid] = value
            return value
        memo[sid] = ref
        return ref

    out = submit(node)
    return api.get(out) if not _is_plain(out) else out


def _is_plain(value: Any) -> bool:
    from ..core.runtime import ObjectRef

    return not isinstance(value, ObjectRef)


def list_completed(storage: str, workflow_id: str = "default") -> List[str]:
    return _Storage(storage, workflow_id).completed_steps()
