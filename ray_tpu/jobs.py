"""Job submission: supervised driver subprocesses with captured logs.

Reference parity: dashboard/modules/job/job_manager.py:60 JobManager +
JobSupervisor actor (job_supervisor.py:55) behind `ray job submit`. Each
job is an entrypoint command run as a subprocess with PYTHONPATH set so
`import ray_tpu` works, stdout/stderr tee'd to a per-job log file, status
tracked by a watcher thread (PENDING → RUNNING → SUCCEEDED/FAILED/STOPPED).
"""

from __future__ import annotations

import dataclasses
import enum
import os
import shlex
import signal
import subprocess
import threading
import time
import uuid
from typing import Dict, List, Optional


class JobStatus(enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@dataclasses.dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: JobStatus
    log_path: str
    submitted_at: float
    finished_at: Optional[float] = None
    returncode: Optional[int] = None
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)


class JobManager:
    def __init__(self, log_dir: Optional[str] = None):
        self.log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_jobs"
        )
        os.makedirs(self.log_dir, exist_ok=True)
        self._jobs: Dict[str, JobInfo] = {}
        self._procs: Dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def submit(
        self,
        entrypoint: str,
        *,
        job_id: Optional[str] = None,
        env_vars: Optional[Dict[str, str]] = None,
        working_dir: Optional[str] = None,
        metadata: Optional[Dict[str, str]] = None,
    ) -> str:
        # Validate BEFORE registering: a late Popen TypeError must not
        # leave a phantom PENDING job in the table (REST payloads can
        # carry arbitrary JSON types).
        if not isinstance(entrypoint, str) or not entrypoint.strip():
            raise TypeError("entrypoint must be a non-empty string")
        if env_vars is not None and not all(
            isinstance(k, str) and isinstance(v, str)
            for k, v in env_vars.items()
        ):
            raise TypeError("env_vars must map str -> str")
        if job_id is not None and not isinstance(job_id, str):
            raise TypeError("job_id must be a string")
        job_id = job_id or f"raytpu-job-{uuid.uuid4().hex[:8]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id!r} already exists")
        log_path = os.path.join(self.log_dir, f"{job_id}.log")
        env = dict(os.environ)
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        env["RAY_TPU_JOB_ID"] = job_id
        env.update(env_vars or {})
        info = JobInfo(
            job_id=job_id,
            entrypoint=entrypoint,
            status=JobStatus.PENDING,
            log_path=log_path,
            submitted_at=time.time(),
            metadata=dict(metadata or {}),
        )
        with self._lock:
            self._jobs[job_id] = info
        log_file = open(log_path, "wb")
        try:
            proc = subprocess.Popen(
                shlex.split(entrypoint),
                stdout=log_file,
                stderr=subprocess.STDOUT,
                env=env,
                cwd=working_dir,
                start_new_session=True,  # own process group: stop kills children
            )
        except Exception as e:  # noqa: BLE001 - ANY launch failure (OSError,
            # shlex ValueError, bad working_dir TypeError, ...) must land the
            # registered job in FAILED — never a phantom PENDING entry
            log_file.write(f"failed to launch: {e!r}\n".encode())
            log_file.close()
            info.status = JobStatus.FAILED
            info.finished_at = time.time()
            return job_id
        info.status = JobStatus.RUNNING
        with self._lock:
            self._procs[job_id] = proc
        threading.Thread(
            target=self._watch, args=(job_id, proc, log_file), daemon=True,
            name=f"job-watch-{job_id}",
        ).start()
        return job_id

    def _watch(self, job_id: str, proc: subprocess.Popen, log_file) -> None:
        returncode = proc.wait()
        log_file.close()
        with self._lock:
            info = self._jobs[job_id]
            info.returncode = returncode
            info.finished_at = time.time()
            if info.status != JobStatus.STOPPED:
                info.status = (
                    JobStatus.SUCCEEDED if returncode == 0 else JobStatus.FAILED
                )
            self._procs.pop(job_id, None)

    def status(self, job_id: str) -> JobStatus:
        return self._get(job_id).status

    def info(self, job_id: str) -> JobInfo:
        return self._get(job_id)

    def logs(self, job_id: str) -> str:
        info = self._get(job_id)
        try:
            with open(info.log_path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def list(self) -> List[JobInfo]:
        with self._lock:
            return sorted(self._jobs.values(), key=lambda j: j.submitted_at)

    def stop(self, job_id: str, timeout: float = 5.0) -> bool:
        with self._lock:
            proc = self._procs.get(job_id)
            info = self._jobs.get(job_id)
        if proc is None or info is None:
            return False
        info.status = JobStatus.STOPPED
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            return True
        try:
            proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            os.killpg(proc.pid, signal.SIGKILL)
        return True

    def wait(self, job_id: str, timeout: Optional[float] = None) -> JobStatus:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            status = self.status(job_id)
            if status in (JobStatus.SUCCEEDED, JobStatus.FAILED, JobStatus.STOPPED):
                return status
            if deadline is not None and time.monotonic() > deadline:
                return status
            time.sleep(0.05)

    def _get(self, job_id: str) -> JobInfo:
        with self._lock:
            if job_id not in self._jobs:
                raise KeyError(f"no job {job_id!r}")
            return self._jobs[job_id]


_default_manager: Optional[JobManager] = None
_mgr_lock = threading.Lock()


def default_job_manager() -> JobManager:
    global _default_manager
    with _mgr_lock:
        if _default_manager is None:
            _default_manager = JobManager()
        return _default_manager
