"""Cluster launcher: `ray_tpu up / down <cluster.yaml>`.

Reference parity: `ray up` over the autoscaler's NodeProvider zoo
(/root/reference/python/ray/autoscaler/_private/commands.py + 42k LoC
of cloud providers). TPU inversion: a TPU pod's hosts are a KNOWN,
FIXED list (the pod slice), not an elastic cloud fleet — so the
launcher takes an explicit host list and two providers cover reality:

- ``local``: every node is a subprocess on this machine (the
  development topology; also what cluster_utils uses).
- ``ssh``: one `python -m ray_tpu start` per remote host over plain
  ssh, the way TPU pods are actually driven (the reference's on-prem
  "local" provider does the same). Needs network reachability —
  unit-tested for command construction here (zero-egress image),
  exercised for real on a pod.

Config (YAML or JSON)::

    head:
      port: 6379
      num_cpus: 8
    workers:
      - host: localhost        # or 10.0.0.2 for ssh
        num_cpus: 8
        resources: {"TPU": 4}
    provider: local            # or ssh
    token: my-cluster-secret   # required off-localhost
    ssh_user: me               # ssh provider only
"""

from __future__ import annotations

import json
import os
import shlex
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    try:
        import yaml

        return yaml.safe_load(text)
    except ImportError:  # pragma: no cover - yaml is in this image
        return json.loads(text)


def _start_cmd(*, address: Optional[str], port: Optional[int],
               num_cpus: Optional[int], resources: Optional[Dict[str, float]],
               token: Optional[str], no_tpu: bool,
               tag: Optional[str] = None) -> List[str]:
    cmd = [sys.executable, "-m", "ray_tpu"]
    if no_tpu:
        cmd.append("--no-tpu")
    cmd.append("start")
    if tag:
        # identification only: lets `down` target THIS cluster's agents
        # by cmdline pattern without touching co-tenant clusters
        cmd += ["--launch-tag", tag]
    if address:
        cmd += ["--address", address]
    else:
        cmd += ["--head", "--port", str(port or 6379)]
    if num_cpus is not None:
        cmd += ["--num-cpus", str(num_cpus)]
    if resources:
        cmd += ["--resources", json.dumps(resources)]
    if token:
        cmd += ["--token", token]
    return cmd


class LocalLaunchProvider:
    """Every node is a subprocess of this machine (reference: the
    on-prem/local node provider)."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.procs: List[subprocess.Popen] = []
        self.log_paths: List[str] = []

    def launch(self, cmd: List[str], host: str) -> Dict[str, Any]:
        fd, log_path = tempfile.mkstemp(prefix="ray_tpu_up_", suffix=".log")
        log = os.fdopen(fd, "w")
        proc = subprocess.Popen(
            cmd, stdout=log, stderr=subprocess.STDOUT, text=True,
            env=dict(os.environ),
        )
        log.close()
        self.procs.append(proc)
        self.log_paths.append(log_path)
        return {"host": host, "pid": proc.pid, "log": log_path}

    def terminate_all(self) -> None:
        for proc in self.procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()


class SSHLaunchProvider:
    """One `ray_tpu start` per remote host over ssh (reference: the
    command_runner SSH path behind every cloud provider). The remote
    host must have the same ray_tpu version importable (protocol gate
    enforces it) and be reachable — on a TPU pod that is the slice's
    internal network."""

    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.user = config.get("ssh_user")
        self.ssh_opts = config.get("ssh_opts", ["-o", "StrictHostKeyChecking=no"])
        # injectable transport: tests drive the full up→join→down
        # lifecycle through a loopback/recording fake instead of a real
        # ssh binary; pods use the default
        self.ssh_bin = config.get("ssh_bin", "ssh")
        self.procs: List[subprocess.Popen] = []

    def ssh_command(self, host: str, cmd: List[str]) -> List[str]:
        target = f"{self.user}@{host}" if self.user else host
        remote = " ".join(shlex.quote(part) for part in cmd)
        # nohup: the agent must outlive the ssh session
        return [self.ssh_bin, *self.ssh_opts, target,
                f"nohup {remote} >/tmp/ray_tpu_agent.log 2>&1 & echo $!"]

    def launch(self, cmd: List[str], host: str) -> Dict[str, Any]:
        full = self.ssh_command(host, cmd)
        proc = subprocess.Popen(
            full, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True
        )
        self.procs.append(proc)
        return {"host": host, "ssh_pid": proc.pid}

    def terminate_all(self) -> None:
        # best effort, HEAD INCLUDED: kill THIS cluster's agents by the
        # launch tag in their cmdline — co-tenant clusters on the same
        # host (other tags) are untouched
        tag = self.config.get("_launch_tag", "")
        pattern = (
            f"ray_tpu.*--launch-tag {tag}" if tag else "ray_tpu.*start"
        )
        hosts = [self.config.get("head", {}).get("host", "localhost")] + [
            w.get("host", "localhost")
            for w in self.config.get("workers", [])
        ]
        for host in hosts:
            target = f"{self.user}@{host}" if self.user else host
            try:
                subprocess.run(
                    [self.ssh_bin, *self.ssh_opts, target,
                     f"pkill -f {shlex.quote(pattern)} || true"],
                    capture_output=True, timeout=30,
                )
            except Exception:
                pass


_PROVIDERS = {"local": LocalLaunchProvider, "ssh": SSHLaunchProvider}


class ClusterLauncher:
    """`ray up` equivalent: bring up the head + every configured worker,
    wait for them to register, report the join line."""

    def __init__(self, config: Dict[str, Any], *, no_tpu: bool = False):
        self.config = config
        provider_name = config.get("provider", "local")
        if provider_name not in _PROVIDERS:
            raise ValueError(
                f"unknown provider {provider_name!r}; known: {sorted(_PROVIDERS)}"
            )
        self.provider = _PROVIDERS[provider_name](config)
        self.no_tpu = no_tpu
        self.address: Optional[str] = None

    def up(self, wait_s: float = 60.0) -> Dict[str, Any]:
        import uuid as _uuid

        head = self.config.get("head", {})
        token = self.config.get("token")
        tag = self.config.setdefault("_launch_tag", _uuid.uuid4().hex[:12])
        port = int(head.get("port", 6379))
        head_host = head.get("host", "localhost")
        head_cmd = _start_cmd(
            address=None, port=port, num_cpus=head.get("num_cpus"),
            resources=head.get("resources"), token=token, no_tpu=self.no_tpu,
            tag=tag,
        )
        head_info = self.provider.launch(head_cmd, head_host)
        connect_host = "127.0.0.1" if head_host == "localhost" else head_host
        self.address = f"{connect_host}:{port}"
        workers = self.config.get("workers", [])
        launched = [head_info]
        # give the head a beat so workers don't race its GCS socket
        time.sleep(1.0)
        try:
            for w in workers:
                cmd = _start_cmd(
                    address=self.address, port=None,
                    num_cpus=w.get("num_cpus"),
                    resources=w.get("resources"), token=token,
                    no_tpu=self.no_tpu, tag=tag,
                )
                launched.append(
                    self.provider.launch(cmd, w.get("host", "localhost"))
                )
            self._wait_for_nodes(1 + len(workers), wait_s)
        except BaseException:
            # a half-up cluster must not orphan detached agents the user
            # can never `down` (no state file was written yet)
            self.provider.terminate_all()
            raise
        return {"address": self.address, "nodes": launched}

    def _wait_for_nodes(self, count: int, wait_s: float) -> None:
        from .core.gcs_service import GcsClient

        deadline = time.monotonic() + wait_s
        client = GcsClient(self.address, token=self.config.get("token"))
        try:
            while time.monotonic() < deadline:
                try:
                    view = client.cluster_view()
                    if len(view["nodes"]) >= count:
                        return
                except Exception:
                    pass
                time.sleep(0.5)
            raise TimeoutError(
                f"cluster did not reach {count} nodes within {wait_s}s"
            )
        finally:
            client.close()

    def down(self) -> None:
        """`ray down`: terminate everything this launcher started."""
        self.provider.terminate_all()


# ------------------------------------------------------------ CLI state file
# `up` returns after provisioning (the nodes are detached); `down` in a
# fresh process needs to find them — the reference keeps the same kind
# of cluster state under ~/.ray (commands.py). One JSON file per config.


def _state_path(config_path: str) -> str:
    import hashlib

    digest = hashlib.sha256(
        os.path.abspath(config_path).encode()
    ).hexdigest()[:16]
    state_dir = os.path.join(os.path.expanduser("~"), ".ray_tpu")
    os.makedirs(state_dir, exist_ok=True)
    return os.path.join(state_dir, f"launch_{digest}.json")


def up_from_cli(config_path: str, *, no_tpu: bool = False) -> Dict[str, Any]:
    config = load_config(config_path)
    launcher = ClusterLauncher(config, no_tpu=no_tpu)
    info = launcher.up()
    state = {
        "address": info["address"],
        "provider": config.get("provider", "local"),
        "pids": [n.get("pid") for n in info["nodes"] if n.get("pid")],
        "launch_tag": config.get("_launch_tag"),
        "config_path": os.path.abspath(config_path),
    }
    with open(_state_path(config_path), "w") as f:
        json.dump(state, f)
    return info


def down_from_cli(config_path: str) -> int:
    """Terminate a cluster started by up_from_cli; returns nodes stopped."""
    import signal

    path = _state_path(config_path)
    if not os.path.exists(path):
        raise FileNotFoundError(
            f"no launch state for {config_path} (was `up` run here?)"
        )
    with open(path) as f:
        state = json.load(f)
    stopped = 0
    if state["provider"] == "local":
        for pid in state.get("pids", []):
            # pids recycle across reboots: verify the target still IS a
            # ray_tpu node before signaling it
            try:
                with open(f"/proc/{pid}/cmdline", "rb") as f:
                    cmdline = f.read().decode(errors="replace")
            except OSError:
                continue
            if "ray_tpu" not in cmdline:
                continue
            tag = state.get("launch_tag")
            if tag and tag not in cmdline:
                continue
            try:
                os.kill(pid, signal.SIGTERM)
                stopped += 1
            except ProcessLookupError:
                pass
    else:
        config = load_config(state["config_path"])
        config["_launch_tag"] = state.get("launch_tag", "")
        SSHLaunchProvider(config).terminate_all()
        stopped = len(config.get("workers", [])) + 1
    os.unlink(path)
    return stopped
