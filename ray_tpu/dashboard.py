"""Dashboard: a single-page cluster view over the state API + metrics.

Reference parity: the aiohttp dashboard (/root/reference/python/ray/
dashboard/head.py — jobs/state/metrics modules, 32k LoC of React). TPU
inversion: the runtime is in-process, so the dashboard is a thin HTTP
server over the EXISTING state API (util/state.py) and metrics registry —
JSON endpoints plus one self-refreshing HTML page; no build step, no
node agents, nothing the control plane doesn't already know.

    from ray_tpu.dashboard import start_dashboard
    url = start_dashboard(port=8265)   # -> http://127.0.0.1:8265
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body{font-family:system-ui,sans-serif;margin:2em;background:#fafafa;color:#222}
h1{font-size:1.3em} h2{font-size:1.05em;margin-top:1.4em}
table{border-collapse:collapse;min-width:30em}
td,th{border:1px solid #ccc;padding:.25em .6em;font-size:.85em;text-align:left}
th{background:#eee} code{background:#eee;padding:0 .3em}
#err{color:#b00}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="err"></div>
<h2>Cluster</h2><table id="summary"></table>
<h2>Autoscaler</h2><table id="autoscaler"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Node telemetry</h2><table id="telemetry"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Traces</h2><table id="traces"></table>
<h2>Profiles</h2><table id="profiles"></table>
<h2>Events</h2><table id="events"></table>
<h2>Logs (per node, last lines)</h2><pre id="logs" style="font-size:.75em;background:#eee;padding:.6em;max-height:22em;overflow:auto"></pre>
<script>
function fill(id, rows) {
  const t = document.getElementById(id);
  if (!rows.length) { t.innerHTML = "<tr><td>(none)</td></tr>"; return; }
  const cols = Object.keys(rows[0]);
  t.innerHTML = "<tr>" + cols.map(c => `<th>${c}</th>`).join("") + "</tr>" +
    rows.map(r => "<tr>" + cols.map(c =>
      `<td>${typeof r[c] === "object" ? JSON.stringify(r[c]) : r[c]}</td>`
    ).join("") + "</tr>").join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    fill("summary", [s]);
    const sc = await (await fetch("/api/autoscaler")).json();
    fill("autoscaler", Object.keys(sc).length ? [sc] : []);
    fill("nodes", await (await fetch("/api/nodes")).json());
    const ns = await (await fetch("/api/node_stats")).json();
    fill("telemetry", Object.entries(ns).map(([node, t]) => ({
      node: node.slice(0, 12),
      cpu_pct: t.cpu_percent,
      rss_mb: (t.rss_bytes / 1048576).toFixed(1),
      store_bytes: (t.object_store || {}).host_bytes,
      objects: (t.object_store || {}).num_objects,
      pool: `${(t.worker_pool || {}).busy || 0} busy / ${(t.worker_pool || {}).idle || 0} idle`,
      queues: t.task_queues,
      tpu: (t.tpu || []).length,
    })));
    fill("actors", await (await fetch("/api/actors")).json());
    const tasks = await (await fetch("/api/tasks")).json();
    fill("tasks", tasks.slice(-20).reverse());
    fill("jobs", await (await fetch("/api/jobs")).json());
    const tr = await (await fetch("/api/traces")).json();
    fill("traces", tr.slice(-15).reverse().map(t => ({
      trace: `<a href="/trace?id=${t.trace_id}">${t.trace_id.slice(0,12)}</a>`,
      root: t.root, spans: t.spans, errors: t.errors,
      duration_s: t.duration_s.toFixed(4),
    })));
    const pr = await (await fetch("/api/profiles")).json();
    fill("profiles", pr.slice(-10).reverse().map(p => ({
      profile: p.profile_id, nodes: Object.keys(p.nodes || {}).length,
      duration_s: p.duration_s, bytes: p.total_bytes,
    })));
    const ev = await (await fetch("/api/events")).json();
    fill("events", ev.slice(-15).reverse());
    const logs = await (await fetch("/api/logs")).json();
    document.getElementById("logs").textContent = Object.entries(logs)
      .map(([n, lines]) => `=== ${n} ===\n` + lines.slice(-12).join("\n"))
      .join("\n\n");
    document.getElementById("err").textContent = "";
  } catch (e) { document.getElementById("err").textContent = "refresh failed: " + e; }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


_TRACE_PAGE = """<!doctype html>
<html><head><title>ray_tpu trace</title><style>
body{font-family:system-ui,sans-serif;margin:2em;background:#fafafa;color:#222}
h1{font-size:1.2em} .row{position:relative;height:1.45em;margin:1px 0}
.bar{position:absolute;height:1.25em;background:#7aa7d6;border-radius:2px;
     font-size:.72em;padding:0 .3em;white-space:nowrap;overflow:visible;
     color:#102a43;line-height:1.7}
.bar.err{background:#d67a7a}
.lane{font-size:.72em;color:#666;position:absolute;left:0;width:11em;
      overflow:hidden;text-overflow:ellipsis}
#chart{position:relative;margin-left:11.5em}
#meta{font-size:.8em;color:#555;margin-bottom:1em}
</style></head><body>
<h1>trace waterfall</h1><div id="meta"></div>
<div style="position:relative"><div id="lanes"></div><div id="chart"></div></div>
<script>
const id = new URLSearchParams(location.search).get("id");
async function render() {
  const spans = await (await fetch("/api/trace?id=" + id)).json();
  if (!spans.length) { document.getElementById("meta").textContent =
      "no spans for trace " + id; return; }
  const t0 = Math.min(...spans.map(s => s.start_ts));
  const t1 = Math.max(...spans.map(s => s.end_ts || s.start_ts));
  const total = Math.max(t1 - t0, 1e-6);
  document.getElementById("meta").textContent =
    `trace ${id} — ${spans.length} spans, ${(total*1000).toFixed(2)} ms`;
  const chart = document.getElementById("chart");
  const lanes = document.getElementById("lanes");
  spans.sort((a, b) => a.start_ts - b.start_ts);
  spans.forEach((s, i) => {
    const left = 100 * (s.start_ts - t0) / total;
    const width = Math.max(100 * ((s.end_ts || s.start_ts) - s.start_ts) / total, 0.15);
    const row = document.createElement("div"); row.className = "row";
    const bar = document.createElement("div");
    bar.className = "bar" + (s.status !== "OK" ? " err" : "");
    bar.style.left = left + "%"; bar.style.width = width + "%";
    bar.textContent = `${s.name} (${((s.duration_s||0)*1000).toFixed(2)} ms)`;
    bar.title = JSON.stringify(s.attrs);
    row.appendChild(bar); chart.appendChild(row);
    const lane = document.createElement("div"); lane.className = "lane";
    lane.style.top = (i * 1.45 + 3.2) + "em"; lane.textContent = s.lane || "";
    lanes.appendChild(lane);
  });
}
render();
</script></body></html>"""


class _Handler(BaseHTTPRequestHandler):
    def log_message(self, *args):  # quiet
        pass

    def do_POST(self):  # noqa: N802 - http.server API
        """REST job submission (reference: dashboard job module behind
        `ray job submit`): POST /api/jobs {"entrypoint": "...", ...}."""
        try:
            if self.path != "/api/jobs":
                self._send(404, "not found", "text/plain")
                return
            # Require a JSON content type: cross-origin form POSTs (CSRF
            # "simple requests") cannot set it without a CORS preflight,
            # so a drive-by page cannot exec commands through this
            # endpoint (the real Ray dashboard's CVE-2023-48022 class).
            ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
            if ctype != "application/json":
                self._send(
                    415,
                    json.dumps({"error": "Content-Type must be application/json"}),
                    "application/json",
                )
                return
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            from .jobs import default_job_manager

            job_id = default_job_manager().submit(
                payload["entrypoint"],
                job_id=payload.get("job_id"),
                env_vars=payload.get("env_vars"),
                working_dir=payload.get("working_dir"),
                metadata=payload.get("metadata"),
            )
            self._send(200, json.dumps({"job_id": job_id}), "application/json")
        except Exception as e:  # noqa: BLE001 - handler must answer something
            self._send(400, json.dumps({"error": repr(e)}), "application/json")

    def do_GET(self):  # noqa: N802 - http.server API
        try:
            if self.path == "/" or self.path == "/index.html":
                self._send(200, _PAGE, "text/html")
                return
            if self.path.split("?", 1)[0] == "/trace":
                self._send(200, _TRACE_PAGE, "text/html")
                return
            if self.path.startswith("/api/"):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                if parsed.path == "/api/profile_artifact":
                    # binary download of one captured artifact
                    from .util import state

                    data = state.profile_artifact(
                        query["id"], query["node"], query["name"]
                    )
                    self._send_bytes(200, data, "application/octet-stream")
                    return
                self._send(200, json.dumps(self._api(parsed.path[5:], query)),
                           "application/json")
                return
            if self.path == "/metrics/cluster":
                from .util.metrics import cluster_prometheus_text

                self._send(200, cluster_prometheus_text(), "text/plain")
                return
            if self.path == "/metrics":
                from .util.metrics import registry

                self._send(200, registry().prometheus_text(), "text/plain")
                return
            self._send(404, "not found", "text/plain")
        except Exception as e:  # noqa: BLE001 - handler must answer something
            self._send(500, json.dumps({"error": repr(e)}), "application/json")

    def _api(self, name: str, query: Optional[dict] = None):
        from .util import state

        query = query or {}
        if name == "summary":
            return state.summary()
        if name == "nodes":
            return state.list_nodes()
        if name == "node_stats":
            return state.node_stats()
        if name == "cluster_metrics":
            return state.cluster_metrics(raw=True)
        if name == "autoscaler":
            # capacity-plane status: managed nodes by type/class, pending
            # demand by origin, scale/replace/blocked counters
            return state.autoscaler_summary() or {}
        if name == "head":
            # head fault-tolerance health: epoch, WAL lag/size, snapshot
            # age, restore/reconcile provenance, buffered federation
            return state.head_summary() or {}
        if name == "status":
            return {"report": state.status_report()}
        if name == "actors":
            return state.list_actors()
        if name == "tasks":
            return state.list_tasks()
        if name == "objects":
            return state.list_objects()
        if name == "timeline":
            # trace_dump directly: chrome_tracing_dump is a deprecated
            # alias of it now (same payload, minus the warning)
            return json.loads(state.trace_dump())
        if name == "traces":
            return state.list_traces()
        if name == "trace":
            # per-trace waterfall data: spans stitched cluster-wide
            if "id" not in query:
                raise ValueError("trace endpoint needs ?id=<trace_id>")
            return state.get_trace(query["id"])
        if name == "trace_export":
            return json.loads(state.trace_dump(
                trace_id=query.get("id"),
                profile_id=query.get("profile_id"),
            ))
        if name == "profiles":
            # artifact bytes stay behind /api/profile_artifact; the list
            # is meta only (per-node status + artifact names/sizes)
            return state.list_profiles()
        if name == "profile":
            if "id" not in query:
                raise ValueError("profile endpoint needs ?id=<profile_id>")
            return state.get_profile(query["id"])
        if name == "events":
            # the merged cluster-wide flight-recorder tail (filterable
            # like `ray_tpu events`: ?kind=&node=&severity=&since=)
            return state.events(
                limit=int(query.get("limit", 200)),
                kind=query.get("kind"),
                node=query.get("node"),
                severity=query.get("severity"),
                since=float(query.get("since", 0.0)),
            )
        if name == "cluster_events":
            return state.cluster_events()
        if name == "requests":
            # request-forensics summaries (the on-call triage list:
            # ?tenant=&slow=1&limit=)
            return state.list_requests(
                tenant=query.get("tenant"),
                slow_only=query.get("slow", "0") in ("1", "true"),
                limit=int(query.get("limit", 200)),
            )
        if name == "request":
            # one request's cluster-wide phase timeline + the rendered
            # waterfall (the CLI's `ray_tpu request <id>` view)
            if "id" not in query:
                raise ValueError("request endpoint needs ?id=<request_id>")
            from .serve import reqlog

            marks = state.request_timeline(query["id"])
            return {
                "request_id": query["id"],
                "marks": marks,
                "decomposition": reqlog.decompose(marks),
                "waterfall": reqlog.render_waterfall(marks),
            }
        if name == "steps":
            # training-forensics sampled-step summaries (?run=&limit=),
            # or with ?run= plus ?waterfall=1 the run's rendered
            # per-rank waterfall + skew matrix (`ray_tpu steps <run>`)
            run = query.get("run")
            if run and query.get("waterfall", "0") in ("1", "true"):
                from .train import steplog

                summaries = state.step_timeline(run)
                return {
                    "run": run,
                    "steps": summaries,
                    "skew": steplog.skew_matrix(summaries),
                    "waterfall": steplog.render_waterfall(summaries),
                }
            return state.list_steps(
                run=run,
                limit=int(query.get("limit", 200)),
            )
        if name == "engines":
            # live engine introspection: lane table, page pool, prefix
            # cache chains, fair-queue depths (this process's engines)
            return state.engine_snapshot()
        if name == "goodput":
            # serve-side SLO attainment + any train goodput gauges land
            # in /metrics; this endpoint serves the serve ledger
            from .util.goodput import serve_slo_report

            return serve_slo_report()
        if name == "logs":
            # the UI shows ~12 lines/node; don't ship 200 per refresh
            return state.cluster_logs(tail=20)
        if name == "jobs":
            from .jobs import _default_manager

            if _default_manager is None:
                return []
            return [
                {
                    "job_id": j.job_id,
                    "status": j.status.value,
                    "entrypoint": j.entrypoint,
                    "submitted_at": j.submitted_at,
                    "returncode": j.returncode,
                }
                for j in _default_manager.list()
            ]
        raise ValueError(f"unknown endpoint {name!r}")

    def _send(self, code: int, body: str, ctype: str) -> None:
        self._send_bytes(code, body.encode(), ctype)

    def _send_bytes(self, code: int, data: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


_server: Optional[ThreadingHTTPServer] = None


def start_dashboard(port: int = 8265, host: str = "127.0.0.1") -> str:
    """Serve the dashboard for the current runtime; returns its URL.
    port=0 picks a free port."""
    global _server
    if _server is not None:
        return f"http://{_server.server_address[0]}:{_server.server_address[1]}"
    _server = ThreadingHTTPServer((host, port), _Handler)
    threading.Thread(
        target=_server.serve_forever, daemon=True, name="ray-tpu-dashboard"
    ).start()
    return f"http://{host}:{_server.server_address[1]}"


def stop_dashboard() -> None:
    global _server
    if _server is not None:
        _server.shutdown()
        _server = None
