"""Deployment API: @serve.deployment → Deployment → .bind() → Application.

Reference parity: python/ray/serve/api.py (deployment decorator), serve/
config.py (DeploymentConfig, AutoscalingConfig), deployment graph binding.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple


@dataclasses.dataclass
class AutoscalingConfig:
    """Scale on ongoing requests (reference serve/_private/autoscaling_state.py).

    With slo_driven=True the controller additionally reads the
    ServeSLOMonitor attainment ledger each pass: new SLO-violating
    windows (TTFT/queue p99 over objective) bump the target by one
    replica — beyond what the ongoing-count heuristic asks for — as long
    as there is real demand pressure (cfg.autoscale_pressure_floor), and
    scale-down stays damped through scale_down_delay_s and the graceful
    drain path. Thresholds live on cfg (autoscale_burn_windows,
    autoscale_pressure_floor) so operators tune them fleet-wide."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    interval_s: float = 0.5
    scale_down_delay_s: float = 2.0
    slo_driven: bool = False


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    # admission control: requests beyond (replicas x max_ongoing_requests)
    # + max_queued_requests are SHED at the router with a typed
    # BackPressureError (HTTP layers map it to 429 + Retry-After).
    # -1 = unlimited queueing (the pre-resilience behavior).
    max_queued_requests: int = -1
    # graceful scale-down/redeploy: a removed replica goes DRAINING (no
    # new requests routed) and gets this long to finish in-flight work
    # before the controller force-kills it. 0 = kill immediately.
    drain_timeout_s: float = 10.0
    autoscaling: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 1.0
    # probe budget for a RUNNING replica (reference
    # health_check_timeout_s); slow first-compile models need headroom
    health_check_timeout_s: float = 30.0
    # a replica whose __init__ is still running (e.g. compiling / loading
    # weights on the chip) is NOT unhealthy: give it this long before
    # health probes can prune it (readiness vs liveness)
    startup_grace_s: float = 180.0
    resources_per_replica: Optional[Dict[str, float]] = None
    max_restarts: int = 3


class Deployment:
    """A configured (but not yet deployed) class."""

    def __init__(self, cls: type, name: str, config: DeploymentConfig):
        self.cls = cls
        self.name = name
        self.config = config

    def options(self, **overrides) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        name = overrides.pop("name", self.name)
        for k, v in overrides.items():
            if not hasattr(cfg, k):
                raise TypeError(f"unknown deployment option {k!r}")
            setattr(cfg, k, v)
        return Deployment(self.cls, name, cfg)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)


@dataclasses.dataclass
class Application:
    """A deployment bound to its constructor args (a 1-node graph; handle
    args may themselves be Applications → composition)."""

    deployment: Deployment
    init_args: Tuple[Any, ...]
    init_kwargs: Dict[str, Any]


def deployment(
    cls: Optional[type] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_ongoing_requests: int = 8,
    max_queued_requests: int = -1,
    drain_timeout_s: float = 10.0,
    autoscaling: Optional[AutoscalingConfig] = None,
    resources_per_replica: Optional[Dict[str, float]] = None,
    max_restarts: int = 3,
) -> Any:
    """@serve.deployment decorator (reference serve/api.py:deployment).

    max_queued_requests bounds router-side queueing (overflow sheds with
    BackPressureError → HTTP 429); drain_timeout_s is the grace a
    replica gets to finish in-flight requests on scale-down/redeploy.
    """

    def wrap(c: type) -> Deployment:
        config = DeploymentConfig(
            num_replicas=num_replicas,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            drain_timeout_s=drain_timeout_s,
            autoscaling=autoscaling,
            resources_per_replica=resources_per_replica,
            max_restarts=max_restarts,
        )
        return Deployment(c, name or c.__name__, config)

    return wrap(cls) if cls is not None else wrap
