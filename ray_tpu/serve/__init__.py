"""ray_tpu.serve — model serving (Ray Serve equivalent).

Controller/replica FSM with restarts, pow-2-choices routing, ongoing-request
autoscaling, stdlib HTTP ingress, and a TPU continuous-batching LLM engine
(static slot grid over a dense KV cache — compiles once, batches forever).
"""

from ..core.exceptions import (  # noqa: F401 - serve-facing typed errors
    BackPressureError,
    DeploymentUnavailableError,
    ReplicaDrainingError,
    RequestTimeoutError,
)
from .api import (  # noqa: F401
    delete,
    get_handle,
    run,
    shutdown,
    start_http,
    status,
)
from .context import (  # noqa: F401
    get_request_deadline,
    get_request_priority,
    get_request_tenant,
    remaining_s,
)
from .deployment import (  # noqa: F401
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentConfig,
    deployment,
)
from .multiplex import get_multiplexed_model_id, multiplexed  # noqa: F401
from .router import DeploymentHandle  # noqa: F401
from .tenancy import TenantSpec, set_tenant  # noqa: F401
