"""serve public API: run/get_handle/status/shutdown + HTTP ingress.

Reference parity: serve.run (serve/api.py:591), ProxyActor HTTP ingress
(serve/_private/proxy.py:1137). The proxy here is a threaded HTTP server
routing JSON POSTs to deployment handles — per-node uvicorn/ASGI machinery
is intentionally replaced by stdlib (no external deps in this image).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from .. import api as _core_api
from .controller import ServeController
from .deployment import Application
from .router import DeploymentHandle

_controller: Optional[ServeController] = None
_proxy: Optional["_HttpProxy"] = None
_lock = threading.Lock()


def _get_controller() -> ServeController:
    global _controller
    with _lock:
        if _controller is None:
            _core_api.init()  # make sure the runtime exists
            _controller = ServeController()
        return _controller


def run(app: Application, *, name: Optional[str] = None) -> DeploymentHandle:
    """Deploy (or redeploy) an application; returns its handle."""
    if name is not None:
        app = Application(app.deployment.options(name=name), app.init_args, app.init_kwargs)
    return _get_controller().deploy(app)


def get_handle(name: str) -> DeploymentHandle:
    return _get_controller().get_handle(name)


def status() -> Dict[str, Dict[str, Any]]:
    return _get_controller().status()


def delete(name: str) -> None:
    _get_controller().delete(name)


def shutdown() -> None:
    global _controller, _proxy
    with _lock:
        if _proxy is not None:
            _proxy.stop()
            _proxy = None
        if _controller is not None:
            _controller.shutdown()
            _controller = None


# ------------------------------------------------------------------ HTTP proxy


class EgresslessHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer for zero-egress hosts: the default
    server_bind calls socket.getfqdn() — a reverse-DNS lookup that
    hangs without egress. Shared by the serve proxy and the OpenAI
    frontend."""

    daemon_threads = True

    def server_bind(self):
        import socketserver

        socketserver.TCPServer.server_bind(self)
        self.server_name = self.server_address[0]
        self.server_port = self.server_address[1]


def write_chunk(wfile, data: bytes) -> None:
    """One HTTP/1.1 chunked-transfer frame."""
    wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
    wfile.flush()


class _HttpProxy:
    def __init__(self, controller: ServeController, host: str, port: int):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            # chunked transfer is illegal on HTTP/1.0; spec-compliant
            # clients only dechunk 1.1 responses
            protocol_version = "HTTP/1.1"

            def do_POST(self):  # noqa: N802
                from . import reqlog

                # end-to-end forensics id: honor the client's
                # x-request-id, else mint one at first touch
                request_id = (
                    self.headers.get("x-request-id")
                    or reqlog.new_request_id()
                )
                retry_after = None
                try:
                    from urllib.parse import parse_qs, urlsplit

                    url = urlsplit(self.path)
                    reqlog.mark(request_id, "http.received", path=url.path)
                    query = parse_qs(url.query)
                    length = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    # path = /<deployment>[/<method>][?stream=1][&model_id=m]
                    #        [&timeout_s=5]
                    parts = [p for p in url.path.split("/") if p]
                    if not parts:
                        raise KeyError("missing deployment in path")
                    handle = controller.get_handle(parts[0])
                    model_id = query.get("model_id", [None])[0]
                    if model_id:
                        handle = handle.options(multiplexed_model_id=model_id)
                    timeout_s = query.get("timeout_s", [None])[0]
                    if timeout_s:
                        handle = handle.options(timeout_s=float(timeout_s))
                    # tenant/priority: query param wins, headers fall back
                    # (same resolution the OpenAI front-end does)
                    from . import tenancy

                    tenant = query.get("tenant", [None])[0]
                    priority = query.get("priority", [None])[0]
                    if tenant is None and priority is None:
                        tenant, h_priority = tenancy.resolve_http_tenant(
                            self.headers
                        )
                        priority = h_priority
                    if tenant is not None or priority is not None:
                        handle = handle.options(
                            tenant=tenant,
                            priority=(
                                int(priority) if priority is not None else None
                            ),
                        )
                    handle = handle.options(request_id=request_id)
                    method = parts[1] if len(parts) > 1 else "__call__"
                    if query.get("stream", ["0"])[0] in ("1", "true"):
                        self._stream_response(handle, method, payload,
                                              request_id)
                        return
                    ref = getattr(handle, method).remote(payload) if method != "__call__" else handle.remote(payload)
                    result = _core_api.get(ref, timeout=120)
                    body = json.dumps({
                        "result": result, "request_id": request_id,
                    }).encode()
                    self.send_response(200)
                except KeyError as e:
                    body = json.dumps({
                        "error": f"not found: {e}",
                        "request_id": request_id,
                    }).encode()
                    self.send_response(404)
                except Exception as e:
                    # typed serve errors keep their HTTP semantics: shed →
                    # 429 + Retry-After, no replicas → 503, deadline → 504
                    from ..core.exceptions import (
                        BackPressureError,
                        DeploymentUnavailableError,
                        GetTimeoutError,
                        ReplicaDrainingError,
                        RequestTimeoutError,
                        unwrap_error,
                    )

                    cause = unwrap_error(e)
                    if isinstance(cause, BackPressureError):
                        # honest Retry-After: token-bucket refill or queue
                        # drain-rate estimate when the shedder computed one
                        import math

                        retry = getattr(cause, "retry_after_s", None)
                        code = 429
                        retry_after = (
                            max(1, int(math.ceil(float(retry))))
                            if retry and retry > 0 else 1
                        )
                    elif isinstance(
                        cause, (DeploymentUnavailableError, ReplicaDrainingError)
                    ):
                        code, retry_after = 503, 1
                    elif isinstance(
                        cause, (RequestTimeoutError, GetTimeoutError)
                    ):
                        code = 504
                    else:
                        code = 500
                    # request_id rides NEXT TO Retry-After: a shed client
                    # can quote it straight to `ray_tpu request <id>`
                    body = json.dumps({
                        "error": repr(cause), "request_id": request_id,
                    }).encode()
                    self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.send_header("x-request-id", request_id)
                self.end_headers()
                self.wfile.write(body)

            def _stream_response(self, handle, method, payload,
                                 request_id=None) -> None:
                """Chunked transfer: one JSON line per yielded item
                (reference: Serve streaming responses over ASGI). Items
                flow as the replica's generator produces them — backed by
                num_returns='streaming' on the actor call."""
                caller = handle.options(stream=True)
                stream = (
                    caller.remote(payload) if method == "__call__"
                    else getattr(caller, method).remote(payload)
                )
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                if request_id is not None:
                    self.send_header("x-request-id", request_id)
                self.end_headers()

                def chunk(data: bytes) -> None:
                    write_chunk(self.wfile, data)

                try:
                    for ref in stream:
                        item = _core_api.get(ref, timeout=120)
                        chunk((json.dumps({"result": item}) + "\n").encode())
                except Exception as e:  # noqa: BLE001 - surfaces as final line
                    chunk((json.dumps({"error": repr(e)}) + "\n").encode())
                self.wfile.write(b"0\r\n\r\n")

            def log_message(self, *args):  # silence request logs
                pass

        self.server = EgresslessHTTPServer((host, port), Handler)
        self.port = self.server.server_address[1]
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True, name="serve-http"
        )
        self.thread.start()

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()


def start_http(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the HTTP ingress; returns the bound port."""
    global _proxy
    controller = _get_controller()  # before taking _lock: it locks too
    with _lock:
        if _proxy is None:
            _proxy = _HttpProxy(controller, host, port)
        return _proxy.port
