"""Per-request serve context: the end-to-end deadline and the tenant.

The router stamps each request with an absolute deadline (epoch seconds,
``_deadline_ts`` kwarg — the same kwargs channel tracing context rides).
`_ReplicaWrapper.call` pops it and makes it ambient here so deployment
code — and anything it calls, notably `LLMServer._submit` handing the
deadline to an engine, or a downstream `DeploymentHandle` hop — inherits
the remaining budget instead of starting a fresh clock per hop
(reference parity: Serve's request-context deadline propagation).

The tenant/priority pair rides the same channel (``_tenant`` /
``_priority`` kwargs): the HTTP frontends resolve it from headers or API
keys, ``DeploymentHandle.options(tenant=..., priority=...)`` overrides
it per call, and engines read it here to drive weighted-fair admission,
token-bucket quotas, and lane preemption (serve/tenancy.py).
"""

from __future__ import annotations

import contextvars
import time
from typing import Optional, Tuple

_deadline: contextvars.ContextVar[Optional[float]] = contextvars.ContextVar(
    "raytpu_serve_deadline", default=None
)


def get_request_deadline() -> Optional[float]:
    """Absolute deadline (time.time() epoch seconds) of the serve request
    currently executing on this thread, or None when no deadline is set."""
    return _deadline.get()


def remaining_s() -> Optional[float]:
    """Seconds left before the ambient deadline (None = no deadline;
    never negative)."""
    deadline = _deadline.get()
    if deadline is None:
        return None
    return max(0.0, deadline - time.time())


def _set_request_deadline(deadline_ts: Optional[float]):
    """Internal: installs the deadline for the executing request; returns
    the reset token. Only `_ReplicaWrapper` should call this."""
    return _deadline.set(deadline_ts)


def _reset_request_deadline(token) -> None:
    _deadline.reset(token)


_tenant: contextvars.ContextVar[Optional[Tuple[Optional[str], Optional[int]]]] = (
    contextvars.ContextVar("raytpu_serve_tenant", default=None)
)


def get_request_tenant() -> Optional[str]:
    """Tenant id of the serve request currently executing on this thread,
    or None when the request carries no tenant (engines treat None as the
    'default' tenant)."""
    pair = _tenant.get()
    return pair[0] if pair is not None else None


def get_request_priority() -> Optional[int]:
    """Priority of the executing serve request (higher = more important;
    used only for lane preemption eligibility, never queue order), or
    None when unset."""
    pair = _tenant.get()
    return pair[1] if pair is not None else None


def _set_request_tenant(tenant: Optional[str], priority: Optional[int]):
    """Internal: installs the tenant/priority pair for the executing
    request; returns the reset token. Only `_ReplicaWrapper` should call
    this (mirrors `_set_request_deadline`)."""
    return _tenant.set((tenant, priority))


def _reset_request_tenant(token) -> None:
    _tenant.reset(token)


_request_id: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "raytpu_serve_request_id", default=None
)


def get_request_id() -> Optional[str]:
    """End-to-end id of the serve request currently executing on this
    thread (the public key the request-forensics plane records marks
    under and responses echo as `x-request-id`), or None when the call
    did not arrive through the router with an id."""
    return _request_id.get()


def _set_request_id(request_id: Optional[str]):
    """Internal: installs the request id for the executing request;
    returns the reset token (mirrors `_set_request_deadline`)."""
    return _request_id.set(request_id)


def _reset_request_id(token) -> None:
    _request_id.reset(token)
