"""ServeController: reconciles target deployment state against live actors.

Reference parity: serve/_private/controller.py:86 ServeController +
deployment_state.py (DeploymentStateManager :2343, DeploymentState FSM
:1248) + autoscaling_state.py. One reconcile thread owns: replica start/
stop, health checks with restarts, ongoing-request autoscaling, and
graceful draining — scale-down and redeploy mark replicas DRAINING (the
router stops picking them; in-flight requests finish up to a drain
deadline) before the actor is killed.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import api
from ..core.actors import ActorState
from ..core.exceptions import ReplicaDrainingError, RequestTimeoutError
from .deployment import Application, Deployment
from .router import _counter, _head_outage_s, _rkey, DeploymentHandle, ReplicaSet

logger = logging.getLogger(__name__)


class _ReplicaWrapper:
    """Actor body: hosts the user's deployment instance."""

    def __init__(self, cls, args, kwargs):
        self._draining = False
        self._instance = cls(*args, **kwargs)

    def prepare_drain(self) -> str:
        """Controller marks this replica DRAINING: in-flight calls finish,
        new calls are rejected with a typed (router-retryable) error."""
        self._draining = True
        return "draining"

    def call(self, method: str, *args, **kwargs):
        from . import context as serve_ctx
        from .multiplex import _set_model_id

        model_id = kwargs.pop("_multiplexed_model_id", None)
        deadline = kwargs.pop("_deadline_ts", None)
        tenant = kwargs.pop("_tenant", None)
        priority = kwargs.pop("_priority", None)
        request_id = kwargs.pop("_request_id", None)
        if self._draining:
            # a call that raced the drain mark: bounce it so the router
            # fails over instead of queueing work behind a dying replica
            raise ReplicaDrainingError(
                f"replica is draining; retry {method!r} on a live replica"
            )
        if deadline is not None and time.time() >= deadline:
            raise RequestTimeoutError(
                f"request deadline expired before {method!r} started"
            )
        _set_model_id(model_id)
        token = serve_ctx._set_request_deadline(deadline)
        tenant_token = serve_ctx._set_request_tenant(tenant, priority)
        rid_token = serve_ctx._set_request_id(request_id)
        try:
            result = getattr(self._instance, method)(*args, **kwargs)
            if hasattr(result, "__next__") and (
                model_id or deadline is not None or tenant is not None
                or request_id is not None
            ):
                # generator bodies run at iteration time (the streaming
                # executor drains them after this returns): re-establish
                # the model-id + deadline + tenant + request-id context
                # around actual execution
                return _with_request_context(
                    result, model_id, deadline, tenant, priority,
                    request_id,
                )
            return result
        finally:
            serve_ctx._reset_request_id(rid_token)
            serve_ctx._reset_request_tenant(tenant_token)
            serve_ctx._reset_request_deadline(token)
            _set_model_id(None)

    def health(self) -> str:
        check = getattr(self._instance, "check_health", None)
        if check is not None:
            check()
        return "ok"


def _with_request_context(gen, model_id: Optional[str],
                          deadline: Optional[float],
                          tenant: Optional[str] = None,
                          priority: Optional[int] = None,
                          request_id: Optional[str] = None):
    from . import context as serve_ctx
    from .multiplex import _set_model_id

    _set_model_id(model_id)
    token = serve_ctx._set_request_deadline(deadline)
    tenant_token = serve_ctx._set_request_tenant(tenant, priority)
    rid_token = serve_ctx._set_request_id(request_id)
    try:
        yield from gen
    finally:
        serve_ctx._reset_request_id(rid_token)
        serve_ctx._reset_request_tenant(tenant_token)
        serve_ctx._reset_request_deadline(token)
        _set_model_id(None)


class _DeploymentState:
    """Per-deployment record in the controller."""

    def __init__(self, deployment: Deployment, app: Application,
                 source_app: Optional[Application] = None):
        self.deployment = deployment
        self.app = app
        # the ORIGINAL (unresolved) Application object: child-dedup keys on
        # its identity so shared children deploy once but a fresh .bind()
        # redeploys
        self.source_app = source_app if source_app is not None else app
        self.target_replicas = deployment.config.num_replicas
        if deployment.config.autoscaling:
            self.target_replicas = deployment.config.autoscaling.min_replicas
        self.replicas: List[Any] = []
        self.replica_set = ReplicaSet(
            deployment.name,
            max_ongoing=deployment.config.max_ongoing_requests,
            max_queued=deployment.config.max_queued_requests,
        )
        self.last_scale_down = time.time()
        # readiness/probe tracking for the health pruner (keyed by actor
        # id hex — stable, unlike id() which recycles addresses)
        self.started_at: Dict[str, float] = {}
        self.ready_at: Dict[str, float] = {}
        self.probe_refs: Dict[str, Any] = {}   # key -> (ref, sent_at)
        self.last_probe: Dict[str, float] = {}
        # DRAINING replicas: key -> (handle, force-kill deadline). Out of
        # `replicas` (never routed/probed) but kept alive until their
        # ongoing count hits zero or the drain deadline passes.
        self.draining: Dict[str, Tuple[Any, float]] = {}

    def forget(self, key: str) -> None:
        for d in (self.started_at, self.ready_at, self.probe_refs, self.last_probe):
            d.pop(key, None)


class ServeController:
    """In-process controller; reconcile loop runs on a daemon thread."""

    def __init__(self, reconcile_interval_s: float = 0.2):
        self._states: Dict[str, _DeploymentState] = {}  # guarded-by: _lock
        # deleted/redeployed deployments whose replicas are still draining
        self._condemned: List[_DeploymentState] = []  # guarded-by: _lock
        self._lock = threading.Lock()
        self._interval = reconcile_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # advertise replica targets with no placeable host to the
        # capacity plane (origin=serve); unregistered in shutdown()
        from ..core.capacity import register_demand_source

        self._demand_source_name = f"serve:{id(self):x}"
        register_demand_source(
            self._demand_source_name, self._pending_capacity_demand
        )

    def _pending_capacity_demand(self) -> List[Dict[str, Any]]:
        """DemandLedger source: per-deployment replica deficits whose
        resources_per_replica fit on NO placeable node — reconcile can
        retry forever, only new capacity unblocks those."""
        from ..core import runtime as rt

        if not rt.is_initialized():
            return []
        nodes = [
            n for n in rt.get_runtime().scheduler.nodes() if n.placeable()
        ]
        with self._lock:
            states = list(self._states.values())
        out: List[Dict[str, Any]] = []
        for state in states:
            deficit = state.target_replicas - len(state.replicas)
            if deficit <= 0:
                continue
            res = dict(
                state.deployment.config.resources_per_replica
                or {"CPU": 1.0}
            )
            placeable = any(
                all(n.resources.total.get(k, 0.0) >= v
                    for k, v in res.items())
                for n in nodes
            )
            if placeable:
                continue  # a live node can host it once load drains
            out.append({
                "bundles": [dict(res) for _ in range(deficit)],
                "origin": "serve",
                "detail": f"{deficit} replica(s) of "
                          f"{state.deployment.name}",
            })
        return out

    # ------------------------------------------------------------- lifecycle

    def deploy(self, app: Application, _is_child: bool = False) -> DeploymentHandle:
        # COMPOSITION (reference: deployment graphs / handle chaining):
        # an Application passed as an init arg deploys first and is
        # replaced by its DeploymentHandle, so deployments call
        # deployments through the router (per-hop load balancing).
        dep = app.deployment
        with self._lock:
            existing = self._states.get(dep.name)
        if _is_child and existing is not None and existing.source_app is app:
            # the SAME Application object (shared child: bound twice in one
            # graph, or across parents) deploys once; a redeploy with a
            # fresh .bind() is a different object and replaces below
            return DeploymentHandle(existing.replica_set)
        if existing is not None:
            self.delete(dep.name)  # redeploy: old replicas drain out
        source_app = app
        init_args = tuple(
            self.deploy(a, _is_child=True) if isinstance(a, Application) else a
            for a in app.init_args
        )
        init_kwargs = {
            k: self.deploy(v, _is_child=True) if isinstance(v, Application) else v
            for k, v in app.init_kwargs.items()
        }
        app = Application(app.deployment, init_args, init_kwargs)
        with self._lock:
            state = _DeploymentState(dep, app, source_app=source_app)
            self._states[dep.name] = state
        from ..util.events import emit

        emit("INFO", "serve",
             f"deployment {dep.name} deployed "
             f"(target {state.target_replicas} replica(s))",
             kind="serve.deploy", deployment=dep.name,
             target_replicas=state.target_replicas)
        self._reconcile_one(state)  # synchronous first bring-up
        self._ensure_thread()
        return DeploymentHandle(state.replica_set)

    def get_handle(self, name: str) -> DeploymentHandle:
        with self._lock:
            if name not in self._states:
                raise KeyError(f"no deployment {name!r}; have {list(self._states)}")
            return DeploymentHandle(self._states[name].replica_set)

    def delete(self, name: str, drain: bool = True) -> None:
        """Remove a deployment. With drain=True (the default) its live
        replicas go DRAINING — they finish in-flight requests up to the
        drain deadline before being killed; drain=False kills instantly."""
        with self._lock:
            state = self._states.pop(name, None)
        if not state:
            return
        if drain:
            for r in list(state.replicas):
                self._begin_drain(state, r)
            state.replicas = []
            state.replica_set.set_replicas([])
            with self._lock:
                if state.draining:
                    self._condemned.append(state)
            if state.draining:
                self._ensure_thread()
            return
        for r in state.replicas:
            _kill_quietly(r)
        for key, (r, _) in list(state.draining.items()):
            _kill_quietly(r)
            state.replica_set.finish_draining(key)
        state.draining.clear()
        state.replica_set.set_replicas([])

    def shutdown(self) -> None:
        from ..core.capacity import unregister_demand_source

        unregister_demand_source(self._demand_source_name)
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        with self._lock:
            names = list(self._states)
            condemned = list(self._condemned)
            self._condemned = []
        for name in names:
            self.delete(name, drain=False)
        for state in condemned:
            for key, (r, _) in list(state.draining.items()):
                _kill_quietly(r)
                state.replica_set.finish_draining(key)
            state.draining.clear()

    def status(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                name: {
                    "target_replicas": s.target_replicas,
                    "live_replicas": len(s.replicas),
                    "draining_replicas": len(s.draining),
                    "ongoing": s.replica_set.total_ongoing(),
                }
                for name, s in self._states.items()
            }

    # ------------------------------------------------------------- reconcile

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="serve-controller"
            )
            self._thread.start()

    def _loop(self) -> None:
        frozen_since = 0.0
        while not self._stop.wait(self._interval):
            outage = _head_outage_s()
            if outage > 0.0:
                # Head outage: replica CALLS still flow (direct to node
                # agents), but scaling decisions need the head (named-
                # actor registration, placement). Freeze reconciliation
                # for the grace window instead of churning replicas on a
                # blind control plane; past the window, resume and let
                # typed HeadUnavailableError surface per decision.
                from ..core.config import cfg as _cfg

                if outage <= float(_cfg.head_outage_grace_s):
                    if not frozen_since:
                        frozen_since = time.monotonic()
                        from ..util.events import emit

                        emit("WARNING", "serve",
                             "serve controller frozen: head unreachable; "
                             "serving on cached replica membership",
                             kind="serve.degraded", outage_s=round(outage, 2))
                    continue
            if frozen_since:
                # probes issued before the freeze are all overdue by now;
                # clearing probe state prevents a mass prune on unfreeze
                with self._lock:
                    states = list(self._states.values())
                for state in states:
                    state.probe_refs.clear()
                    state.last_probe.clear()
                from ..util.events import emit

                emit("INFO", "serve",
                     "serve controller resumed after "
                     f"{time.monotonic() - frozen_since:.1f}s frozen",
                     kind="serve.degraded", resumed=True)
                frozen_since = 0.0
            with self._lock:
                states = list(self._states.values())
                condemned = list(self._condemned)
            for state in states:
                try:
                    self._autoscale(state)
                    self._reconcile_one(state)
                except Exception:
                    logger.exception("reconcile failed for %s", state.deployment.name)
            for state in condemned:
                try:
                    self._reap_draining(state)
                except Exception:
                    logger.exception("drain reap failed for %s", state.deployment.name)
                if not state.draining:
                    with self._lock:
                        try:
                            self._condemned.remove(state)
                        except ValueError:
                            pass

    def _begin_drain(self, state: _DeploymentState, victim: Any) -> None:
        """Move a replica to DRAINING: the router stops picking it, the
        replica bounces new calls, and the reaper below kills it once its
        ongoing count drains (or the drain deadline passes)."""
        key = _rkey(victim)
        state.replica_set.mark_draining(key)
        state.forget(key)
        state.draining[key] = (
            victim,
            time.monotonic() + state.deployment.config.drain_timeout_s,
        )
        from ..util.events import emit

        emit("INFO", "serve",
             f"deployment {state.deployment.name}: replica {key[:12]} "
             f"draining", kind="serve.drain",
             deployment=state.deployment.name, replica=key,
             ongoing=state.replica_set.ongoing_for(key))
        try:
            victim.prepare_drain.remote()  # best-effort flag on the actor
        except Exception:
            pass

    def _reap_draining(self, state: _DeploymentState) -> None:
        now = time.monotonic()
        for key, (victim, kill_at) in list(state.draining.items()):
            ongoing = state.replica_set.ongoing_for(key)
            if ongoing <= 0 or now >= kill_at:
                if ongoing > 0:
                    _counter(
                        "raytpu_serve_drain_forced_total",
                        "draining replicas force-killed at the drain deadline",
                    ).inc()
                    logger.warning(
                        "drain deadline passed for %s replica %s with %d "
                        "request(s) still in flight; force-killing",
                        state.deployment.name, key[:12], ongoing,
                    )
                else:
                    _counter(
                        "raytpu_serve_drained_total",
                        "replicas drained cleanly before removal",
                    ).inc()
                _kill_quietly(victim)
                state.replica_set.finish_draining(key)
                del state.draining[key]

    def _reconcile_one(self, state: _DeploymentState) -> None:
        dep = state.deployment
        # Health/readiness pruning. Probes are NON-BLOCKING (fired on the
        # health_check_period_s cadence, harvested next rounds) so one
        # slow replica can never stall reconciliation of every deployment.
        # A replica still STARTING (its __init__ may legitimately spend
        # minutes compiling/loading on the chip) is not unhealthy until
        # startup_grace_s expires — readiness vs liveness, like the
        # reference's deployment FSM.
        live = []
        now = time.monotonic()
        for r in state.replicas:
            key = _rkey(r)
            if self._probe_ok(state, dep, r, key, now):
                live.append(r)
            else:
                _kill_quietly(r)
                state.forget(key)
        state.replicas = live
        # scale up
        started = 0
        while len(state.replicas) < state.target_replicas:
            # an EXPLICIT resources_per_replica charges exactly what it
            # says (num_cpus would clobber its CPU entry otherwise); the
            # default keeps replicas CPU-free as before
            explicit = dep.config.resources_per_replica
            actor_cls = api.remote(_ReplicaWrapper).options(
                max_concurrency=dep.config.max_ongoing_requests,
                resources=explicit or {"CPU": 1.0},
                num_cpus=float(explicit.get("CPU", 0.0)) if explicit else 0,
                name=f"serve:{dep.name}#{len(state.replicas)}-{time.monotonic_ns()}",
            )
            replica = actor_cls.remote(dep.cls, state.app.init_args, state.app.init_kwargs)
            state.started_at[_rkey(replica)] = time.monotonic()
            state.replicas.append(replica)
            started += 1
        if started:
            from ..util.events import emit

            emit("INFO", "serve",
                 f"deployment {dep.name}: +{started} replica(s) "
                 f"(target {state.target_replicas})",
                 kind="serve.scaled", deployment=dep.name,
                 direction="up", delta=started,
                 target_replicas=state.target_replicas)
        # scale down (newest first): drain, don't guillotine — READY
        # replicas may be mid-request; unready ones die immediately
        scaled_down = 0
        while len(state.replicas) > state.target_replicas:
            victim = state.replicas.pop()
            key = _rkey(victim)
            scaled_down += 1
            if key in state.ready_at and dep.config.drain_timeout_s > 0:
                self._begin_drain(state, victim)
            else:
                _kill_quietly(victim)
                state.forget(key)
        if scaled_down:
            from ..util.events import emit

            emit("INFO", "serve",
                 f"deployment {dep.name}: -{scaled_down} replica(s) "
                 f"(target {state.target_replicas})",
                 kind="serve.scaled", deployment=dep.name,
                 direction="down", delta=scaled_down,
                 target_replicas=state.target_replicas)
        self._reap_draining(state)
        # route only to READY replicas so requests never queue behind a
        # replica's __init__; fall back to all replicas during initial
        # bring-up (an empty set would hard-fail callers instead of
        # letting the first requests wait out the first compile)
        ready = [r for r in state.replicas if _rkey(r) in state.ready_at]
        state.replica_set.set_replicas(ready if ready else state.replicas)

    def _probe_ok(self, state: _DeploymentState, dep, r, key: str, now: float) -> bool:
        """Advance this replica's probe state machine; False = prune it."""
        cfg = dep.config
        pending = state.probe_refs.get(key)
        if pending is None:
            last = state.last_probe.get(key, 0.0)
            if now - last >= cfg.health_check_period_s:
                state.probe_refs[key] = (r.health.remote(), now)
                state.last_probe[key] = now
            return True
        ref, sent = pending
        failed = False
        if ref.is_ready():
            state.probe_refs.pop(key, None)
            try:
                api.get(ref, timeout=1)
                state.ready_at.setdefault(key, now)
                return True
            except Exception:
                failed = True
        elif now - sent > cfg.health_check_timeout_s:
            failed = True  # probe overdue (leave it pending: it completes
            # the moment a starting replica finishes __init__)
        if not failed:
            return True  # probe in flight, within budget
        still_starting = (
            key not in state.ready_at
            and now - state.started_at.get(key, now) < cfg.startup_grace_s
        )
        try:
            hard_dead = r.state() == ActorState.DEAD
        except Exception:
            hard_dead = True
        return still_starting and not hard_dead

    def _slo_burn_delta(self, state: _DeploymentState) -> int:
        """New SLO-violating windows in the ServeSLOMonitor attainment
        ledger since this deployment's last autoscale pass. The ledger is
        cumulative, so each state keeps a high-water mark; the monitor is
        process-global (SLOs are measured at the router, not per
        deployment), so every slo_driven deployment reacts to a burn —
        correct for the common one-LLM-deployment serve graph this
        targets."""
        try:
            from ..util.watchdog import serve_slo_monitor

            report = serve_slo_monitor().attainment_report()
        except Exception:
            return 0
        violated = sum(int(led.get("violated", 0)) for led in report.values())
        prev = getattr(state, "_slo_violated_seen", 0)
        state._slo_violated_seen = violated
        return max(0, violated - prev)

    @staticmethod
    def _engine_pressure() -> float:
        """Max batch_fill across registered engines (the
        raytpu_engine_batch_fill callback gauge): how full the decode
        batches actually are, the second demand signal next to the
        router's ongoing count."""
        try:
            from ..util.metrics import registry

            gauge = registry().get("raytpu_engine_batch_fill")
            if gauge is None:
                return 0.0
            return max((v for _t, v in gauge.collect()), default=0.0)
        except Exception:
            return 0.0

    def _autoscale(self, state: _DeploymentState) -> None:
        """Replica-target policy. Base term: ongoing requests over
        target_ongoing_requests (the reference's autoscaling_state
        heuristic). SLO term (slo_driven): new burn windows from the
        ServeSLOMonitor bump the target one replica past the live count —
        latency is burning while the ongoing count still looks fine, the
        exact gap the heuristic cannot see (queued work waiting on slow
        TTFT counts as few ongoing requests). Targets only move here;
        _reconcile_one realizes them, so scale-down always rides the
        graceful drain path."""
        auto = state.deployment.config.autoscaling
        if auto is None:
            return
        import math

        from ..core.config import cfg
        from ..util.events import emit

        ongoing = state.replica_set.total_ongoing()
        desired = ongoing / auto.target_ongoing_requests
        target = max(auto.min_replicas, min(auto.max_replicas, math.ceil(desired)))
        reason = "ongoing"
        burn = 0
        if auto.slo_driven and cfg.autoscale_burn_windows > 0:
            burn = self._slo_burn_delta(state)
            if burn >= cfg.autoscale_burn_windows:
                live = max(len(state.replicas), state.target_replicas)
                pressure = max(
                    desired / max(1, live), self._engine_pressure()
                )
                if pressure >= cfg.autoscale_pressure_floor:
                    bumped = min(auto.max_replicas, live + 1)
                    if bumped > target:
                        target = bumped
                        reason = "slo_burn"
        if target > state.target_replicas:
            prev = state.target_replicas
            state.target_replicas = target
            state.last_scale_down = time.time()
            emit("INFO", "serve",
                 f"autoscaler: {state.deployment.name} target "
                 f"{prev} -> {target} ({reason}"
                 f"{f', {burn} burn window(s)' if burn else ''}, "
                 f"ongoing {ongoing})",
                 kind="serve.autoscale", deployment=state.deployment.name,
                 direction="up", reason=reason, burn_windows=burn,
                 ongoing=ongoing, target_replicas=target)
        elif target < state.target_replicas:
            # dampen scale-down; a fresh burn window also resets the timer
            # so a burning deployment never sheds capacity
            if burn > 0:
                state.last_scale_down = time.time()
                return
            if time.time() - state.last_scale_down > auto.scale_down_delay_s:
                prev = state.target_replicas
                state.target_replicas = target
                state.last_scale_down = time.time()
                emit("INFO", "serve",
                     f"autoscaler: {state.deployment.name} target "
                     f"{prev} -> {target} "
                     f"({'idle' if ongoing == 0 else 'ongoing'}, "
                     f"ongoing {ongoing})",
                     kind="serve.autoscale",
                     deployment=state.deployment.name, direction="down",
                     reason="idle" if ongoing == 0 else "ongoing",
                     burn_windows=0, ongoing=ongoing,
                     target_replicas=target)


def _kill_quietly(replica: Any) -> None:
    try:
        api.kill(replica)
    except Exception:
        pass
