"""Speculative decoding for the paged engine: draft, verify, accept.

Decode is latency-bound — one token per sequential model pass per lane —
while the ragged paged-attention launch already scores MULTI-token
regions (prefill chunks) at near-decode cost. Speculative decoding spends
that slack: a cheap DRAFT proposer guesses the next K tokens, the engine
verifies all K in one ragged launch (q_len = K region per lane, the same
descriptor a prefill chunk uses), and an exact accept/resample step keeps
the output distribution identical to plain autoregressive decoding:

- temperature 0: accept drafts while they match the verified argmax;
  the first mismatch emits the argmax instead (token-for-token parity
  with the non-speculative engine).
- temperature > 0: rejection sampling against the verified (temperature/
  top-k/top-p filtered) distribution. The default proposers are
  deterministic (point-mass q), so draft t is accepted with probability
  p(t) and a rejection resamples from p with t masked out and
  renormalized — the textbook residual, exact by the standard
  speculative-sampling argument.

Every round emits between 1 (all drafts rejected — the corrected token)
and K+1 (all accepted plus the bonus token sampled from the last verified
row) tokens, so speculation can only add tokens per launch, never stall.

Proposers are pluggable (`DraftProposer`): the default is n-gram
prompt-lookup self-drafting (no extra model, great on repetitive/
templated continuations), with an optional small draft model sharing the
mesh, and a replay proposer used by benches/tests to pin acceptance
deterministically.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Protocol, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class DraftProposer(Protocol):
    """Propose up to `k` draft tokens continuing `context` (prompt plus
    every token emitted so far). Returning fewer than `k` (or none) is
    always legal — the verify round shrinks to what was proposed."""

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ...


class NgramProposer:
    """Prompt-lookup self-drafting: find the longest recent n-gram suffix
    of the context earlier in the context and propose the tokens that
    followed it. Free (no model, no device), and strong exactly where
    speculation pays — templated continuations, quoted spans, code — while
    degrading to empty proposals (a plain 1-token round) on novel text."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if max_ngram < min_ngram or min_ngram < 1:
            raise ValueError(f"bad ngram range [{min_ngram}, {max_ngram}]")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if k <= 0:
            return []
        ctx = list(context)
        for n in range(min(self.max_ngram, len(ctx) - 1), self.min_ngram - 1, -1):
            needle = ctx[-n:]
            # newest match first: recent repetition predicts best
            for i in range(len(ctx) - n - 1, -1, -1):
                if ctx[i:i + n] == needle:
                    cont = ctx[i + n:i + n + k]
                    if cont:
                        return cont
        return []


class ReplayProposer:
    """Drill proposer: replays known continuations keyed by prompt.

    Benches and tests use it to pin the acceptance rate — replaying a
    previous greedy run's outputs makes every draft accept (the
    high-acceptance drill); replaying corrupted outputs makes every draft
    reject (the rollback/adversarial drill)."""

    def __init__(self, continuations: Dict[Tuple[int, ...], Sequence[int]]):
        self._cont = {tuple(p): list(c) for p, c in continuations.items()}
        self._lens = sorted({len(p) for p in self._cont}, reverse=True)

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        ctx = list(context)
        for plen in self._lens:
            cont = self._cont.get(tuple(ctx[:plen]))
            if cont is None:
                continue
            done = len(ctx) - plen  # tokens already emitted
            if done < 0 or ctx[plen:] != cont[:done]:
                continue  # diverged from the recorded run: stop drafting
            return cont[done:done + k]
        return []


class DraftModelProposer:
    """Greedy K-token draft from a small dense model sharing the device.

    Recomputes the full window per drafted token (K forwards over a
    fixed `window`-token tail) — fine for the tiny draft models this is
    meant for; the verify launch amortizes the real model regardless.
    """

    def __init__(self, model_config: Any, params: Any, window: int = 64):
        from ...models.transformer import init_cache, prefill

        self.window = int(window)
        mc = model_config

        def _draft(params, buf, length, k_steps):
            def body(carry, _):
                buf, n = carry
                cache = init_cache(mc, 1, buf.shape[1])
                logits, _ = prefill(params, buf, n[None], cache, mc)
                nxt = jnp.argmax(logits[0]).astype(jnp.int32)
                buf = jax.lax.dynamic_update_slice(
                    buf, nxt[None, None], (0, n)
                )
                return (buf, n + 1), nxt

            (_, _), toks = jax.lax.scan(
                body, (buf, length), None, length=k_steps
            )
            return toks

        self._params = params
        self._draft = jax.jit(_draft, static_argnums=(3,))

    def propose(self, context: Sequence[int], k: int) -> List[int]:
        if k <= 0:
            return []
        tail = list(context)[-(self.window - k):]
        buf = np.zeros((1, self.window), dtype=np.int32)
        buf[0, : len(tail)] = tail
        toks = self._draft(
            self._params, jnp.asarray(buf),
            jnp.asarray(len(tail), jnp.int32), int(k),
        )
        # Opt-in proposer: this host read is the draft model's output and
        # the engine budgets a full round trip per verify round anyway.
        return [int(t) for t in np.asarray(toks)]  # raylint: disable=jax-hot-path


# ------------------------------------------------------------ accept step


def filtered_scores(logits, temps, top_ks, top_ps):
    """Per-lane temperature + top-k + top-p filtered scores (log-space;
    filtered-out tokens at -inf). POSITIONAL filtering over one argsort:
    exactly top_k tokens survive even under logit ties, and the nucleus
    keep-mask scatters back through the sort order (disabled lanes use
    k=V / p=1.0, which keep all). softmax of the result is the exact
    distribution `_sample_filtered` draws from — the accept step scores
    drafts against it so speculative output matches plain sampling."""
    b, vocab = logits.shape
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    order = jnp.argsort(scaled, axis=-1)[:, ::-1]  # desc indices
    desc = jnp.take_along_axis(scaled, order, axis=-1)
    k_idx = jnp.where(top_ks > 0, top_ks, vocab)
    positions = jnp.arange(vocab)[None, :]
    in_topk = positions < k_idx[:, None]
    p_desc = jax.nn.softmax(jnp.where(in_topk, desc, -jnp.inf), axis=-1)
    cum = jnp.cumsum(p_desc, axis=-1)
    # keep a token if the cumulative mass BEFORE it is < top_p
    # (the top token always survives: cum - p == 0 there)
    keep_sorted = in_topk & ((cum - p_desc) < top_ps[:, None])
    keep = jnp.zeros_like(keep_sorted).at[
        jnp.arange(b)[:, None], order
    ].set(keep_sorted)
    return jnp.where(keep, scaled, -jnp.inf)


def accept_speculative(logits, tokens, counts, key, temps, top_ks, top_ps):
    """Exact accept/resample over one verify round.

    logits: (B, K, V) verified logits; row j scores the token AFTER
        input row j (inputs are `tokens`: row 0 the pending token, rows
        1..K-1 the drafts).
    tokens: (B, K) int32 verify inputs.
    counts: (B,) int32 real input rows per lane (0 = inactive).
    Returns (out_tokens (B, K), n_out (B,)): lane b emits
    out_tokens[b, :n_out[b]] — its accepted drafts followed by the
    corrected (on rejection) or bonus (all accepted) token. n_out is
    always >= 1 for active lanes: a round can only add tokens.
    """
    b, kd, vocab = logits.shape
    flat = filtered_scores(
        logits.reshape(b * kd, vocab),
        jnp.repeat(temps, kd), jnp.repeat(top_ks, kd), jnp.repeat(top_ps, kd),
    )
    scores = flat.reshape(b, kd, vocab)
    probs = jax.nn.softmax(scores, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)  # (B, K) — matches _sample_* at t=0

    drafts = tokens[:, 1:]  # (B, K-1): draft j+1 is scored by logits row j
    k_u, k_r = jax.random.split(key)
    if kd > 1:
        p_draft = jnp.take_along_axis(
            probs[:, :-1, :], drafts[..., None], axis=-1
        )[..., 0]  # (B, K-1)
        accept_greedy = drafts == greedy[:, :-1]
        u = jax.random.uniform(k_u, (b, kd - 1))
        accept = jnp.where(temps[:, None] <= 0.0, accept_greedy, u < p_draft)
        # draft j+1 only exists (and only verifies) inside the real rows
        accept &= jnp.arange(kd - 1)[None, :] < (counts[:, None] - 1)
        run = jnp.cumprod(accept.astype(jnp.int32), axis=1)
        a = jnp.sum(run, axis=1)  # accepted draft count per lane
    else:
        a = jnp.zeros((b,), jnp.int32)
    lane = jnp.arange(b)
    # correction/bonus comes from verified row a: on rejection the first
    # rejected draft is masked out of row a's distribution (the exact
    # point-mass residual); when every draft accepted, row a == counts-1
    # and the full distribution yields the bonus token.
    row_scores = scores[lane, a]  # (B, V)
    rejected = tokens[lane, jnp.minimum(a + 1, kd - 1)]
    bonus = a >= (counts - 1)
    resid = jnp.where(
        (jax.nn.one_hot(rejected, vocab, dtype=bool)) & (~bonus)[:, None],
        -jnp.inf, row_scores,
    )
    next_sampled = jax.random.categorical(k_r, resid, axis=-1)
    next_tok = jnp.where(
        temps <= 0.0, greedy[lane, a], next_sampled
    ).astype(jnp.int32)
    idx = jnp.arange(kd)[None, :]
    draft_shift = jnp.concatenate(
        [drafts, jnp.zeros((b, 1), tokens.dtype)], axis=1
    ) if kd > 1 else jnp.zeros((b, kd), tokens.dtype)
    out = jnp.where(
        idx < a[:, None], draft_shift,
        jnp.where(idx == a[:, None], next_tok[:, None], 0),
    ).astype(jnp.int32)
    n_out = jnp.where(counts > 0, a + 1, 0).astype(jnp.int32)
    return out, n_out
