"""Paged KV cache + chunked prefill: the TPU continuous-batching substrate.

Reference parity: vLLM's paged attention + chunked prefill, which the
reference rides via VLLMEngine (/root/reference/python/ray/llm/_internal/
serve/deployments/llm/vllm/vllm_engine.py:254). TPU inversion (the ragged
paged attention recipe from PAPERS.md): XLA needs static shapes, so

- the KV cache is one FLAT pool of pages, (Hkv, L*num_pages, page_size, D)
  — layer i owns page range [i*num_pages, (i+1)*num_pages) — shared by
  every slot; a host-side allocator hands out (layer-agnostic) page ids
  and a per-slot block table maps logical positions to pages. HBM no
  longer scales with max_slots × max_seq — concurrency is bounded by
  actual tokens, like vLLM;
- decode attention reads ONLY the pages a slot uses: on TPU via the Pallas
  paged-attention kernel (scalar-prefetched block tables drive the block
  index_map, so unused pages are never fetched); off-TPU via a gather+mask
  XLA reference with identical semantics;
- prefill is CHUNKED: prompts are ingested page-aligned chunk by chunk
  (one chunk per engine tick), each chunk attending to the pages written
  so far — so a long prompt never blocks running decodes for more than
  one chunk's latency, and every chunk reuses ONE compiled program
  (offset is a traced scalar, the chunk length is static).

Page 0 is reserved as a scratch page: idle decode lanes write there and
block-table rows default to it, so the fixed-shape decode program needs no
host-side compaction.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import TransformerConfig, _norm
from ...ops import apply_rope, rope_frequencies

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class PagedConfig:
    page_size: int = 64
    num_pages: int = 256          # pool size (page 0 reserved as scratch)
    max_pages_per_slot: int = 16  # static block-table width
    chunk_pages: int = 4          # prefill chunk = chunk_pages * page_size

    @property
    def chunk_tokens(self) -> int:
        return self.chunk_pages * self.page_size

    @property
    def max_slot_tokens(self) -> int:
        return self.max_pages_per_slot * self.page_size


def init_paged_cache(
    model: TransformerConfig, paged: PagedConfig
) -> Dict[str, jax.Array]:
    """One FLAT page pool across layers: layer i owns pages
    [i*num_pages, (i+1)*num_pages). Folding the layer axis into the page
    axis is what keeps every cache access O(pages touched): updates are
    provably-aliasing dynamic_update_slices and reads are single gathers
    driven by per-layer-offset block tables — no per-layer slab ever
    materializes. (A (L, ...) leading axis forces XLA to either scan-
    double-buffer or slice out ~pool/L per layer per step; measured 8x
    decode slowdown at 512 pages.)"""
    shape = (
        model.kv_heads,
        model.n_layers * paged.num_pages,
        paged.page_size,
        model.head_dim,
    )
    return {"k": jnp.zeros(shape, model.dtype), "v": jnp.zeros(shape, model.dtype)}


class PageAllocator:
    """Host-side free list over the page pool. Page 0 is never handed out."""

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))
        self._allocated: set = set()
        self._lock = threading.Lock()

    def alloc(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if len(self._free) < n:
                return None
            pages = [self._free.pop() for _ in range(n)]
            self._allocated.update(pages)
            return pages

    def free(self, pages: List[int]) -> None:
        # Double-free guard: a page not currently allocated is ignored, so a
        # buggy caller can never put the same physical page on the free list
        # twice (which would hand it to two slots and corrupt both KV caches).
        with self._lock:
            for p in pages:
                if p > 0 and p in self._allocated:
                    self._allocated.discard(p)
                    self._free.append(p)

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)


# ------------------------------------------------------------------ attention


def _gather_ref_attention(q, k_cache, v_cache, block_tables, lengths):
    """XLA reference paged attention. q (B, Hq, D); caches
    (Hkv, P, ps, D); block_tables (B, maxP); lengths (B,). Returns (B, Hq, D).
    Semantics ground truth for the Pallas kernel (and the CPU path)."""
    b, hq, d = q.shape
    hkv, _, ps, _ = k_cache.shape
    # (B, maxP, Hkv, ps, D) -> (B, Hkv, maxP*ps, D)
    k = jnp.swapaxes(k_cache[:, block_tables], 0, 1)
    v = jnp.swapaxes(v_cache[:, block_tables], 0, 1)
    k = k.reshape(b, hkv, -1, d)
    v = v.reshape(b, hkv, -1, d)
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    logits = jnp.einsum(
        "bhd,bhkd->bhk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    mask = jnp.arange(k.shape[2])[None, :] < lengths[:, None]
    logits = jnp.where(mask[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bhkd->bhd", probs.astype(v.dtype), v)


def paged_attention(q, k_cache, v_cache, block_tables, lengths, *, page_size: int,
                    use_kernel: Optional[bool] = None):
    """Dispatch: Pallas paged kernel on TPU, gather reference elsewhere.

    The Mosaic lowering requires the trailing block dims be (8, 128)-
    divisible, so the kernel is only eligible for head_dim % 128 == 0 and
    page_size % 8 == 0 (e.g. Llama-class models); smaller shapes (tiny
    test configs, GPT-2's 64-dim heads) take the gather reference, which
    XLA fuses well at those sizes anyway.

    use_kernel=False forces the gather path: under a tensor-parallel mesh
    the GSPMD partitioner cannot split a Pallas call, while the gather
    reference partitions cleanly on the (tp-sharded) kv-head axis."""
    head_dim = q.shape[-1]
    if use_kernel is None:
        use_kernel = (
            jax.default_backend() == "tpu"
            and head_dim % 128 == 0
            and page_size % 8 == 0
        )
    if use_kernel:
        from jax.experimental.pallas.ops.tpu.paged_attention import (
            paged_attention as _kernel,
        )

        hq = q.shape[1]
        hkv = k_cache.shape[0]
        # kernel layout: q (B, Hq, D); pages (Hkv, P, ps, D); scale built in?
        # The kernel computes unscaled q·k, so pre-scale q.
        scaled = q / math.sqrt(q.shape[-1])
        pages_per_block = max(1, min(4, block_tables.shape[1]))
        while block_tables.shape[1] % pages_per_block:
            pages_per_block -= 1
        return _kernel(
            scaled,
            k_cache,
            v_cache,
            lengths,
            block_tables,
            pages_per_compute_block=pages_per_block,
        )
    return _gather_ref_attention(q, k_cache, v_cache, block_tables, lengths)


# --------------------------------------------------------------- model passes


def batched_chunk_prefill_step(
    params: Params,
    cache: Dict[str, jax.Array],
    page_rows: jax.Array,       # (B, maxP) block tables of the batched slots
    chunk_page_ids: jax.Array,  # (B, chunk_pages) pages each chunk fills
    tokens: jax.Array,          # (B, C) chunks, right-padded
    offsets: jax.Array,         # (B,) tokens already ingested (page-aligned)
    total_lens: jax.Array,      # (B,) offset + real tokens this chunk
    config: TransformerConfig,
    *,
    page_size: int,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Ingest one prompt chunk for up to B slots in ONE device call —
    burst admissions prefill together instead of serializing TTFT
    (vLLM batches prefill chunks across sequences the same way;
    reference vllm_engine.py:254). Inactive lanes point their
    chunk_page_ids at the scratch page (0) with total_len 0: they burn
    lane FLOPs but write only garbage the attention masks off.

    Returns the LAST real token's logits per lane (B, V) — only the
    lanes finishing their prompt this tick sample from them.
    """
    c = config
    dt = c.dtype
    b, chunk = tokens.shape
    chunk_pages = chunk // page_size
    pos = offsets[:, None] + jnp.arange(chunk)[None, :]  # (B, C)
    x = params["wte"].astype(dt)[tokens]  # (B, C, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[jnp.clip(pos, 0, c.max_seq - 1)]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    flat_ids = chunk_page_ids.reshape(-1)  # (B*cp,) — scratch dups are fine

    # Unrolled layers over the FLAT page pool (see init_paged_cache):
    # page writes are per-page DUS (in place), reads gather only each
    # lane's tables shifted into the layer's page range.
    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // c.n_layers
    zero = jnp.int32(0)
    for i in range(c.n_layers):
        lp = {name: w[i] for name, w in params["blocks"].items()}
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
        # whole-page in-place writes: one DUS per (lane, chunk page)
        kp = (
            k.transpose(1, 0, 2, 3)
            .reshape(k.shape[1], b * chunk_pages, page_size, k.shape[-1])
            .astype(c.dtype)
        )
        vp = (
            v.transpose(1, 0, 2, 3)
            .reshape(v.shape[1], b * chunk_pages, page_size, v.shape[-1])
            .astype(c.dtype)
        )
        layer_flat = flat_ids + i * num_pages
        for j in range(b * chunk_pages):
            start = (zero, layer_flat[j], zero, zero)
            k_full = jax.lax.dynamic_update_slice(k_full, kp[:, j][:, None], start)
            v_full = jax.lax.dynamic_update_slice(v_full, vp[:, j][:, None], start)
        # per-lane gathered attention over each slot's own pages
        layer_rows = page_rows + i * num_pages  # (B, maxP)
        keys = jnp.swapaxes(k_full[:, layer_rows], 0, 1)  # (B, Hkv, maxP, ps, D)
        vals = jnp.swapaxes(v_full[:, layer_rows], 0, 1)
        keys = keys.reshape(b, keys.shape[1], -1, keys.shape[-1])
        vals = vals.reshape(b, vals.shape[1], -1, vals.shape[-1])
        hq, hkv = q.shape[1], keys.shape[1]
        if hq != hkv:
            keys = jnp.repeat(keys, hq // hkv, axis=1)
            vals = jnp.repeat(vals, hq // hkv, axis=1)
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32
        ) / math.sqrt(q.shape[-1])
        key_pos = jnp.arange(keys.shape[2])
        causal = key_pos[None, None, :] <= pos[:, :, None]       # (B, C, S)
        valid = key_pos[None, None, :] < total_lens[:, None, None]
        logits = jnp.where((causal & valid)[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vals.dtype), vals)
        out = jnp.einsum("bhsd,hde->bse", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            from ...ops import swiglu

            act = swiglu(jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt)), up)
        else:
            from ...ops import gelu

            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        x = x + down
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head")
    if head is None:
        head = params["wte"].T
    # vocab projection ONLY for each lane's last real token (B, E) @ (E, V)
    last = jnp.clip(total_lens - offsets - 1, 0, chunk - 1)
    x_last = x[jnp.arange(b), last]  # (B, E)
    logits = jnp.einsum("be,ev->bv", x_last, head.astype(dt))
    return logits, {"k": k_full, "v": v_full}


def paged_decode_step(
    params: Params,
    cache: Dict[str, jax.Array],
    block_tables: jax.Array,  # (B, maxP) int32
    tokens: jax.Array,        # (B,) int32
    positions: jax.Array,     # (B,) int32 — write slot; length = position + 1
    config: TransformerConfig,
    *,
    page_size: int,
    use_kernel: Optional[bool] = None,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One continuous-batching decode step over the paged cache."""
    c = config
    dt = c.dtype
    b = tokens.shape[0]
    x = params["wte"].astype(dt)[tokens][:, None, :]  # (B, 1, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[positions][:, None, :]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    lengths = positions + 1
    page_ids = block_tables[jnp.arange(b), positions // page_size]  # (B,)
    rows = positions % page_size  # (B,)

    # Layers are UNROLLED (python loop) over the FLAT page pool (see
    # init_paged_cache): per-layer block tables are the slot's tables
    # shifted into layer i's page range, updates are per-lane DUS (in
    # place on the donated pool), reads gather only the table's pages.
    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // c.n_layers
    for i in range(c.n_layers):
        lp = {name: w[i] for name, w in params["blocks"].items()}
        layer_tables = block_tables + i * num_pages
        layer_pages = page_ids + i * num_pages
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            pos2d = positions[:, None]
            q = apply_rope(q, cos, sin, pos2d)
            k = apply_rope(k, cos, sin, pos2d)
        # Write this token's K/V into each slot's current page/row with
        # per-lane dynamic_update_slice — the canonical in-place KV-cache
        # update (a scatter over mixed indices lowers to a transposing
        # scatter that copies pool-sized buffers; DUS provably aliases).
        # Cost model: 2*B DUS ops per (unrolled) layer, so trace/compile
        # time scales with B*L — paid once per batch bucket at engine
        # precompile, never per request. Worth it: execution went 762ms ->
        # 52ms per 24-step block at a 1.2GB pool on v5e.
        newk = k[:, :, 0, :].astype(c.dtype)  # (B, Hkv, D)
        newv = v[:, :, 0, :].astype(c.dtype)
        zero = jnp.int32(0)
        for lane in range(b):
            upd_k = newk[lane][:, None, None, :]  # (Hkv, 1, 1, D)
            upd_v = newv[lane][:, None, None, :]
            start = (zero, layer_pages[lane], rows[lane], zero)
            k_full = jax.lax.dynamic_update_slice(k_full, upd_k, start)
            v_full = jax.lax.dynamic_update_slice(v_full, upd_v, start)
        attn = paged_attention(
            q[:, :, 0, :], k_full, v_full, layer_tables, lengths,
            page_size=page_size, use_kernel=use_kernel,
        )[:, :, None, :]
        out = jnp.einsum("bhsd,hde->bse", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            from ...ops import swiglu

            gate = jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt))
            act = swiglu(gate, up)
        else:
            from ...ops import gelu

            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        x = x + down
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head")
    if head is None:
        head = params["wte"].T
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(dt))[:, 0]
    return logits, {"k": k_full, "v": v_full}


def chunk_prefill_step(
    params: Params,
    cache: Dict[str, jax.Array],
    page_row: jax.Array,      # (maxP,) this slot's block table
    chunk_page_ids: jax.Array,  # (chunk_pages,) pages this chunk fills
    tokens: jax.Array,        # (1, C) the chunk, right-padded
    offset: jax.Array,        # () int32 — tokens already ingested (page-aligned)
    total_len: jax.Array,     # () int32 — offset + real tokens in this chunk
    config: TransformerConfig,
    *,
    page_size: int,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Single-slot chunk prefill: the B=1 case of
    batched_chunk_prefill_step (kept as the documented one-slot API).
    Returns the last real token's logits (1, V) and the updated pool."""
    return batched_chunk_prefill_step(
        params,
        cache,
        page_row[None],
        chunk_page_ids[None],
        tokens,
        jnp.reshape(offset, (1,)),
        jnp.reshape(total_len, (1,)),
        config,
        page_size=page_size,
    )
