"""Paged KV cache + chunked prefill: the TPU continuous-batching substrate.

Reference parity: vLLM's paged attention + chunked prefill, which the
reference rides via VLLMEngine (/root/reference/python/ray/llm/_internal/
serve/deployments/llm/vllm/vllm_engine.py:254). TPU inversion (the ragged
paged attention recipe from PAPERS.md): XLA needs static shapes, so

- the KV cache is one FLAT pool of pages, (Hkv, L*num_pages, page_size, D)
  — layer i owns page range [i*num_pages, (i+1)*num_pages) — shared by
  every slot; a host-side allocator hands out (layer-agnostic) page ids
  and a per-slot block table maps logical positions to pages. HBM no
  longer scales with max_slots × max_seq — concurrency is bounded by
  actual tokens, like vLLM;
- decode attention reads ONLY the pages a slot uses: on TPU via the Pallas
  paged-attention kernel (scalar-prefetched block tables drive the block
  index_map, so unused pages are never fetched); off-TPU via a gather+mask
  XLA reference with identical semantics;
- prefill is CHUNKED: prompts are ingested page-aligned chunk by chunk
  (one chunk per engine tick), each chunk attending to the pages written
  so far — so a long prompt never blocks running decodes for more than
  one chunk's latency, and every chunk reuses ONE compiled program
  (offset is a traced scalar, the chunk length is static).

Page 0 is reserved as a scratch page: idle decode lanes write there and
block-table rows default to it, so the fixed-shape decode program needs no
host-side compaction.
"""

from __future__ import annotations

import dataclasses
import hashlib
import math
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import TransformerConfig, _norm
from ...ops import apply_rope, rope_frequencies
from ...ops.ragged_paged_attention import ragged_paged_attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class PagedConfig:
    page_size: int = 64
    num_pages: int = 256          # pool size (page 0 reserved as scratch)
    max_pages_per_slot: int = 16  # static block-table width
    chunk_pages: int = 4          # prefill chunk = chunk_pages * page_size
    # Prefix/KV-cache reuse (PrefixCache): requests sharing a page-aligned
    # prompt prefix reuse its KV instead of re-prefilling. Off by default —
    # retired prompts then PIN their pages (cache holds a ref) until pool
    # pressure evicts them, which changes allocator-accounting invariants
    # tests and capacity planning may rely on.
    prefix_cache: bool = False
    prefix_cache_pages: int = 0   # max cached pages; 0 = pool-pressure only

    @property
    def chunk_tokens(self) -> int:
        return self.chunk_pages * self.page_size

    @property
    def max_slot_tokens(self) -> int:
        return self.max_pages_per_slot * self.page_size


def init_paged_cache(
    model: TransformerConfig, paged: PagedConfig
) -> Dict[str, jax.Array]:
    """One FLAT page pool across layers: layer i owns pages
    [i*num_pages, (i+1)*num_pages). Folding the layer axis into the page
    axis is what keeps every cache access O(pages touched): updates are
    provably-aliasing dynamic_update_slices and reads are single gathers
    driven by per-layer-offset block tables — no per-layer slab ever
    materializes. (A (L, ...) leading axis forces XLA to either scan-
    double-buffer or slice out ~pool/L per layer per step; measured 8x
    decode slowdown at 512 pages.)"""
    shape = (
        model.kv_heads,
        model.n_layers * paged.num_pages,
        paged.page_size,
        model.head_dim,
    )
    return {"k": jnp.zeros(shape, model.dtype), "v": jnp.zeros(shape, model.dtype)}


class PageAllocator:
    """Host-side REFCOUNTED free list over the page pool.

    Prefix caching means a physical page can back several block tables at
    once (N slots sharing a system prompt, plus the cache's own pin), so
    ownership is a count, not a set: `alloc` hands out pages at refcount 1,
    `share` adds a holder, and `free` drops one — the page returns to the
    free list only when the LAST holder lets go. Page 0 is the scratch
    page: never handed out, never refcounted, and `free`/`share` ignore it.
    """

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}
        self._lock = threading.Lock()

    def alloc(self, n: int) -> Optional[List[int]]:
        with self._lock:
            if len(self._free) < n:
                return None
            pages = [self._free.pop() for _ in range(n)]
            for p in pages:
                self._refs[p] = 1
            return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one holder to each page. Sharing a page that is not
        currently allocated is a caller bug and raises — silently
        resurrecting a freed page would corrupt whichever slot the free
        list hands it to next."""
        with self._lock:
            for p in pages:
                if p <= 0:
                    continue
                if p not in self._refs:
                    raise ValueError(f"share of unallocated page {p}")
                self._refs[p] += 1

    def free(self, pages: Sequence[int]) -> None:
        # Drop ONE holder per page. The double-free guard survives from the
        # pre-refcount allocator: a page with no live holders is ignored, so
        # a buggy caller can never put the same physical page on the free
        # list twice (which would hand it to two slots and corrupt both).
        with self._lock:
            for p in pages:
                if p > 0 and p in self._refs:
                    self._refs[p] -= 1
                    if self._refs[p] <= 0:
                        del self._refs[p]
                        self._free.append(p)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    @property
    def available(self) -> int:
        with self._lock:
            return len(self._free)


# ---------------------------------------------------------------- prefix cache


def _chain_hash(prev: bytes, chunk: Sequence[int]) -> bytes:
    """Collision-resistant chain hash of page-aligned token chunks.

    KV for a page is a pure function of every token up to the page's end
    (causal attention), so keying page p by H(H(...), tokens of page p)
    makes a hit sufficient for reuse. blake2b rather than python hash():
    a tuple-hash collision would silently splice one prompt's KV into
    another request."""
    h = hashlib.blake2b(prev, digest_size=16)
    h.update(np.asarray(chunk, dtype=np.int64).tobytes())
    return h.digest()


class PrefixCache:
    """Refcounted page-level prefix cache over the allocator.

    Maps the chain hash of each fully-prompt-covered page to the physical
    page holding its KV. The cache itself holds ONE reference per entry
    (the pin that keeps a finished request's prompt pages warm); every
    slot that reuses a page takes its own reference via `allocator.share`.
    Eviction (LRU, and only of pages whose sole holder is the cache) is
    driven by pool pressure: the engine calls `evict` when an alloc
    fails, so cached prefixes never starve admissions — but pages still
    referenced by live slots are pinned and survive the sweep.
    """

    def __init__(self, allocator: PageAllocator, page_size: int,
                 capacity_pages: int = 0):
        self.allocator = allocator
        self.page_size = page_size
        self.capacity_pages = capacity_pages  # 0 = bounded by pool pressure only
        self._entries: "OrderedDict[bytes, int]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def lookup(self, prompt: Sequence[int]) -> List[int]:
        """Longest cached page-aligned prefix of `prompt`, capped so at
        least one prompt token is always left to prefill (its logits seed
        sampling — vLLM caps its hit the same way). Matched pages get one
        reference taken FOR THE CALLER; the caller releases them through
        the normal refcounted free path when the slot retires."""
        ps = self.page_size
        max_reuse = max(0, (len(prompt) - 1) // ps)
        matched: List[int] = []
        digest = b""
        with self._lock:
            for p in range(max_reuse):
                digest = _chain_hash(digest, prompt[p * ps:(p + 1) * ps])
                page = self._entries.get(digest)
                if page is None:
                    break
                matched.append(page)
                self._entries.move_to_end(digest)
            self.hits += len(matched)
            self.misses += max_reuse - len(matched)
        if matched:
            self.allocator.share(matched)
        return matched

    def register(self, prompt: Sequence[int], pages: Sequence[int]) -> int:
        """Publish every page fully covered by `prompt` (KV already
        written by this slot's prefill). The cache takes its own reference
        per NEW entry; hashes already present keep their existing page.
        Returns the number of pages newly published."""
        ps = self.page_size
        full = len(prompt) // ps
        added = 0
        with self._lock:
            digest = b""
            for p in range(full):
                digest = _chain_hash(digest, prompt[p * ps:(p + 1) * ps])
                if digest in self._entries:
                    self._entries.move_to_end(digest)
                    continue
                if (
                    self.capacity_pages > 0
                    and len(self._entries) >= self.capacity_pages
                    and not self._evict_locked(1)
                ):
                    break
                page = pages[p]
                self.allocator.share([page])
                self._entries[digest] = page
                self._entries.move_to_end(digest)
                added += 1
        return added

    def evict(self, n: int) -> int:
        """Release up to n cache-pinned pages back toward the pool (LRU
        first, skipping pages live slots still hold)."""
        with self._lock:
            return self._evict_locked(n)

    def _evict_locked(self, n: int) -> int:
        dropped = 0
        for digest, page in list(self._entries.items()):
            if dropped >= n:
                break
            if self.allocator.refcount(page) != 1:
                continue  # pinned by a live slot: survives the sweep
            del self._entries[digest]
            self.allocator.free([page])
            self.evictions += 1
            dropped += 1
        return dropped

    def stats(self) -> Dict[str, float]:
        with self._lock:
            hits, misses = self.hits, self.misses
            return {
                "hits": float(hits),
                "misses": float(misses),
                "evictions": float(self.evictions),
                "pages": float(len(self._entries)),
                "hit_rate": hits / max(1, hits + misses),
            }

    def chain_heads(self, limit: int = 64) -> List[Dict[str, Any]]:
        """MRU-first view of the cached chain entries for engine
        introspection (`engine.snapshot()`): each row is one published
        page keyed by its blake2b chain-hash head, with its live
        refcount (1 = pinned only by the cache, >1 = shared by slots)."""
        with self._lock:
            rows = [
                {"digest": digest.hex(), "page": page}
                for digest, page in reversed(self._entries.items())
            ][:limit]
        for row in rows:
            row["refcount"] = self.allocator.refcount(row["page"])
        return rows


# ------------------------------------------------------------------ attention


def _gather_ref_attention(q, k_cache, v_cache, block_tables, lengths):
    """XLA reference paged attention. q (B, Hq, D); caches
    (Hkv, P, ps, D); block_tables (B, maxP); lengths (B,). Returns (B, Hq, D).
    Semantics ground truth for the Pallas kernel (and the CPU path)."""
    b, hq, d = q.shape
    hkv, _, ps, _ = k_cache.shape
    # (B, maxP, Hkv, ps, D) -> (B, Hkv, maxP*ps, D)
    k = jnp.swapaxes(k_cache[:, block_tables], 0, 1)
    v = jnp.swapaxes(v_cache[:, block_tables], 0, 1)
    k = k.reshape(b, hkv, -1, d)
    v = v.reshape(b, hkv, -1, d)
    if hq != hkv:
        k = jnp.repeat(k, hq // hkv, axis=1)
        v = jnp.repeat(v, hq // hkv, axis=1)
    logits = jnp.einsum(
        "bhd,bhkd->bhk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    mask = jnp.arange(k.shape[2])[None, :] < lengths[:, None]
    logits = jnp.where(mask[:, None, :], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhk,bhkd->bhd", probs.astype(v.dtype), v)


def paged_attention(q, k_cache, v_cache, block_tables, lengths, *, page_size: int,
                    use_kernel: Optional[bool] = None, mesh=None,
                    interpret: bool = False):
    """Decode-step paged attention: the q_len == 1 case of the ragged
    kernel. Dispatch: Pallas ragged kernel on TPU, gather reference
    elsewhere.

    The Mosaic lowering requires the trailing block dims be (8, 128)-
    divisible, so the kernel is only eligible for head_dim % 128 == 0 and
    page_size % 8 == 0 (e.g. Llama-class models); smaller shapes (tiny
    test configs, GPT-2's 64-dim heads) take the gather reference, which
    XLA fuses well at those sizes anyway.

    Tensor parallelism: the kernel path is `shard_map`-wrapped over the
    tp mesh axis inside `ragged_paged_attention` (GSPMD cannot partition
    a pallas_call, but both head axes divide by tp, so each shard runs
    the kernel on its local head group) — use_kernel=False is no longer
    forced under a mesh; pass `mesh` instead. The gather reference still
    partitions cleanly on the kv-head axis under plain GSPMD."""
    b, hq, head_dim = q.shape
    if use_kernel is None:
        use_kernel = (
            jax.default_backend() == "tpu"
            and head_dim % 128 == 0
            and page_size % 8 == 0
        )
    if use_kernel or interpret:
        block_q = 8
        # adapt (B, Hq, D) single-token lanes to the ragged layout: one
        # block_q-row region per lane, real row 0, q_len 1
        q_r = jnp.swapaxes(q, 0, 1)[:, :, None, :]  # (Hq, B, 1, D)
        q_r = jnp.pad(q_r, ((0, 0), (0, 0), (0, block_q - 1), (0, 0)))
        q_r = q_r.reshape(hq, b * block_q, head_dim)
        ones = jnp.ones((b,), jnp.int32)
        out = ragged_paged_attention(
            q_r, k_cache, v_cache,
            jnp.arange(b, dtype=jnp.int32), ones, ones, lengths,
            block_tables,
            block_q=block_q, max_q_blocks=1,
            use_kernel=True, interpret=interpret, mesh=mesh,
        )
        out = out.reshape(hq, b, block_q, head_dim)[:, :, 0, :]
        return jnp.swapaxes(out, 0, 1)  # (B, Hq, D)
    return _gather_ref_attention(q, k_cache, v_cache, block_tables, lengths)


# --------------------------------------------------------------- model passes


def batched_chunk_prefill_step(
    params: Params,
    cache: Dict[str, jax.Array],
    page_rows: jax.Array,       # (B, maxP) block tables of the batched slots
    chunk_page_ids: jax.Array,  # (B, chunk_pages) pages each chunk fills
    tokens: jax.Array,          # (B, C) chunks, right-padded
    offsets: jax.Array,         # (B,) tokens already ingested (page-aligned)
    total_lens: jax.Array,      # (B,) offset + real tokens this chunk
    config: TransformerConfig,
    *,
    page_size: int,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Ingest one prompt chunk for up to B slots in ONE device call —
    burst admissions prefill together instead of serializing TTFT
    (vLLM batches prefill chunks across sequences the same way;
    reference vllm_engine.py:254). Inactive lanes point their
    chunk_page_ids at the scratch page (0) with total_len 0: they burn
    lane FLOPs but write only garbage the attention masks off.

    Returns the LAST real token's logits per lane (B, V) — only the
    lanes finishing their prompt this tick sample from them.
    """
    c = config
    dt = c.dtype
    b, chunk = tokens.shape
    chunk_pages = chunk // page_size
    pos = offsets[:, None] + jnp.arange(chunk)[None, :]  # (B, C)
    x = params["wte"].astype(dt)[tokens]  # (B, C, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[jnp.clip(pos, 0, c.max_seq - 1)]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    flat_ids = chunk_page_ids.reshape(-1)  # (B*cp,) — scratch dups are fine

    # Unrolled layers over the FLAT page pool (see init_paged_cache):
    # page writes are per-page DUS (in place), reads gather only each
    # lane's tables shifted into the layer's page range.
    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // c.n_layers
    zero = jnp.int32(0)
    for i in range(c.n_layers):
        lp = {name: w[i] for name, w in params["blocks"].items()}
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            q = apply_rope(q, cos, sin, pos)
            k = apply_rope(k, cos, sin, pos)
        # whole-page in-place writes: one DUS per (lane, chunk page)
        kp = (
            k.transpose(1, 0, 2, 3)
            .reshape(k.shape[1], b * chunk_pages, page_size, k.shape[-1])
            .astype(c.dtype)
        )
        vp = (
            v.transpose(1, 0, 2, 3)
            .reshape(v.shape[1], b * chunk_pages, page_size, v.shape[-1])
            .astype(c.dtype)
        )
        layer_flat = flat_ids + i * num_pages
        for j in range(b * chunk_pages):
            start = (zero, layer_flat[j], zero, zero)
            k_full = jax.lax.dynamic_update_slice(k_full, kp[:, j][:, None], start)
            v_full = jax.lax.dynamic_update_slice(v_full, vp[:, j][:, None], start)
        # per-lane gathered attention over each slot's own pages
        layer_rows = page_rows + i * num_pages  # (B, maxP)
        keys = jnp.swapaxes(k_full[:, layer_rows], 0, 1)  # (B, Hkv, maxP, ps, D)
        vals = jnp.swapaxes(v_full[:, layer_rows], 0, 1)
        keys = keys.reshape(b, keys.shape[1], -1, keys.shape[-1])
        vals = vals.reshape(b, vals.shape[1], -1, vals.shape[-1])
        hq, hkv = q.shape[1], keys.shape[1]
        if hq != hkv:
            keys = jnp.repeat(keys, hq // hkv, axis=1)
            vals = jnp.repeat(vals, hq // hkv, axis=1)
        logits = jnp.einsum(
            "bhqd,bhkd->bhqk", q, keys, preferred_element_type=jnp.float32
        ) / math.sqrt(q.shape[-1])
        key_pos = jnp.arange(keys.shape[2])
        causal = key_pos[None, None, :] <= pos[:, :, None]       # (B, C, S)
        valid = key_pos[None, None, :] < total_lens[:, None, None]
        logits = jnp.where((causal & valid)[:, None], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        attn = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(vals.dtype), vals)
        out = jnp.einsum("bhsd,hde->bse", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            from ...ops import swiglu

            act = swiglu(jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt)), up)
        else:
            from ...ops import gelu

            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        x = x + down
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head")
    if head is None:
        head = params["wte"].T
    # vocab projection ONLY for each lane's last real token (B, E) @ (E, V)
    last = jnp.clip(total_lens - offsets - 1, 0, chunk - 1)
    x_last = x[jnp.arange(b), last]  # (B, E)
    logits = jnp.einsum("be,ev->bv", x_last, head.astype(dt))
    return logits, {"k": k_full, "v": v_full}


def ragged_mixed_step(
    params: Params,
    cache: Dict[str, jax.Array],
    page_rows: jax.Array,       # (P+B, maxP) tables: prefill lanes then decode
    chunk_page_ids: jax.Array,  # (P, cp) pages each prefill chunk fills
    prefill_tokens: jax.Array,  # (P, C) chunks, right-padded
    offsets: jax.Array,         # (P,) tokens already ingested (page-aligned)
    totals: jax.Array,          # (P,) offset + real tokens (0 = inactive)
    dec_tokens: jax.Array,      # (B,) or (B, Kd) decode input tokens
    dec_positions: jax.Array,   # (B,) decode write positions (first token)
    dec_active: jax.Array,      # (B,) int32 real tokens this tick (0..Kd)
    config: TransformerConfig,
    *,
    page_size: int,
    block_q: int = 8,
    use_kernel: Optional[bool] = None,
    mesh=None,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, Dict[str, jax.Array]]:
    """ONE device call for a mixed tick: P prefill chunks AND B decode
    lanes run through a single token-major transformer pass whose
    attention is one ragged-paged-attention launch per layer. This
    replaces the split batched_chunk_prefill_step + paged_decode_step
    dispatch: a tick with both kinds of work used to pay two compiled
    programs and two passes over the page pool.

    Token-major layout: T = P*C + B*R rows (R = ceil(Kd/block_q)*block_q).
    Prefill lane p owns rows [p*C, (p+1)*C) (C = chunk tokens, a multiple
    of block_q); decode lane b owns the R-row region at P*C + b*R with its
    dec_active[b] real tokens at rows 0.. — ONE token for plain decode,
    1 + drafts for a speculative verify round (the pending token plus the
    drafted continuation, scored causally in the same launch exactly like
    a prefill chunk). The ragged descriptor (q_lens = chunk fill / count /
    0, kv_lens = totals / position+count / 0) masks everything else off,
    so inactive lanes and pad rows burn FLOPs but write only to the
    scratch page (a pad row near capacity must NOT clamp its page-table
    gather onto the lane's own live page — it is explicitly routed to
    page 0).

    Returns (prefill last-token logits (P, V), decode logits — (B, V) for
    1-D dec_tokens, else (B, Kd, V) with row j scoring the token after
    input row j — and the updated cache).
    """
    c = config
    dt = c.dtype
    p_lanes, chunk = prefill_tokens.shape
    squeeze_dec = dec_tokens.ndim == 1
    if squeeze_dec:
        dec_tokens = dec_tokens[:, None]
    b_lanes, dec_width = dec_tokens.shape
    chunk_pages = chunk // page_size
    if chunk % block_q:
        raise ValueError(f"chunk tokens ({chunk}) must divide by block_q "
                         f"({block_q})")
    dec_blocks = -(-dec_width // block_q)
    dec_region = dec_blocks * block_q  # rows per decode lane
    dec_counts = dec_active.astype(jnp.int32)
    t_tokens = p_lanes * chunk + b_lanes * dec_region

    # ---- token-major embedding -------------------------------------------
    pre_pos = offsets[:, None] + jnp.arange(chunk)[None, :]     # (P, C)
    dec_pos_grid = dec_positions[:, None] + jnp.arange(dec_width)[None, :]
    dec_region_pos = jnp.zeros((b_lanes, dec_region), jnp.int32).at[
        :, :dec_width
    ].set(dec_pos_grid)
    positions = jnp.concatenate(
        [pre_pos.reshape(-1), dec_region_pos.reshape(-1)]
    )  # (T,)
    dec_region_tok = jnp.zeros((b_lanes, dec_region), jnp.int32).at[
        :, :dec_width
    ].set(dec_tokens)
    tokens = jnp.concatenate(
        [prefill_tokens.reshape(-1), dec_region_tok.reshape(-1)]
    )  # (T,)
    x = params["wte"].astype(dt)[tokens]  # (T, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[jnp.clip(positions, 0, c.max_seq - 1)]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    # ---- ragged descriptor (static regions, dynamic lengths) -------------
    cb = chunk // block_q
    starts = jnp.concatenate([
        jnp.arange(p_lanes, dtype=jnp.int32) * cb,
        p_lanes * cb + jnp.arange(b_lanes, dtype=jnp.int32) * dec_blocks,
    ])
    counts = jnp.concatenate([
        jnp.full((p_lanes,), cb, jnp.int32),
        jnp.full((b_lanes,), dec_blocks, jnp.int32),
    ])
    q_lens = jnp.concatenate([
        (totals - offsets).astype(jnp.int32),
        dec_counts,
    ])
    kv_lens = jnp.concatenate([
        totals.astype(jnp.int32),
        (dec_positions + dec_counts) * (dec_counts > 0),
    ])

    flat_ids = chunk_page_ids.reshape(-1)                 # (P*cp,)
    # per-(lane, token) page/row targets: token j of lane b lands at
    # position dec_positions[b] + j. Rows past dec_counts[b] (pad rows,
    # shrunken verify rounds) go to the scratch page — the gather index
    # is clamped so a lane near max_pages can't wrap, and the page is
    # forced to 0 so a clamped gather can't alias the lane's live KV.
    maxp = page_rows.shape[1]
    valid_tok = jnp.arange(dec_width)[None, :] < dec_counts[:, None]
    page_idx = jnp.clip(dec_pos_grid // page_size, 0, maxp - 1)
    gathered = page_rows[p_lanes + jnp.arange(b_lanes)[:, None], page_idx]
    dec_pages = jnp.where(valid_tok, gathered, 0)          # (B, Kd)
    dec_rows = jnp.where(valid_tok, dec_pos_grid % page_size, 0)

    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // c.n_layers
    zero = jnp.int32(0)
    for i in range(c.n_layers):
        lp = {name: w[i] for name, w in params["blocks"].items()}
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        # heads-leading token-major projections: (T, E) @ (E, H, D) -> (H, T, D)
        q = jnp.einsum("te,ehd->htd", h, lp["wq"].astype(dt))
        k = jnp.einsum("te,ehd->htd", h, lp["wk"].astype(dt))
        v = jnp.einsum("te,ehd->htd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[:, None, :]
            k = k + lp["bk"].astype(dt)[:, None, :]
            v = v + lp["bv"].astype(dt)[:, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            q = apply_rope(q[None], cos, sin, positions[None])[0]
            k = apply_rope(k[None], cos, sin, positions[None])[0]
        # prefill KV: whole-page DUS per (lane, chunk page), as in
        # batched_chunk_prefill_step
        layer_flat = flat_ids + i * num_pages
        kp = (
            k[:, : p_lanes * chunk]
            .reshape(k.shape[0], p_lanes * chunk_pages, page_size, k.shape[-1])
            .astype(c.dtype)
        )
        vp = (
            v[:, : p_lanes * chunk]
            .reshape(v.shape[0], p_lanes * chunk_pages, page_size, v.shape[-1])
            .astype(c.dtype)
        )
        for j in range(p_lanes * chunk_pages):
            start = (zero, layer_flat[j], zero, zero)
            k_full = jax.lax.dynamic_update_slice(k_full, kp[:, j][:, None], start)
            v_full = jax.lax.dynamic_update_slice(v_full, vp[:, j][:, None], start)
        # decode KV: per-(lane, token) row DUS at (page, row), as in
        # paged_decode_step; 2*B*Kd DUS per layer (Kd=1 for plain decode)
        for lane in range(b_lanes):
            for j in range(dec_width):
                row_idx = p_lanes * chunk + lane * dec_region + j
                upd_k = k[:, row_idx].astype(c.dtype)[:, None, None, :]
                upd_v = v[:, row_idx].astype(c.dtype)[:, None, None, :]
                start = (zero, dec_pages[lane, j] + i * num_pages,
                         dec_rows[lane, j], zero)
                k_full = jax.lax.dynamic_update_slice(k_full, upd_k, start)
                v_full = jax.lax.dynamic_update_slice(v_full, upd_v, start)
        # ONE ragged attention launch for every lane, prefill and decode
        attn = ragged_paged_attention(
            q, k_full, v_full, starts, counts, q_lens, kv_lens,
            page_rows + i * num_pages,
            block_q=block_q, max_q_blocks=max(cb, dec_blocks),
            use_kernel=use_kernel, mesh=mesh, interpret=interpret,
        )  # (Hq, T, D)
        out = jnp.einsum("htd,hde->te", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("te,ef->tf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            from ...ops import swiglu

            act = swiglu(jnp.einsum("te,ef->tf", h, lp["w_gate"].astype(dt)), up)
        else:
            from ...ops import gelu

            act = gelu(up)
        down = jnp.einsum("tf,fe->te", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        x = x + down
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head")
    if head is None:
        head = params["wte"].T
    # vocab projection ONLY for sample rows: each prefill lane's last real
    # token and each decode lane's Kd token rows (all of them — a verify
    # round needs every row's logits to score the drafted continuation)
    last = jnp.clip(totals - offsets - 1, 0, chunk - 1)
    pre_rows = jnp.arange(p_lanes) * chunk + last
    dec_rows_x = (
        p_lanes * chunk
        + (jnp.arange(b_lanes) * dec_region)[:, None]
        + jnp.arange(dec_width)[None, :]
    ).reshape(-1)
    x_sample = x[jnp.concatenate([pre_rows, dec_rows_x])]  # (P+B*Kd, E)
    logits = jnp.einsum("be,ev->bv", x_sample, head.astype(dt))
    dec_logits = logits[p_lanes:].reshape(b_lanes, dec_width, -1)
    if squeeze_dec:
        dec_logits = dec_logits[:, 0]
    return logits[:p_lanes], dec_logits, {"k": k_full, "v": v_full}


def copy_page(
    cache: Dict[str, jax.Array], src: jax.Array, dst: jax.Array,
    *, n_layers: int,
) -> Dict[str, jax.Array]:
    """Copy one logical page (every layer's stripe) src -> dst in the flat
    pool: the device half of copy-on-write divergence. Layer i's stripe
    lives at page + i*num_pages (see init_paged_cache)."""
    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // n_layers
    zero = jnp.int32(0)
    for i in range(n_layers):
        s = src + i * num_pages
        d = dst + i * num_pages
        k_pg = jax.lax.dynamic_slice(
            k_full, (zero, s, zero, zero),
            (k_full.shape[0], 1, k_full.shape[2], k_full.shape[3]),
        )
        v_pg = jax.lax.dynamic_slice(
            v_full, (zero, s, zero, zero),
            (v_full.shape[0], 1, v_full.shape[2], v_full.shape[3]),
        )
        k_full = jax.lax.dynamic_update_slice(k_full, k_pg, (zero, d, zero, zero))
        v_full = jax.lax.dynamic_update_slice(v_full, v_pg, (zero, d, zero, zero))
    return {"k": k_full, "v": v_full}


def paged_decode_step(
    params: Params,
    cache: Dict[str, jax.Array],
    block_tables: jax.Array,  # (B, maxP) int32
    tokens: jax.Array,        # (B,) int32
    positions: jax.Array,     # (B,) int32 — write slot; length = position + 1
    config: TransformerConfig,
    *,
    page_size: int,
    use_kernel: Optional[bool] = None,
    mesh=None,
    interpret: bool = False,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """One continuous-batching decode step over the paged cache."""
    c = config
    dt = c.dtype
    b = tokens.shape[0]
    x = params["wte"].astype(dt)[tokens][:, None, :]  # (B, 1, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[positions][:, None, :]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    lengths = positions + 1
    page_ids = block_tables[jnp.arange(b), positions // page_size]  # (B,)
    rows = positions % page_size  # (B,)

    # Layers are UNROLLED (python loop) over the FLAT page pool (see
    # init_paged_cache): per-layer block tables are the slot's tables
    # shifted into layer i's page range, updates are per-lane DUS (in
    # place on the donated pool), reads gather only the table's pages.
    k_full, v_full = cache["k"], cache["v"]
    num_pages = k_full.shape[1] // c.n_layers
    for i in range(c.n_layers):
        lp = {name: w[i] for name, w in params["blocks"].items()}
        layer_tables = block_tables + i * num_pages
        layer_pages = page_ids + i * num_pages
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            pos2d = positions[:, None]
            q = apply_rope(q, cos, sin, pos2d)
            k = apply_rope(k, cos, sin, pos2d)
        # Write this token's K/V into each slot's current page/row with
        # per-lane dynamic_update_slice — the canonical in-place KV-cache
        # update (a scatter over mixed indices lowers to a transposing
        # scatter that copies pool-sized buffers; DUS provably aliases).
        # Cost model: 2*B DUS ops per (unrolled) layer, so trace/compile
        # time scales with B*L — paid once per batch bucket at engine
        # precompile, never per request. Worth it: execution went 762ms ->
        # 52ms per 24-step block at a 1.2GB pool on v5e.
        newk = k[:, :, 0, :].astype(c.dtype)  # (B, Hkv, D)
        newv = v[:, :, 0, :].astype(c.dtype)
        zero = jnp.int32(0)
        for lane in range(b):
            upd_k = newk[lane][:, None, None, :]  # (Hkv, 1, 1, D)
            upd_v = newv[lane][:, None, None, :]
            start = (zero, layer_pages[lane], rows[lane], zero)
            k_full = jax.lax.dynamic_update_slice(k_full, upd_k, start)
            v_full = jax.lax.dynamic_update_slice(v_full, upd_v, start)
        attn = paged_attention(
            q[:, :, 0, :], k_full, v_full, layer_tables, lengths,
            page_size=page_size, use_kernel=use_kernel, mesh=mesh,
            interpret=interpret,
        )[:, :, None, :]
        out = jnp.einsum("bhsd,hde->bse", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            from ...ops import swiglu

            gate = jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt))
            act = swiglu(gate, up)
        else:
            from ...ops import gelu

            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        x = x + down
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head")
    if head is None:
        head = params["wte"].T
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(dt))[:, 0]
    return logits, {"k": k_full, "v": v_full}


def chunk_prefill_step(
    params: Params,
    cache: Dict[str, jax.Array],
    page_row: jax.Array,      # (maxP,) this slot's block table
    chunk_page_ids: jax.Array,  # (chunk_pages,) pages this chunk fills
    tokens: jax.Array,        # (1, C) the chunk, right-padded
    offset: jax.Array,        # () int32 — tokens already ingested (page-aligned)
    total_len: jax.Array,     # () int32 — offset + real tokens in this chunk
    config: TransformerConfig,
    *,
    page_size: int,
) -> Tuple[jax.Array, Dict[str, jax.Array]]:
    """Single-slot chunk prefill: the B=1 case of
    batched_chunk_prefill_step (kept as the documented one-slot API).
    Returns the last real token's logits (1, V) and the updated pool."""
    return batched_chunk_prefill_step(
        params,
        cache,
        page_row[None],
        chunk_page_ids[None],
        tokens,
        jnp.reshape(offset, (1,)),
        jnp.reshape(total_len, (1,)),
        config,
        page_size=page_size,
    )
