"""Paged continuous-batching engine: vLLM-class serving, TPU-native.

Reference parity: the vLLM engine the reference rides
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:254 — paged KV, chunked prefill, continuous batching).
TPU inversion (the ragged-paged-attention recipe from PAPERS.md):

- HBM holds one fixed PAGE POOL shared by all slots (paged.py); a slot's
  KV occupancy scales with its actual tokens, not max_seq — like vLLM,
  unlike the dense engine's (L, max_slots, H, max_seq, D) grid.
- Prefill is CHUNKED and interleaved: each engine tick runs at most one
  prompt chunk plus one decode block, so a long prompt delays running
  streams by one chunk's latency, never by its full length.
- Decode runs in BLOCKS of K fused decode+sample steps per device call
  (lax.scan), with sampled tokens staying ON DEVICE between blocks and
  results fetched through an async pipeline one block deep. The host
  never blocks on a device read in the dispatch path — essential both on
  real TPU (host reads stall the device pipeline) and on tunneled chips
  (a synchronous read costs a full network round trip per token).
- Backpressure is physical: admission, prefill growth, and the K-step
  lookahead all wait on the page allocator; finished slots return pages.

Retirement (EOS / budget) is detected at emission, up to one block after
the fact; blocks already in flight for a retired slot write only into
pages that are either still owned or provably overwritten before they
become visible (pages fill strictly forward from row 0 and attention
masks rows beyond a slot's length), so late retirement never corrupts a
neighbor.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import TransformerConfig
from .. import reqlog
from ..tenancy import FairQueue
from .engine import (
    ResponseStream,
    _Request,
    _charge_wait,
    _check_admission,
    _fail_all_requests,
    _finish_request_span,
    _hit_stop_sequence,
    _normalize_stop_sequences,
    _observe_tenant_ttft,
    _observe_tick,
    _register_engine_metrics,
    _reject_if_dead,
    _start_request_span,
    _tick_cost,
    _timeout_request,
)
from .paged import (
    PagedConfig,
    PageAllocator,
    PrefixCache,
    batched_chunk_prefill_step,
    copy_page,
    init_paged_cache,
    paged_decode_step,
    ragged_mixed_step,
)
from .speculative import NgramProposer, accept_speculative, filtered_scores


@dataclasses.dataclass
class PagedEngineConfig:
    max_slots: int = 8
    eos_id: int = -1
    decode_block_steps: int = 16  # K: fused decode+sample steps per dispatch
    max_inflight_blocks: int = 8  # device blocks outstanding before gating
    # admission bound on the submit queue: overflow raises a typed
    # BackPressureError instead of queueing unboundedly. 0 = auto
    # (8 x max_slots); negative disables the bound.
    max_queued_requests: int = 0
    # Compile every prefill bucket + both decode variants at construction
    # (vLLM pre-captures its batch-size graphs the same way). Off by
    # default: tests build many engines; serving/bench wants it on so the
    # first burst never pays a 20-40s XLA compile mid-request.
    precompile: bool = False
    # Speculative decoding: tokens drafted per verify round. None reads
    # the cfg.serve_speculative_tokens flag; 0 disables. When enabled the
    # decode path becomes draft-and-verify: each ready lane's pending
    # token plus up to this many drafts are scored in ONE ragged launch
    # (a q_len=K region, exactly a prefill chunk's shape), with exact
    # greedy acceptance at temperature 0 and exact rejection sampling
    # otherwise, and page rollback on rejection.
    speculative_tokens: Optional[int] = None
    speculative_ngram: int = 3  # default proposer's max n-gram
    # Optional DraftProposer (speculative.py protocol); None = n-gram
    # prompt-lookup self-drafting.
    speculative_proposer: Optional[Any] = None
    paged: PagedConfig = dataclasses.field(default_factory=PagedConfig)


# ------------------------------------------------------- jittable components
# Module-level builders so the TP AOT test can lower the exact programs the
# engine runs (at Llama-3-8B shapes) without instantiating an engine.


def _sample_plain(logits, key, temps):
    """temperature-only / greedy sampling — the common fast path."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


def _sample_filtered(logits, key, temps, top_ks, top_ps):
    """Per-lane temperature + top-k + top-p (nucleus) sampling —
    vLLM SamplingParams parity. The filtering body lives in
    speculative.filtered_scores (the verify step scores drafts against
    the SAME filtered distribution, which is what makes speculative
    output exactly match plain sampling)."""
    greedy = jnp.argmax(logits, axis=-1)
    final = filtered_scores(logits, temps, top_ks, top_ps)
    sampled = jax.random.categorical(key, final, axis=-1)
    return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)


def build_decode_block(mc: TransformerConfig, page_size: int, K: int,
                       sample_fn, use_kernel=None, mesh=None):
    """K fused decode+sample steps; tokens never leave the device.
    Output row 0 is the INPUT token vector — a freshly prefilled
    lane's first sampled token rides along with its first block,
    so it never needs a fetch of its own (every materialization
    costs a full round trip on tunneled TPUs). Two variants are
    compiled: plain (temperature only — no per-step vocab sort)
    and filtered (top-k/top-p); the dispatcher picks per block."""

    def _decode_block(params, cache, block_tables, tokens, positions,
                      key, temps, *filters):
        def body(carry, _):
            cache, toks_c, pos_c, key_c = carry
            logits, cache = paged_decode_step(
                params, cache, block_tables, toks_c, pos_c, mc,
                page_size=page_size, use_kernel=use_kernel, mesh=mesh,
            )
            key_c, sub = jax.random.split(key_c)
            nxt = sample_fn(logits, sub, temps, *filters)
            return (cache, nxt, pos_c + 1, key_c), nxt

        (cache, final, _, _), toks = jax.lax.scan(
            body, (cache, tokens, positions, key), None, length=K
        )
        toks = jnp.concatenate([tokens[None], toks], axis=0)  # (K+1, B)
        return toks, final, cache

    return _decode_block


def build_batched_chunk_fn(mc: TransformerConfig, page_size: int):
    def _batched_chunk(params, cache, page_rows, chunk_page_ids, tokens,
                       offsets, totals):
        return batched_chunk_prefill_step(
            params, cache, page_rows, chunk_page_ids, tokens, offsets, totals,
            mc, page_size=page_size,
        )

    return _batched_chunk


def mixed_block_q(chunk_tokens: int) -> int:
    """Ragged q-block size for a given prefill chunk length: 8 (the
    Mosaic-tileable size the kernel wants) whenever the chunk divides by
    it, else the largest power of two that does (tiny test configs — the
    XLA reference path handles any block_q)."""
    bq = 8
    while chunk_tokens % bq:
        bq //= 2
    return max(bq, 1)


def build_mixed_step(mc: TransformerConfig, page_size: int,
                     use_kernel=None, mesh=None, block_q: int = 8):
    """The single mixed tick: P prefill chunks + B decode lanes through
    one ragged-paged-attention program (replaces the split
    build_batched_chunk_fn + per-step decode dispatch for ticks that have
    prefill work; the K-step fused decode block remains the decode-only
    steady state)."""

    def _mixed(params, cache, page_rows, chunk_page_ids, tokens,
               offsets, totals, dec_tokens, dec_positions, dec_active):
        return ragged_mixed_step(
            params, cache, page_rows, chunk_page_ids, tokens, offsets,
            totals, dec_tokens, dec_positions, dec_active, mc,
            page_size=page_size, block_q=block_q, use_kernel=use_kernel,
            mesh=mesh,
        )

    return _mixed


def serving_shardings(model_config: TransformerConfig, mesh, rules=None):
    """(param shardings, KV-pool sharding, replicated) for TP serving.

    Reference parity: the reference serves TP via vLLM workers in a
    placement group (/root/reference/python/ray/llm/_internal/serve/
    deployments/llm/vllm/vllm_models.py:124 — one process per GPU,
    NCCL all-reduce per layer). TPU inversion: ONE program over a mesh;
    the same rule table train uses (Megatron split on heads/mlp/vocab)
    annotates the params and the page pool shards on the kv-head axis,
    and XLA inserts the collectives over ICI.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    from ...models.transformer import logical_axes
    from ...parallel import default_rules
    from ...parallel.sharding import tree_specs

    tp = mesh.shape.get("tp", 1)
    if model_config.kv_heads % tp or model_config.n_heads % tp:
        raise ValueError(
            f"tp={tp} must divide kv_heads ({model_config.kv_heads}) and "
            f"n_heads ({model_config.n_heads})"
        )
    specs = tree_specs(logical_axes(model_config), rules or default_rules())
    param_sh = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    # flat pool layout (Hkv, L*P, ps, D): kv heads lead
    kv_spec = NamedSharding(
        mesh, PartitionSpec("tp", None, None, None)
    )
    cache_sh = {"k": kv_spec, "v": kv_spec}
    replicated = NamedSharding(mesh, PartitionSpec())
    return param_sh, cache_sh, replicated


@dataclasses.dataclass
class _PagedSlot:
    request: Optional[_Request] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    position: int = 0          # next KV write index at DISPATCH time
    prefill_offset: int = 0    # prompt tokens already ingested
    stalled: bool = False      # waiting on a page
    # dispatch-side generation bookkeeping
    dispatch_remaining: int = 0
    done_dispatching: bool = False
    blocks_in_flight: int = 0
    awaiting_first: bool = False  # first token rides the next block's row 0
    # emission-side bookkeeping
    emit_remaining: int = 0
    finished_emit: bool = False
    # speculative decoding: the host-side token context the proposer
    # drafts from (prompt + every emitted token; seeded by the "first"
    # fetch), and the one-round-in-flight latch — a lane never has two
    # verify rounds outstanding, so rollback math stays race-free.
    spec_ctx: Optional[List[int]] = None
    spec_inflight: bool = False
    # lane preemption: a marked lane stops dispatching new blocks and is
    # parked (trimmed to its emitted frontier) once its in-flight blocks
    # drain — an actively pipelined lane is never quiescent at mark time
    preempt_pending: bool = False
    # observability: admit wall time, so the per-request engine.prefill
    # span covers chunked ingest end to end (chunks batch across lanes)
    prefill_t0: float = 0.0

    @property
    def free(self) -> bool:
        return self.request is None

    @property
    def prefilling(self) -> bool:
        return (
            self.request is not None
            and self.prefill_offset < len(self.request.prompt)
        )

    @property
    def decodable(self) -> bool:
        return (
            self.request is not None
            and not self.prefilling
            and not self.done_dispatching
            and not self.preempt_pending
            and self.dispatch_remaining > 0
        )


class PagedLLMEngine:
    """Continuous batching over a paged KV pool with chunked prefill and
    pipelined block decoding."""

    def __init__(
        self,
        model_config: TransformerConfig,
        params: Any,
        engine_config: Optional[PagedEngineConfig] = None,
        mesh: Any = None,
    ):
        """mesh: optional jax.sharding.Mesh with a 'tp' axis — params and
        the KV page pool shard across it (serving_shardings) and every
        prefill/decode program runs SPMD over the mesh. Host-side state
        (slots, block tables, allocator) is unchanged: page tables are
        replicated, exactly like vLLM's TP workers sharing one scheduler."""
        self.model_config = model_config
        self.params = params
        self.mesh = mesh
        self.config = engine_config or PagedEngineConfig()
        pc = self.config.paged
        if pc.max_pages_per_slot % pc.chunk_pages:
            raise ValueError(
                f"max_pages_per_slot ({pc.max_pages_per_slot}) must be a "
                f"multiple of chunk_pages ({pc.chunk_pages}): prefill grows "
                "page tables chunk-aligned"
            )
        if pc.chunk_pages > pc.num_pages - 1:
            raise ValueError(
                f"chunk_pages ({pc.chunk_pages}) exceeds the pool "
                f"({pc.num_pages - 1} allocatable pages)"
            )
        self.paged = pc
        self.cache = init_paged_cache(model_config, pc)
        self.allocator = PageAllocator(pc.num_pages)
        self.slots = [_PagedSlot() for _ in range(self.config.max_slots)]
        self.block_tables = np.zeros(
            (self.config.max_slots, pc.max_pages_per_slot), dtype=np.int32
        )
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._rid = itertools.count()
        self._stop = threading.Event()
        self._wake = threading.Event()
        # Device→host results flow through a dedicated DRAIN THREAD: on
        # tunneled TPUs a host read costs a full network round trip that
        # copy_to_host_async does not hide, so the blocking np.asarray
        # must never run on the dispatch thread. Entries:
        #   ("first", (slot, request), (1,) arr)
        #   ("block", [(slot, request), ...], (K, B) arr)
        self._fetchq: "queue.Queue[Optional[Tuple[str, Any, jax.Array]]]" = queue.Queue()
        self._doneq: "queue.Queue[Tuple[str, Any, Any]]" = queue.Queue()
        self._inflight = 0  # fetch entries not yet emitted
        self.drain_log: List[Tuple[int, float]] = []  # (batch_size, seconds)

        mc = model_config
        ps = pc.page_size
        K = self.config.decode_block_steps

        def _scatter_tokens(tokens, lane_slots, sampled):
            """Thread freshly sampled first tokens into the engine token
            vector: lane_slots maps each batched-prefill lane to its slot
            index, with non-finishing lanes pointing past the end (their
            garbage samples drop)."""
            return tokens.at[lane_slots].set(sampled, mode="drop")

        def _take(tokens, idx):
            return tokens[idx][None]

        def _merge_tokens(old, new, mask):
            """Merge a decode block's final sampled tokens back into the
            engine token vector ONLY for lanes that were dispatched in
            that block. Excluded lanes (page-stalled mid-decode, still
            prefilling) keep their pending input token — the block
            sampled garbage for them (attention over the scratch page)
            and writing it back would silently corrupt their stream when
            they unstall."""
            return jnp.where(mask, new, old)

        def _dec_pack(old, new, mask):
            """Pack a mixed tick's decode samples for fetch + carry: row 0
            is the tick's INPUT tokens (a fresh lane's first sampled token
            rides there, like a decode block's row 0), row 1 the per-lane
            merged output (non-dispatched lanes keep their pending token —
            same invariant as _merge_tokens)."""
            merged = jnp.where(mask, new, old)
            return jnp.stack([old, merged]), merged

        # Kernel dispatch: auto (None) selects the Pallas ragged kernel on
        # TPU at tileable shapes and the XLA schedule-replay reference
        # elsewhere. Under a TP mesh the kernel call is shard_map-wrapped
        # over the tp axis inside ragged_paged_attention, so a mesh no
        # longer forces the gather fallback (the old `use_kernel = False
        # if tp_active` pessimization).
        from ...core.config import cfg

        use_kernel = None if cfg.serve_ragged_kernel else False
        spec = self.config.speculative_tokens
        if spec is None:
            spec = int(cfg.serve_speculative_tokens)
        self.spec_tokens = max(0, int(spec))
        # verify width: the pending token + the drafts (row 0 of a verify
        # region re-scores the token whose KV write was deferred)
        self._spec_width = self.spec_tokens + 1
        self._proposer = None
        if self.spec_tokens:
            self._proposer = (
                self.config.speculative_proposer
                or NgramProposer(self.config.speculative_ngram)
            )
        bq = mixed_block_q(pc.chunk_tokens)
        self._block_q = bq
        dec_plain = build_decode_block(mc, ps, K, _sample_plain, use_kernel,
                                       mesh=mesh)
        dec_filtered = build_decode_block(mc, ps, K, _sample_filtered,
                                          use_kernel, mesh=mesh)
        mixed = build_mixed_step(mc, ps, use_kernel, mesh, block_q=bq)
        _copy = lambda cache, s, d: copy_page(cache, s, d, n_layers=mc.n_layers)  # noqa: E731
        if mesh is not None:
            param_sh, cache_sh, rep = serving_shardings(mc, mesh)
            self.params = jax.device_put(params, param_sh)
            self.cache = jax.device_put(self.cache, cache_sh)
            common_in = (param_sh, cache_sh, rep, rep, rep, rep, rep)
            self._decode_block_plain = jax.jit(
                dec_plain, donate_argnums=(1,),
                in_shardings=common_in, out_shardings=(rep, rep, cache_sh),
            )
            self._decode_block_filtered = jax.jit(
                dec_filtered, donate_argnums=(1,),
                in_shardings=common_in + (rep, rep),
                out_shardings=(rep, rep, cache_sh),
            )
            self._mixed = jax.jit(
                mixed, donate_argnums=(1,),
                in_shardings=(param_sh, cache_sh) + (rep,) * 8,
                out_shardings=(rep, rep, cache_sh),
            )
            self._copy_page = jax.jit(
                _copy, donate_argnums=(0,),
                in_shardings=(cache_sh, rep, rep), out_shardings=cache_sh,
            )
            self._tokens_dev = jax.device_put(
                jnp.zeros((self.config.max_slots,), jnp.int32), rep
            )
        else:
            self._decode_block_plain = jax.jit(dec_plain, donate_argnums=(1,))
            self._decode_block_filtered = jax.jit(dec_filtered, donate_argnums=(1,))
            self._mixed = jax.jit(mixed, donate_argnums=(1,))
            self._copy_page = jax.jit(_copy, donate_argnums=(0,))
            self._tokens_dev = jnp.zeros((self.config.max_slots,), jnp.int32)
        def _spec_accept_pack(dec_logits, toks, counts, key, temps, tks, tps):
            """Accept/resample a verify round and pack the result for ONE
            small fetch: columns [:W] the emit-ordered tokens, column W the
            per-lane emitted count. Logits never cross to the host."""
            out, n = accept_speculative(
                dec_logits, toks, counts, key, temps, tks, tps
            )
            return jnp.concatenate([out, n[:, None]], axis=1)

        self._sample = jax.jit(_sample_filtered)
        self._spec_accept = jax.jit(_spec_accept_pack)
        self._scatter_tokens = jax.jit(_scatter_tokens, donate_argnums=(0,))
        self._take = jax.jit(_take)
        self._merge_tokens = jax.jit(_merge_tokens, donate_argnums=(0,))
        self._dec_pack = jax.jit(_dec_pack)
        self._key = jax.random.PRNGKey(0)
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.allocator, ps, pc.prefix_cache_pages)
            if pc.prefix_cache else None
        )
        # weighted-fair admit queue (replaces the old FIFO pending deque):
        # raw submits drain into per-(priority, tenant) SCFQ lanes; pops
        # come out in virtual-time order. Deferred admissions (page
        # stalls) and preempted lanes re-enter at the front of their lane
        # without a fresh virtual-time charge.
        self._fair = FairQueue()
        self.metrics: Dict[str, float] = {
            "generated_tokens": 0.0,
            "decode_steps": 0.0,
            "decode_blocks": 0.0,
            "prefill_chunks": 0.0,
            "ongoing": 0.0,
            "page_stalls": 0.0,
            "pages_in_use": 0.0,
            "shed": 0.0,
            "timeouts": 0.0,
            # batch-occupancy accounting (engine.py gauge registry)
            "batch_fill": 0.0,
            "tick_seconds": 0.0,
            "prefill_tokens": 0.0,
            "decode_tokens": 0.0,
            # prefix-cache counters (engine.py gauge registry mirrors
            # these as raytpu_engine_prefix_cache_*); zero when disabled
            "prefix_cache_hits": 0.0,
            "prefix_cache_misses": 0.0,
            "prefix_cache_evictions": 0.0,
            "prefix_cache_pages": 0.0,
            "prefix_cache_hit_rate": 0.0,
            "prefix_cache_cow": 0.0,
            "mixed_ticks": 0.0,
            # speculative-decoding counters (engine.py gauge registry
            # mirrors these as raytpu_engine_spec_*); zero when disabled
            "spec_proposed": 0.0,
            "spec_accepted": 0.0,
            "spec_acceptance_rate": 0.0,
            "spec_rollback_pages": 0.0,
            # lane-preemption counters (multi-tenant overload protection)
            "lane_preemptions": 0.0,
            "lane_resumes": 0.0,
            "preempted_pages": 0.0,
        }
        self._tick_cost = None  # decode-block cost, set at first dispatch
        self.metrics_label = _register_engine_metrics(self, "paged")
        if self.config.precompile:
            self._precompile()
        self._drainer = threading.Thread(
            target=self._drain_worker, daemon=True, name="paged-llm-drain"
        )
        self._drainer.start()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="paged-llm-engine"
        )
        self._thread.start()

    def _precompile(self) -> None:
        """Trigger every XLA compile the serving loop can hit — each
        prefill bucket (1, 2, 4, ..., max_slots lanes) and both decode
        variants — with all-inactive inputs whose writes land only in the
        scratch page. Runs BEFORE the engine threads start, so no request
        ever pays a compile. Donated caches rebind as in the live loop."""
        pc = self.paged
        ms = self.config.max_slots
        ct, cp = pc.chunk_tokens, pc.chunk_pages
        spec = self.spec_tokens > 0
        dec_toks = (
            jnp.zeros((ms, self._spec_width), jnp.int32)
            if spec else self._tokens_dev
        )
        b = 1
        while True:
            logits, dec_logits, self.cache = self._mixed(
                self.params,
                self.cache,
                jnp.zeros((b + ms, pc.max_pages_per_slot), jnp.int32),
                jnp.zeros((b, cp), jnp.int32),     # scratch page only
                jnp.zeros((b, ct), jnp.int32),
                jnp.zeros((b,), jnp.int32),
                jnp.zeros((b,), jnp.int32),        # totals 0: inactive
                dec_toks,
                jnp.zeros((ms,), jnp.int32),
                jnp.zeros((ms,), jnp.int32),       # no decode ride-alongs
            )
            self._key, sub = jax.random.split(self._key)
            self._sample(
                logits, sub, jnp.zeros((b,), jnp.float32),
                jnp.zeros((b,), jnp.int32), jnp.ones((b,), jnp.float32),
            )
            if b == 1:
                self._key, sub = jax.random.split(self._key)
                if spec:
                    self._spec_accept(
                        dec_logits, dec_toks, jnp.zeros((ms,), jnp.int32),
                        sub, jnp.zeros((ms,), jnp.float32),
                        jnp.zeros((ms,), jnp.int32),
                        jnp.ones((ms,), jnp.float32),
                    )
                    self._take(self._tokens_dev, 0)  # every first token
                else:
                    self._sample(
                        dec_logits, sub, jnp.zeros((ms,), jnp.float32),
                        jnp.zeros((ms,), jnp.int32),
                        jnp.ones((ms,), jnp.float32),
                    )
                    self._dec_pack(
                        self._tokens_dev, jnp.zeros((ms,), jnp.int32),
                        jnp.zeros((ms,), bool),
                    )
            if b >= ms:
                break
            b = min(b * 2, ms)
        if not spec:
            # spec mode never launches the fused decode blocks: the verify
            # tick (self._mixed, compiled above) IS its decode path
            zeros_bt = jnp.zeros((ms, pc.max_pages_per_slot), jnp.int32)
            pos = jnp.zeros((ms,), jnp.int32)
            temps = jnp.zeros((ms,), jnp.float32)
            self._key, sub = jax.random.split(self._key)
            _, _, self.cache = self._decode_block_plain(
                self.params, self.cache, zeros_bt, self._tokens_dev, pos,
                sub, temps
            )
            self._key, sub = jax.random.split(self._key)
            _, _, self.cache = self._decode_block_filtered(
                self.params, self.cache, zeros_bt, self._tokens_dev, pos,
                sub, temps, jnp.zeros((ms,), jnp.int32),
                jnp.ones((ms,), jnp.float32),
            )
        jax.block_until_ready(self.cache["k"])

    # ------------------------------------------------------------------- API

    def submit(
        self,
        prompt_tokens: List[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        *,
        top_k: int = 0,
        top_p: float = 1.0,
        stop_token_ids: Optional[List[int]] = None,
        stop_sequences: Optional[List[List[int]]] = None,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        request_id: Optional[str] = None,
    ) -> ResponseStream:
        limit = self.paged.max_slot_tokens
        if len(prompt_tokens) + max_tokens > limit:
            raise ValueError(
                f"prompt({len(prompt_tokens)}) + max_tokens({max_tokens}) "
                f"exceeds per-slot page capacity {limit}"
            )
        if not prompt_tokens:
            raise ValueError("empty prompt")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        tenant = tenant or "default"
        if request_id is None and reqlog.enabled():
            request_id = reqlog.new_request_id()
        _check_admission(self, deadline_ts, tenant, request_id=request_id)
        request = _Request(
            rid=next(self._rid),
            prompt=list(prompt_tokens),
            max_tokens=max_tokens,
            temperature=temperature,
            out=queue.Queue(),
            top_k=int(top_k),
            top_p=float(top_p),
            stop_token_ids=tuple(stop_token_ids or ()),
            stop_sequences=_normalize_stop_sequences(stop_sequences),
            deadline_ts=deadline_ts,
            tenant=tenant,
            priority=int(priority or 0),
            request_id=request_id,
        )
        _start_request_span(request, "paged")
        reqlog.mark(request_id, "engine.submitted", tenant=tenant,
                    prompt_tokens=len(request.prompt),
                    max_tokens=max_tokens)
        request.enqueued_at = time.perf_counter()
        self._queue.put(request)
        _reject_if_dead(self, request)
        self._wake.set()
        return ResponseStream(request)

    def generate(
        self, prompt_tokens: List[int], max_tokens: int = 64,
        temperature: float = 0.0, **sampling,
    ) -> List[int]:
        return self.submit(
            prompt_tokens, max_tokens, temperature, **sampling
        ).result()

    def stats(self) -> Dict[str, float]:
        """Point-in-time engine statistics: the metrics dict plus live
        allocator/prefix-cache state (the latter read fresh, not from the
        last loop tick)."""
        out = dict(self.metrics)
        out["pages_free"] = float(self.allocator.available)
        if self.prefix_cache is not None:
            for key, val in self.prefix_cache.stats().items():
                out[f"prefix_cache_{key}"] = val
        return out

    def snapshot(self) -> Dict[str, Any]:
        """Live engine introspection (`state.engine_snapshot()` / the
        dashboard's /api/engines): the lane table, page-pool occupancy,
        prefix-cache chain heads, and per-tenant fair-queue depths. Read
        in place, point-in-time, lock-free — the loop thread mutates
        between field reads, and a forensics read must never stall the
        engine (a lane row may be a tick stale; that is fine)."""
        lanes: List[Dict[str, Any]] = []
        for idx, slot in enumerate(self.slots):
            request = slot.request
            lane: Dict[str, Any] = {"lane": idx, "free": request is None}
            if request is not None:
                lane.update(
                    rid=request.rid,
                    request_id=request.request_id,
                    tenant=request.tenant,
                    priority=request.priority,
                    prefilling=slot.prefilling,
                    stalled=slot.stalled,
                    preempt_pending=slot.preempt_pending,
                    position=slot.position,
                    prefill_offset=slot.prefill_offset,
                    pages=len(slot.pages),
                    blocks_in_flight=slot.blocks_in_flight,
                    dispatch_remaining=slot.dispatch_remaining,
                    emit_remaining=slot.emit_remaining,
                    generated=request.generated,
                    spec_inflight=slot.spec_inflight,
                )
            lanes.append(lane)
        pc = self.paged
        out: Dict[str, Any] = {
            "kind": "paged",
            "lanes": lanes,
            "pages": {
                "total": pc.num_pages - 1,  # page 0 is scratch
                "free": self.allocator.available,
                "in_use": pc.num_pages - 1 - self.allocator.available,
            },
            "queue_depth": self._queue.qsize(),
            "fair_depths": self._fair.depths(),
            "inflight_blocks": self._inflight,
            "spec_tokens": self.spec_tokens,
        }
        if self.prefix_cache is not None:
            out["prefix_cache"] = dict(
                self.prefix_cache.stats(),
                chains=self.prefix_cache.chain_heads(),
            )
        return out

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._fetchq.put(None)
        self._thread.join(timeout=10)
        self._drainer.join(timeout=10)

    # ------------------------------------------------------------- admission

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Pool alloc with prefix-cache pressure relief: when the free
        list comes up short, evict cache-pinned pages (LRU, never pages a
        live slot shares) to cover the shortfall and retry once. Cached
        prefixes therefore never starve admissions or decode growth."""
        pages = self.allocator.alloc(n)
        if pages is None and self.prefix_cache is not None:
            if self.prefix_cache.evict(n - self.allocator.available) > 0:
                pages = self.allocator.alloc(n)
        return pages

    def _drain_submits(self) -> None:
        """Move raw submits into the weighted-fair admit queue: one
        per-(priority, tenant) SCFQ lane each (serve/tenancy.FairQueue),
        so admission order is virtual-time fair rather than FIFO."""
        while True:
            try:
                request = self._queue.get_nowait()
            except queue.Empty:
                return
            self._fair.push(request, request.tenant, request.priority)

    def _next_admissible(self) -> Optional[_Request]:
        """Next admissible request in weighted-fair order, shedding
        anything whose deadline expired while it queued — an expired
        request never consumes an admission slot ahead of a live one."""
        while True:
            candidate = self._fair.pop()
            if candidate is None:
                return None
            if (
                candidate.deadline_ts is not None
                and time.time() >= candidate.deadline_ts
            ):
                # expired while queued: fail fast, never take a slot
                self.metrics["timeouts"] = (
                    self.metrics.get("timeouts", 0.0) + 1
                )
                _timeout_request(candidate)
                candidate.out.put(None)
                continue
            return candidate

    def _preemption_enabled(self) -> bool:
        from ...core.config import cfg

        return bool(cfg.serve_lane_preemption)

    def _pick_victim(self, min_priority: int) -> Optional[int]:
        """Lowest-priority, largest-page-holding lane strictly below
        `min_priority` that can be preempted: not mid-prefill, not
        already finishing, not already marked. In-flight blocks do NOT
        disqualify — marking stops further dispatch and the park happens
        once the pipeline drains (``_sweep_pending_preemptions``)."""
        best = None
        for idx, slot in enumerate(self.slots):
            request = slot.request
            if (
                request is None
                or request.priority >= min_priority
                or slot.prefilling
                or slot.preempt_pending
                or slot.done_dispatching
                or slot.finished_emit
            ):
                continue
            rank = (request.priority, -len(slot.pages))
            if best is None or rank < best[0]:
                best = (rank, idx)
        return best[1] if best is not None else None

    def _request_preempt(self, idx: int) -> bool:
        """Preempt lane `idx`: park immediately when it is quiescent
        (no in-flight blocks — its emitted tokens equal its drained
        dispatch positions, so re-prefilling prompt+emitted reproduces
        the KV exactly), else mark it pending so dispatch stops feeding
        it and the drain sweep parks it. Returns True when the park
        happened NOW (pages already released)."""
        slot = self.slots[idx]
        if slot.blocks_in_flight == 0 and not slot.spec_inflight:
            self._park_lane(idx)
            return True
        slot.preempt_pending = True
        return False

    def _sweep_pending_preemptions(self) -> None:
        """Park every marked lane whose in-flight blocks have drained.
        A lane that finished (or dispatched its last block) while the
        mark was pending just unmarks — it retires on its own."""
        for idx, slot in enumerate(self.slots):
            if not slot.preempt_pending:
                continue
            if (
                slot.request is None
                or slot.finished_emit
                or slot.done_dispatching
            ):
                slot.preempt_pending = False
                continue
            if slot.blocks_in_flight == 0 and not slot.spec_inflight:
                self._park_lane(idx)

    def _park_lane(self, idx: int) -> int:
        """Preempt a decode lane: trim it to its emitted frontier and
        park the request back in the admit queue with the generated
        prefix folded into its prompt (PR 13's rollback-to-frontier
        guarantee taken to zero pages). Returns the pages released.

        Freeing `slot.pages` only drops THIS slot's refs: prefix-shared
        pages (refcount > 1 via the prefix cache or another lane) merely
        lose one holder and are never written or zeroed — the shared KV
        stays intact for everyone else. On re-admit the lane re-prefills
        prompt+generated (prefix-cache assisted), so a greedy stream
        resumes token-exact with its remaining emit budget; the consumer
        keeps every token already emitted and sees no seam."""
        from ...util.events import emit

        slot = self.slots[idx]
        request = slot.request
        freed = len(slot.pages)
        generated = list(request.gen_tokens)
        request.prompt = list(request.prompt) + generated
        request.max_tokens = slot.emit_remaining
        request.gen_tokens = []
        request.parked = True
        self.allocator.free(slot.pages)
        slot.pages = []
        slot.request = None
        slot.position = 0
        slot.prefill_offset = 0
        slot.stalled = False
        slot.dispatch_remaining = 0
        slot.done_dispatching = False
        slot.blocks_in_flight = 0
        slot.awaiting_first = False
        slot.emit_remaining = 0
        slot.finished_emit = False
        slot.spec_ctx = None
        slot.spec_inflight = False
        slot.preempt_pending = False
        self.block_tables[idx, :] = 0
        # parked lanes keep their place: front of their (priority, tenant)
        # lane, no fresh virtual-time charge
        self._fair.requeue(request, request.tenant, request.priority)
        # park wait charges into the preempt_wait TTFT bucket at resume
        request.enqueued_at = time.perf_counter()
        reqlog.mark(request.request_id, "engine.preempted",
                    tenant=request.tenant, lane=idx, pages=freed,
                    generated=len(generated))
        self.metrics["lane_preemptions"] += 1
        self.metrics["preempted_pages"] += float(freed)
        emit(
            "INFO",
            "serve",
            f"preempted decode lane slot={idx} rid={request.rid} "
            f"tenant={request.tenant} pages={freed}",
            kind="serve.lane_preempted",
            rid=request.rid,
            tenant=request.tenant,
            pages=freed,
        )
        return freed

    def _reclaim_pages(self, incoming: _Request, need: int) -> bool:
        """Page-pressure preemption: preempt strictly lower-priority
        lanes until the pages they hold (counting lanes already marked
        pending) cover `need`. Quiescent victims release immediately;
        pipelined ones release on the drain sweep a tick later — the
        caller's requeue keeps the incoming request's place meanwhile.
        Returns True when enough pages are free RIGHT NOW to retry."""
        expected = self.allocator.available + sum(
            len(s.pages) for s in self.slots if s.preempt_pending
        )
        while expected < need:
            victim = self._pick_victim(incoming.priority)
            if victim is None:
                break
            expected += len(self.slots[victim].pages)
            self._request_preempt(victim)
        return self.allocator.available >= need

    def _preempt_for_head(self) -> None:
        """High-priority admissions must not wedge behind low-priority
        long decodes: when every slot is busy and the fair head outranks
        an eligible lane, preempt one victim so the head seats as soon
        as the victim's pipeline drains (same tick when quiescent). One
        pending park at a time — never cascade victims for one head."""
        if not len(self._fair) or any(s.free for s in self.slots):
            return
        if any(s.preempt_pending for s in self.slots):
            return  # a park is already on the way for this wedge
        head = self._fair.peek()
        if head is None:
            return
        victim = self._pick_victim(head.priority)
        if victim is not None:
            self._request_preempt(victim)

    def _admit(self) -> None:
        from ...util.events import emit

        self._drain_submits()
        if self._preemption_enabled():
            self._sweep_pending_preemptions()
            self._preempt_for_head()
        for idx, slot in enumerate(self.slots):
            if not slot.free:
                continue
            if not len(self._fair):
                return
            request = self._next_admissible()
            if request is None:
                return
            # Prefix reuse: the longest cached page-aligned prefix of the
            # prompt arrives pre-filled (lookup takes this slot's refs);
            # only the tail still needs chunk prefill.
            hit: List[int] = (
                self.prefix_cache.lookup(request.prompt)
                if self.prefix_cache is not None else []
            )
            # hit pages can be chunk-misaligned, so cap fresh pages at the
            # block-table width (prefill tops up page-by-page from there)
            fresh_n = min(
                self.paged.chunk_pages,
                self.paged.max_pages_per_slot - len(hit),
            )
            pages = self._alloc_pages(fresh_n)
            if pages is None and self._preemption_enabled():
                if self._reclaim_pages(request, fresh_n):
                    pages = self._alloc_pages(fresh_n)
            if pages is None:
                if hit:
                    self.allocator.free(hit)
                # deferred admission keeps its place: front of its lane,
                # no fresh virtual-time charge
                self._fair.requeue(request, request.tenant, request.priority)
                self.metrics["page_stalls"] += 1
                if not request.stall_marked:
                    request.stall_marked = True
                    reqlog.mark(request.request_id, "engine.page_stall",
                                tenant=request.tenant, reason="admit",
                                need_pages=fresh_n)
                return
            request.stall_marked = False
            wait = _charge_wait(request)
            request.cached_tokens = len(hit) * self.paged.page_size
            if request.parked:
                request.parked = False
                self.metrics["lane_resumes"] += 1
                emit(
                    "INFO",
                    "serve",
                    f"resuming preempted lane rid={request.rid} "
                    f"tenant={request.tenant}",
                    kind="serve.lane_resumed",
                    rid=request.rid,
                    tenant=request.tenant,
                )
                reqlog.mark(request.request_id, "engine.resumed",
                            tenant=request.tenant, lane=idx, wait_s=wait,
                            hit_pages=len(hit))
            else:
                reqlog.mark(request.request_id, "engine.admitted",
                            tenant=request.tenant, lane=idx, wait_s=wait,
                            hit_pages=len(hit),
                            cached_tokens=request.cached_tokens)
            slot.request = request
            slot.pages = list(hit) + pages
            slot.position = 0
            slot.prefill_offset = len(hit) * self.paged.page_size
            slot.prefill_t0 = time.time()
            if request.span is not None:
                request.span.set_attribute(
                    "queue_s", time.perf_counter() - request.submitted_at
                )
            slot.stalled = False
            slot.dispatch_remaining = 0
            slot.done_dispatching = False
            slot.blocks_in_flight = 0
            slot.awaiting_first = False
            slot.emit_remaining = request.max_tokens
            slot.finished_emit = False
            slot.spec_ctx = None
            slot.spec_inflight = False
            slot.preempt_pending = False
            self.block_tables[idx, :] = 0
            self.block_tables[idx, : len(slot.pages)] = slot.pages

    # --------------------------------------------------------------- prefill

    def _ensure_private_page(self, idx: int, slot: _PagedSlot,
                             page_index: int) -> bool:
        """Copy-on-write guard before a decode write: if the page at the
        write frontier is shared (prefix cache pin or another slot), copy
        its KV stripes to a fresh page, swap the block table, and drop
        this slot's ref on the shared original. Page-granular sharing plus
        forward-only writes means the engine never organically writes a
        shared page today (lookup stops short of the first page a request
        writes); the guard makes that invariant enforced rather than
        assumed. Returns False (and stalls the lane) if no page is free
        for the copy."""
        if self.prefix_cache is None:
            return True
        page = slot.pages[page_index]
        if page <= 0 or self.allocator.refcount(page) <= 1:
            return True
        fresh = self._alloc_pages(1)
        if fresh is None:
            if not slot.stalled:
                slot.stalled = True
                self.metrics["page_stalls"] += 1
                reqlog.mark(slot.request.request_id, "engine.page_stall",
                            tenant=slot.request.tenant, reason="cow")
            return False
        self.cache = self._copy_page(
            self.cache, jnp.asarray(page, jnp.int32),
            jnp.asarray(fresh[0], jnp.int32),
        )
        self.allocator.free([page])
        slot.pages[page_index] = fresh[0]
        self.block_tables[idx, page_index] = fresh[0]
        self.metrics["prefix_cache_cow"] += 1
        reqlog.mark(slot.request.request_id, "engine.cow",
                    tenant=slot.request.tenant, page=page,
                    fresh_page=fresh[0])
        return True

    def _mixed_tick(self) -> bool:
        """THE mixed tick: one ragged-paged-attention device call ingests
        a chunk for EVERY prefilling slot AND advances every decodable
        lane one step. Prefill lanes pad to the next power of two (a
        handful of compiled programs covers every burst size); decode
        lanes ride along in the same launch instead of waiting behind the
        prefill backlog, so a burst of long prompts no longer freezes
        running streams for its whole duration (the split
        batched-chunk/decode-block dispatch it replaces preferred prefill
        for whole ticks at a time). Final chunks sample their first
        tokens on device, batched. Decode-only ticks return False and the
        K-step fused decode block (steady state) takes over."""
        ct = self.paged.chunk_tokens
        cp = self.paged.chunk_pages
        ps = self.paged.page_size
        maxp = self.paged.max_pages_per_slot
        ms = self.config.max_slots
        work: List[Tuple[int, int, int]] = []  # (slot_idx, offset, first_page)
        for idx, slot in enumerate(self.slots):
            if not slot.prefilling:
                continue
            offset = slot.prefill_offset
            first_page = offset // ps
            # a prefix hit can leave first_page chunk-misaligned, so the
            # chunk's page window may brush the block-table cap: grow only
            # to the cap — window pages past it stay scratch-mapped, and
            # only pad rows land there (real tokens always fit in maxp
            # pages by the submit() capacity check)
            need = min(first_page + cp, maxp) - len(slot.pages)
            if need > 0:
                extra = self._alloc_pages(need)
                if extra is None:
                    if not slot.stalled:
                        reqlog.mark(slot.request.request_id,
                                    "engine.page_stall",
                                    tenant=slot.request.tenant,
                                    reason="prefill_growth")
                    slot.stalled = True
                    self.metrics["page_stalls"] += 1
                    continue
                slot.pages.extend(extra)
                self.block_tables[idx, : len(slot.pages)] = slot.pages
            slot.stalled = False
            work.append((idx, offset, first_page))
        if not work:
            return False
        b = 1 << (len(work) - 1).bit_length()
        b = min(b, ms)
        tokens = np.zeros((b, ct), dtype=np.int32)
        page_rows = np.zeros((b + ms, maxp), dtype=np.int32)
        chunk_ids = np.zeros((b, cp), dtype=np.int32)  # inactive → scratch 0
        offsets = np.zeros((b,), dtype=np.int32)
        totals = np.zeros((b,), dtype=np.int32)  # 0 = inactive lane
        for lane, (idx, offset, first_page) in enumerate(work):
            slot = self.slots[idx]
            prompt = slot.request.prompt
            n_real = min(ct, len(prompt) - offset)
            self.metrics["prefill_tokens"] += float(n_real)
            reqlog.mark(slot.request.request_id, "engine.prefill_chunk",
                        tenant=slot.request.tenant, offset=offset,
                        tokens=n_real)
            tokens[lane, :n_real] = prompt[offset : offset + n_real]
            page_rows[lane] = self.block_tables[idx]
            window = slot.pages[first_page : first_page + cp]
            chunk_ids[lane, : len(window)] = window
            offsets[lane] = offset
            totals[lane] = offset + n_real
        # ---- decode ride-along: every decodable lane advances one step
        # (or, in speculative mode, one drafted verify round) in the same
        # launch (gated like a decode block: its fetch entry occupies an
        # inflight slot)
        spec = self.spec_tokens > 0
        dec_positions = np.zeros((ms,), dtype=np.int32)
        dec_active = np.zeros((ms,), dtype=np.int32)
        dec_temps = np.zeros((ms,), dtype=np.float32)
        dec_ks = np.zeros((ms,), dtype=np.int32)
        dec_ps = np.ones((ms,), dtype=np.float32)
        dec_tokens_np = (
            np.zeros((ms, self._spec_width), dtype=np.int32) if spec else None
        )
        dec_lanes: List[Tuple[int, _Request, bool]] = []
        spec_lanes: List[Tuple[int, _Request, int, int, int]] = []
        if self._inflight < self.config.max_inflight_blocks:
            if spec:
                spec_lanes = self._gather_spec_rounds(
                    page_rows, b, dec_tokens_np, dec_positions, dec_active,
                    dec_temps, dec_ks, dec_ps,
                )
            else:
                cap = self.paged.max_slot_tokens
                for i, slot in enumerate(self.slots):
                    if not slot.decodable:
                        continue
                    if slot.position + 1 > cap:
                        slot.done_dispatching = True
                        continue
                    pages_needed = slot.position // ps + 1
                    if pages_needed > len(slot.pages):
                        extra = self._alloc_pages(
                            pages_needed - len(slot.pages)
                        )
                        if extra is None:
                            if not slot.stalled:
                                slot.stalled = True
                                self.metrics["page_stalls"] += 1
                            continue
                        slot.pages.extend(extra)
                        self.block_tables[i, : len(slot.pages)] = slot.pages
                    if not self._ensure_private_page(
                        i, slot, slot.position // ps
                    ):
                        continue
                    slot.stalled = False
                    page_rows[b + i] = self.block_tables[i]
                    dec_positions[i] = slot.position
                    dec_active[i] = 1
                    dec_temps[i] = slot.request.temperature
                    dec_ks[i] = slot.request.top_k
                    dec_ps[i] = slot.request.top_p
                    dec_lanes.append((i, slot.request, slot.awaiting_first))
                    slot.awaiting_first = False
        logits, dec_logits, self.cache = self._mixed(
            self.params,
            self.cache,
            jnp.asarray(page_rows),
            jnp.asarray(chunk_ids),
            jnp.asarray(tokens),
            jnp.asarray(offsets),
            jnp.asarray(totals),
            jnp.asarray(dec_tokens_np) if spec else self._tokens_dev,
            jnp.asarray(dec_positions),
            jnp.asarray(dec_active),
        )
        self.metrics["mixed_ticks"] += 1
        if spec_lanes:
            self._finish_spec_dispatch(
                dec_logits, spec_lanes, dec_tokens_np, dec_active,
                dec_temps, dec_ks, dec_ps,
            )
        # ---- decode bookkeeping: sample, merge, and ship the pair of
        # token rows exactly like a K=1 decode block
        if dec_lanes:
            self._key, sub = jax.random.split(self._key)
            sampled = self._sample(
                dec_logits, sub, jnp.asarray(dec_temps),
                jnp.asarray(dec_ks), jnp.asarray(dec_ps),
            )
            stacked, merged = self._dec_pack(
                self._tokens_dev, sampled, jnp.asarray(dec_active == 1)
            )
            self._tokens_dev = merged
            _async_fetch(stacked)
            for i, request, _ in dec_lanes:
                slot = self.slots[i]
                reqlog.mark(request.request_id, "engine.decode_block",
                            tenant=request.tenant, steps=1)
                slot.position += 1
                slot.dispatch_remaining -= 1
                slot.blocks_in_flight += 1
                if slot.dispatch_remaining <= 0:
                    slot.done_dispatching = True
            self._inflight += 1
            self._fetchq.put(("block", dec_lanes, stacked))
            self.metrics["decode_blocks"] += 1
            self.metrics["decode_steps"] += 1
        # ---- prefill bookkeeping + batched first-token sampling
        lane_slots = np.full((b,), self.config.max_slots, dtype=np.int32)
        temps = np.zeros((b,), dtype=np.float32)
        top_ks = np.zeros((b,), dtype=np.int32)
        top_ps = np.ones((b,), dtype=np.float32)
        finished: List[Tuple[int, int]] = []
        for lane, (idx, offset, first_page) in enumerate(work):
            slot = self.slots[idx]
            slot.prefill_offset = int(totals[lane])
            slot.position = int(totals[lane])
            self.metrics["prefill_chunks"] += 1
            if not slot.prefilling:
                request = slot.request
                from ...util import tracing

                tracing.tracer().record_span(
                    "engine.prefill", slot.prefill_t0, time.time(),
                    parent=(request.span.context
                            if request.span is not None else None),
                    lane=f"engine:slot{idx}",
                    attrs={"rid": request.rid,
                           "prompt_tokens": len(request.prompt)},
                )
                finished.append((lane, idx))
                lane_slots[lane] = idx
                temps[lane] = request.temperature
                top_ks[lane] = request.top_k
                top_ps[lane] = request.top_p
                if self.prefix_cache is not None:
                    # publish every page the finished prompt fully covers
                    # (their KV is final: decode writes start past them)
                    self.prefix_cache.register(request.prompt, slot.pages)
        if finished:
            self._key, sub = jax.random.split(self._key)
            sampled = self._sample(
                logits, sub, jnp.asarray(temps), jnp.asarray(top_ks),
                jnp.asarray(top_ps),
            )
            self._tokens_dev = self._scatter_tokens(
                self._tokens_dev, jnp.asarray(lane_slots), sampled
            )
            for lane, idx in finished:
                slot = self.slots[idx]
                request = slot.request
                slot.dispatch_remaining = request.max_tokens - 1
                if slot.dispatch_remaining <= 0:
                    slot.done_dispatching = True
                if self.spec_tokens or slot.dispatch_remaining <= 0:
                    # spec mode drafts on the HOST, so the first token's
                    # value must round-trip before the first verify round
                    # can be proposed — fetch it now through the async
                    # pipeline ("first" seeds spec_ctx). Also the rare
                    # max_tokens=1 path, where no decode block will ever
                    # carry this lane's first token.
                    first_dev = self._take(self._tokens_dev, idx)
                    _async_fetch(first_dev)
                    self._inflight += 1
                    self._fetchq.put(("first", (idx, request), first_dev))
                else:
                    slot.awaiting_first = True
        return True

    # Historical name: drivers and tests tick prefill through it; it now
    # runs the full mixed tick (prefill chunks + decode ride-along).
    _prefill_tick = _mixed_tick

    # ---------------------------------------------------------------- decode

    def _dispatch_decode_block(self) -> bool:
        """Launch one K-step fused decode+sample block for every decodable
        lane. No host reads: results drain later via _drain()."""
        K = self.config.decode_block_steps
        ps = self.paged.page_size
        cap = self.paged.max_slot_tokens
        bt = np.zeros_like(self.block_tables)  # inactive lanes → scratch
        positions = np.zeros(len(self.slots), dtype=np.int32)
        temps = np.zeros(len(self.slots), dtype=np.float32)
        top_ks = np.zeros(len(self.slots), dtype=np.int32)
        top_ps = np.ones(len(self.slots), dtype=np.float32)
        lanes: List[Tuple[int, _Request]] = []
        useful_steps: Dict[int, int] = {}
        for i, slot in enumerate(self.slots):
            if not slot.decodable:
                continue
            # Only the USEFUL steps of a lane's final block need real
            # pages; overshoot steps (budget < K) write to unmapped block
            # table entries, i.e. the scratch page, and their sampled
            # tokens are dropped at emission.
            useful = min(K, slot.dispatch_remaining)
            if slot.position + useful > cap:
                # cannot fit the remaining budget before page capacity:
                # stop here and let emission retire the stream (possibly
                # short of max_tokens when budget brushes capacity)
                slot.done_dispatching = True
                continue
            pages_needed = (slot.position + useful - 1) // ps + 1
            if pages_needed > len(slot.pages):
                extra = self._alloc_pages(pages_needed - len(slot.pages))
                if extra is None:
                    if not slot.stalled:
                        slot.stalled = True
                        self.metrics["page_stalls"] += 1
                        reqlog.mark(slot.request.request_id,
                                    "engine.page_stall",
                                    tenant=slot.request.tenant,
                                    reason="decode_growth")
                    continue
                slot.pages.extend(extra)
                self.block_tables[i, : len(slot.pages)] = slot.pages
            # COW: every page this block will write must be privately held
            if not all(
                self._ensure_private_page(i, slot, pi)
                for pi in range(slot.position // ps, pages_needed)
            ):
                continue
            slot.stalled = False
            bt[i] = self.block_tables[i]
            positions[i] = slot.position
            temps[i] = slot.request.temperature
            top_ks[i] = slot.request.top_k
            top_ps[i] = slot.request.top_p
            useful_steps[i] = useful
            lanes.append((i, slot.request, slot.awaiting_first))
            slot.awaiting_first = False
        if not lanes:
            return False
        self._key, sub = jax.random.split(self._key)
        common = (
            self.params,
            self.cache,
            jnp.asarray(bt),
            self._tokens_dev,
            jnp.asarray(positions),
            sub,
            jnp.asarray(temps),
        )
        # all-plain batches (the common case) skip the per-step vocab sort
        if (top_ks > 0).any() or (top_ps < 1.0).any():
            toks, final, self.cache = self._decode_block_filtered(
                *common, jnp.asarray(top_ks), jnp.asarray(top_ps)
            )
        else:
            if self._tick_cost is None:
                # before the dispatch consumes the donated cache: price
                # the fused K-step decode block once
                self._tick_cost = _tick_cost(
                    self._decode_block_plain, *common
                ) or False
            toks, final, self.cache = self._decode_block_plain(*common)
        # Per-lane merge: lanes excluded from this dispatch keep their
        # pending token (see _merge_tokens docstring).
        mask = np.zeros(len(self.slots), dtype=bool)
        for i, _, _ in lanes:
            mask[i] = True
        self._tokens_dev = self._merge_tokens(
            self._tokens_dev, final, jnp.asarray(mask)
        )
        _async_fetch(toks)
        for i, request, _ in lanes:
            slot = self.slots[i]
            reqlog.mark(request.request_id, "engine.decode_block",
                        tenant=request.tenant, steps=useful_steps[i])
            slot.position += useful_steps[i]
            slot.dispatch_remaining -= K
            slot.blocks_in_flight += 1
            if slot.dispatch_remaining <= 0:
                slot.done_dispatching = True
        self._inflight += 1
        self._fetchq.put(("block", lanes, toks))
        self.metrics["decode_blocks"] += 1
        self.metrics["decode_steps"] += K
        return True

    # ---------------------------------------------------- speculative decode

    def _gather_spec_rounds(
        self,
        page_rows: np.ndarray,
        base: int,
        dec_tokens: np.ndarray,
        dec_positions: np.ndarray,
        dec_active: np.ndarray,
        dec_temps: np.ndarray,
        dec_ks: np.ndarray,
        dec_ps: np.ndarray,
    ) -> List[Tuple[int, _Request, int, int, int]]:
        """Fill one verify round per ready lane into the mixed-tick decode
        arrays: row 0 the lane's pending token (its KV write was deferred
        to this round), rows 1.. the proposer's drafts, dispatched as a
        q_len=count ragged region at positions position..position+count-1.
        Pages are grown to cover the whole round up front (COW-guarded);
        the drain side rolls back whatever rejection leaves unused. A lane
        needs spec_ctx (seeded by its "first" fetch) and at most one round
        in flight. Returns the dispatched (idx, request, dispatch_position,
        count) list."""
        ps = self.paged.page_size
        cap = self.paged.max_slot_tokens
        lanes: List[Tuple[int, _Request, int, int, int]] = []
        for i, slot in enumerate(self.slots):
            if (
                not slot.decodable
                or slot.spec_inflight
                or slot.spec_ctx is None
            ):
                continue
            # a round with c inputs emits at most c tokens and writes c KV
            # rows: cap the width by both budgets
            width = min(
                self._spec_width, cap - slot.position,
                slot.dispatch_remaining,
            )
            if width <= 0:
                slot.done_dispatching = True
                continue
            drafts: List[int] = []
            if width > 1 and self._proposer is not None:
                try:
                    drafts = list(
                        self._proposer.propose(slot.spec_ctx, width - 1)
                    )[: width - 1]
                except Exception:
                    drafts = []  # a broken proposer degrades to plain decode
            count = 1 + len(drafts)
            pre_pages = len(slot.pages)  # rollback floor: only pages this
            # round grows are ever trimmed back (admit-time spares stay)
            pages_needed = (slot.position + count - 1) // ps + 1
            if pages_needed > len(slot.pages):
                extra = self._alloc_pages(pages_needed - len(slot.pages))
                if extra is None:
                    if not slot.stalled:
                        slot.stalled = True
                        self.metrics["page_stalls"] += 1
                        reqlog.mark(slot.request.request_id,
                                    "engine.page_stall",
                                    tenant=slot.request.tenant,
                                    reason="spec_growth")
                    continue
                slot.pages.extend(extra)
                self.block_tables[i, : len(slot.pages)] = slot.pages
            # COW: every page this round may write must be privately held
            if not all(
                self._ensure_private_page(i, slot, pi)
                for pi in range(slot.position // ps, pages_needed)
            ):
                continue
            slot.stalled = False
            page_rows[base + i] = self.block_tables[i]
            dec_tokens[i, 0] = slot.spec_ctx[-1]
            if drafts:
                dec_tokens[i, 1:count] = drafts
            dec_positions[i] = slot.position
            dec_active[i] = count
            dec_temps[i] = slot.request.temperature
            dec_ks[i] = slot.request.top_k
            dec_ps[i] = slot.request.top_p
            slot.spec_inflight = True
            slot.blocks_in_flight += 1
            self.metrics["spec_proposed"] += float(len(drafts))
            lanes.append((i, slot.request, slot.position, count, pre_pages))
        return lanes

    def _finish_spec_dispatch(
        self,
        dec_logits: jax.Array,
        spec_lanes: List[Tuple[int, _Request, int, int, int]],
        dec_tokens: np.ndarray,
        dec_active: np.ndarray,
        dec_temps: np.ndarray,
        dec_ks: np.ndarray,
        dec_ps: np.ndarray,
    ) -> None:
        """Score the dispatched rounds on device (exact accept/resample)
        and ship ONE packed (tokens + counts) array through the async
        fetch pipeline — verify logits never cross to the host and the
        dispatch thread never blocks on a device read."""
        self._key, sub = jax.random.split(self._key)
        packed = self._spec_accept(
            dec_logits, jnp.asarray(dec_tokens), jnp.asarray(dec_active),
            sub, jnp.asarray(dec_temps), jnp.asarray(dec_ks),
            jnp.asarray(dec_ps),
        )
        _async_fetch(packed)
        self._inflight += 1
        self._fetchq.put(("spec", spec_lanes, packed))
        self.metrics["decode_blocks"] += 1
        self.metrics["decode_steps"] += 1  # one launch, however many tokens

    def _dispatch_spec_verify(self) -> bool:
        """Decode-only verify tick — the speculative steady state. One
        ragged launch scores every ready lane's drafted round; the single
        prefill lane is inactive (zero totals, scratch-mapped) so the call
        reuses the b=1 compiled bucket of the mixed step."""
        pc = self.paged
        ms = self.config.max_slots
        if self._inflight >= self.config.max_inflight_blocks:
            return False
        page_rows = np.zeros((1 + ms, pc.max_pages_per_slot), dtype=np.int32)
        dec_tokens = np.zeros((ms, self._spec_width), dtype=np.int32)
        dec_positions = np.zeros((ms,), dtype=np.int32)
        dec_active = np.zeros((ms,), dtype=np.int32)
        dec_temps = np.zeros((ms,), dtype=np.float32)
        dec_ks = np.zeros((ms,), dtype=np.int32)
        dec_ps = np.ones((ms,), dtype=np.float32)
        spec_lanes = self._gather_spec_rounds(
            page_rows, 1, dec_tokens, dec_positions, dec_active,
            dec_temps, dec_ks, dec_ps,
        )
        if not spec_lanes:
            return False
        _, dec_logits, self.cache = self._mixed(
            self.params,
            self.cache,
            jnp.asarray(page_rows),
            jnp.zeros((1, pc.chunk_pages), jnp.int32),
            jnp.zeros((1, pc.chunk_tokens), jnp.int32),
            jnp.zeros((1,), jnp.int32),
            jnp.zeros((1,), jnp.int32),
            jnp.asarray(dec_tokens),
            jnp.asarray(dec_positions),
            jnp.asarray(dec_active),
        )
        self._finish_spec_dispatch(
            dec_logits, spec_lanes, dec_tokens, dec_active,
            dec_temps, dec_ks, dec_ps,
        )
        return True

    # -------------------------------------------------------------- emission

    def _drain_worker(self) -> None:
        """Dedicated thread that pays the device→host read latency.
        Everything queued is fetched in ONE jax.device_get batch — on a
        tunneled TPU each separate read costs a full network round trip,
        but N batched reads cost one, so backlog amortizes instead of
        serializing. FIFO order is preserved (a request's first token is
        enqueued before any of its decode blocks)."""
        while True:
            item = self._fetchq.get()
            if item is None:
                return
            batch = [item]
            while True:
                try:
                    nxt = self._fetchq.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._fetchq.put(None)  # re-post shutdown sentinel
                    break
                batch.append(nxt)
            # One fetch thread per entry: transfers overlap across threads
            # (a single device_get over pending computations serializes —
            # wait-compute then fetch, per array, each paying the RTT).
            all_vals: List[Any] = [None] * len(batch)
            errors: List[BaseException] = []

            def fetch(i: int, arr) -> None:
                try:
                    all_vals[i] = np.asarray(arr)
                except BaseException as exc:  # noqa: BLE001 - device boundary
                    errors.append(exc)

            t0 = time.perf_counter()
            threads = [
                threading.Thread(target=fetch, args=(i, b[2]), daemon=True)
                for i, b in enumerate(batch)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            self.drain_log.append((len(batch), time.perf_counter() - t0))
            if len(self.drain_log) > 1000:
                del self.drain_log[:500]
            if errors:
                self._doneq.put(("error", errors[0], None))
                return
            for (kind, meta, _), vals in zip(batch, all_vals):
                self._doneq.put((kind, meta, vals))

    def _pump_completed(self, wait: bool = False) -> bool:
        """Emit every completed fetch. wait=True blocks briefly for one
        (used when nothing is dispatchable, so the loop makes progress)."""
        drained = False
        while True:
            try:
                timeout = 0.05 if (wait and not drained) else None
                entry = (
                    self._doneq.get(timeout=timeout)
                    if timeout is not None
                    else self._doneq.get_nowait()
                )
            except queue.Empty:
                return drained
            kind, meta, vals = entry
            if kind == "error":
                raise meta
            self._inflight -= 1
            drained = True
            if kind == "first":
                idx, request = meta
                token = int(vals[0])
                slot = self.slots[idx]
                if (
                    self.spec_tokens
                    and slot.request is request
                    and not slot.finished_emit
                ):
                    # seed the host-side draft context: everything the
                    # proposer may condition on (prompt + first token)
                    slot.spec_ctx = list(request.prompt) + [token]
                self._emit(idx, request, token, first=True)
                self._maybe_retire(idx, request)
            elif kind == "spec":
                self._complete_spec_round(meta, vals)
            else:
                # vals is (K+1, B): row 0 = the block's input tokens —
                # emitted only for lanes whose first token rides this block
                for k in range(vals.shape[0]):
                    for idx, request, fresh in meta:
                        if k == 0 and not fresh:
                            continue
                        self._emit(idx, request, int(vals[k, idx]), first=(k == 0))
                for idx, request, _ in meta:
                    slot = self.slots[idx]
                    if slot.request is request:
                        slot.blocks_in_flight -= 1
                    self._maybe_retire(idx, request)

    def _complete_spec_round(
        self, meta: List[Tuple[int, _Request, int, int, int]], vals: np.ndarray
    ) -> None:
        """Drain one verify round: emit the accepted prefix + the
        corrected/bonus token, advance the lane to the accepted frontier,
        and ROLL BACK pages speculated past it. vals is the packed
        (max_slots, W+1) array — columns [:W] emit-ordered tokens, column
        W the emitted count m (1 <= m <= count for live lanes).

        Rollback safety: the trimmed pages can never be shared. The round
        wrote positions >= dispatch_pos >= len(prompt) + 1, so the kept
        frontier keep = (new_pos-1)//ps + 1 strictly exceeds both the
        prefix-cache hit count (lookup caps at (len(prompt)-1)//ps pages)
        and everything register() publishes (len(prompt)//ps fully-covered
        pages) — trimmed indices are all fresh allocations this engine
        grew for speculated tokens, refcount 1, and free() returns them to
        the pool. Stale KV left in kept pages at rows [new_pos,
        dispatch_pos+count) is masked by every future launch's kv_len
        until the lane's forward writes overwrite it."""
        ps = self.paged.page_size
        for idx, request, dpos, count, pre_pages in meta:
            slot = self.slots[idx]
            m = int(vals[idx, -1])
            self.metrics["spec_accepted"] += float(max(0, m - 1))
            if slot.request is not request:
                continue  # retired mid-flight (deadline/EOS): pages freed
            slot.spec_inflight = False
            slot.blocks_in_flight -= 1
            new_pos = dpos + m
            slot.position = new_pos
            # free only pages THIS round grew past the accepted frontier
            # (admit-time spares below pre_pages stay mapped — trimming
            # them would churn the allocator every round on short prompts)
            keep = max((new_pos - 1) // ps + 1, pre_pages)
            rolled = 0
            if keep < len(slot.pages):
                trimmed = slot.pages[keep:]
                slot.pages = slot.pages[:keep]
                self.allocator.free(trimmed)
                self.block_tables[idx, keep:] = 0
                rolled = len(trimmed)
                self.metrics["spec_rollback_pages"] += float(rolled)
            reqlog.mark(request.request_id, "engine.spec_round",
                        tenant=request.tenant, proposed=count - 1,
                        accepted=m - 1, rollback_pages=rolled)
            slot.dispatch_remaining -= m
            if slot.dispatch_remaining <= 0:
                slot.done_dispatching = True
            emitted = [int(vals[idx, j]) for j in range(m)]
            if slot.spec_ctx is not None:
                slot.spec_ctx.extend(emitted)
            for tok in emitted:
                self._emit(idx, request, tok)
            self._maybe_retire(idx, request)

    def _emit(self, idx: int, request: _Request, token: int, first: bool = False) -> None:
        slot = self.slots[idx]
        if slot.request is not request or slot.finished_emit:
            return  # stale block for an already-retired stream
        if first and request.first_token_at is None:
            request.first_token_at = time.perf_counter()
            buckets = _observe_tenant_ttft(request)
            reqlog.mark(request.request_id, "engine.first_token",
                        tenant=request.tenant, **buckets)
        request.generated += 1
        request.out.put(token)
        # the resume ledger: a preempted lane folds these into its prompt
        request.gen_tokens.append(int(token))
        slot.emit_remaining -= 1
        self.metrics["generated_tokens"] += 1
        if not first:  # first tokens are the prefill's output
            self.metrics["decode_tokens"] += 1.0
        if (
            token == self.config.eos_id
            or token in request.stop_token_ids
            or _hit_stop_sequence(request, token)
            or slot.emit_remaining <= 0
        ):
            slot.finished_emit = True

    def _maybe_retire(self, idx: int, request: _Request) -> None:
        slot = self.slots[idx]
        if slot.request is not request:
            return
        if slot.finished_emit or (
            slot.done_dispatching and slot.blocks_in_flight == 0
        ):
            self._finish(idx, slot)

    def _finish(self, idx: int, slot: _PagedSlot) -> None:
        if slot.request is not None:
            if slot.request.span is not None:
                # span=None means the timeout path already sealed this
                # request with its own terminal mark
                reqlog.mark(slot.request.request_id, "engine.finished",
                            tenant=slot.request.tenant,
                            generated=slot.request.generated)
            _finish_request_span(slot.request)
            slot.request.out.put(None)
        self.allocator.free(slot.pages)
        slot.pages = []
        slot.request = None
        slot.stalled = False
        slot.dispatch_remaining = 0
        slot.blocks_in_flight = 0
        slot.finished_emit = False
        slot.spec_ctx = None
        slot.spec_inflight = False
        self.block_tables[idx, :] = 0

    # ------------------------------------------------------------------ loop

    def _deadline_sweep(self) -> None:
        """Evict slots whose request outlived its deadline: the stream
        fails with a typed RequestTimeoutError and the slot's pages
        return to the pool (late in-flight blocks for the evicted lane
        are benign — same guarantee as EOS retirement, module header)."""
        now = time.time()
        for idx, slot in enumerate(self.slots):
            request = slot.request
            if (
                request is None
                or slot.finished_emit
                or request.deadline_ts is None
                or now < request.deadline_ts
            ):
                continue
            self.metrics["timeouts"] = self.metrics.get("timeouts", 0.0) + 1
            _timeout_request(request)
            slot.finished_emit = True
            self._maybe_retire(idx, request)

    def _all_stalled_deadlock(self) -> Optional[int]:
        """Every occupied slot waits on an empty pool and nothing is in
        flight: truncate the largest page-holder rather than deadlock."""
        occupied = [(i, s) for i, s in enumerate(self.slots) if not s.free]
        if not occupied or self._inflight:
            return None
        if all(s.stalled or s.prefilling for _, s in occupied) and (
            self.allocator.available == 0
        ):
            return max(occupied, key=lambda t: len(t[1].pages))[0]
        return None

    def _loop(self) -> None:
        try:
            self._loop_inner()
        except BaseException as exc:  # noqa: BLE001 - engine death boundary
            self._death_cause = exc
            # queued fair-lane requests (deferred admissions included)
            # fail like freshly queued ones
            for request in self._fair.drain():
                self._queue.put(request)
            _fail_all_requests(self.slots, self._queue, exc)
            raise

    def _loop_inner(self) -> None:
        pc = self.paged
        while not self._stop.is_set():
            tick_t0 = time.perf_counter()
            self._admit()
            self._deadline_sweep()
            progressed = self._prefill_tick()
            # Prefer draining the prefill backlog before launching a decode
            # block: chunks are sub-millisecond, and grouping admissions
            # into ONE joint block minimizes fetch round trips (each block
            # materialization costs a full RTT on tunneled TPUs).
            if not progressed and self._inflight < self.config.max_inflight_blocks:
                progressed |= (
                    self._dispatch_spec_verify()
                    if self.spec_tokens
                    else self._dispatch_decode_block()
                )
            if self.spec_tokens:
                # a spec lane is only dispatchable once its "first" fetch
                # has seeded the draft context and its previous round has
                # drained — otherwise the loop must WAIT on the drain
                # queue, not spin
                dispatchable = any(
                    s.prefilling
                    or (
                        s.decodable
                        and not s.spec_inflight
                        and s.spec_ctx is not None
                    )
                    for s in self.slots
                )
            else:
                dispatchable = any(
                    s.decodable or s.prefilling for s in self.slots
                )
            gated = self._inflight >= self.config.max_inflight_blocks
            progressed |= self._pump_completed(
                wait=self._inflight > 0 and (gated or not dispatchable)
            )
            # Safety sweep: a lane can become retirable outside any pending
            # block (e.g. the capacity gate fired with nothing in flight).
            for i, slot in enumerate(self.slots):
                if slot.request is not None and not slot.prefilling:
                    self._maybe_retire(i, slot.request)
            occupied = sum(1 for s in self.slots if not s.free)
            self.metrics["ongoing"] = (
                occupied + self._queue.qsize() + len(self._fair)
            )
            self.metrics["pages_in_use"] = float(
                pc.num_pages - 1 - self.allocator.available
            )
            self.metrics["batch_fill"] = occupied / max(len(self.slots), 1)
            if self.prefix_cache is not None:
                pcs = self.prefix_cache.stats()
                self.metrics["prefix_cache_hits"] = pcs["hits"]
                self.metrics["prefix_cache_misses"] = pcs["misses"]
                self.metrics["prefix_cache_evictions"] = pcs["evictions"]
                self.metrics["prefix_cache_pages"] = pcs["pages"]
                self.metrics["prefix_cache_hit_rate"] = pcs["hit_rate"]
            if self.spec_tokens:
                prop = self.metrics["spec_proposed"]
                self.metrics["spec_acceptance_rate"] = (
                    self.metrics["spec_accepted"] / prop if prop else 0.0
                )
            if progressed:
                _observe_tick(self, time.perf_counter() - tick_t0)
            if occupied == 0 and not self._inflight:
                self._wake.wait(timeout=0.02)
                self._wake.clear()
                continue
            if not progressed:
                victim = self._all_stalled_deadlock()
                if victim is not None:
                    self._finish(victim, self.slots[victim])
                else:
                    time.sleep(0.001)


def _async_fetch(arr: jax.Array) -> None:
    """Start the device→host transfer without blocking (falls back to a
    no-op where the runtime lacks copy_to_host_async; np.asarray later
    then pays the full read)."""
    start = getattr(arr, "copy_to_host_async", None)
    if start is not None:
        try:
            start()
        except Exception:
            pass
