"""ray_tpu.serve.llm — continuous-batched LLM inference on TPU."""

from .engine import EngineConfig, LLMEngine, ResponseStream  # noqa: F401
from .paged import PagedConfig, PageAllocator  # noqa: F401
from .paged_engine import (  # noqa: F401
    PagedEngineConfig,
    PagedLLMEngine,
    serving_shardings,
)
from .openai import (  # noqa: F401
    ByteTokenizer,
    OpenAIFrontend,
    build_openai_app,
    serve_openai,
)
from .server import LLMServer, build_llm_app  # noqa: F401
