"""ray_tpu.serve.llm — continuous-batched LLM inference on TPU."""

from .engine import EngineConfig, LLMEngine, ResponseStream  # noqa: F401
from .server import LLMServer, build_llm_app  # noqa: F401
