"""Continuous-batching LLM engine, TPU-native.

Reference parity: the vLLM engine the reference wraps
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:254 — continuous batching, paged KV). TPU inversion: XLA
wants static shapes, so the engine owns a fixed SLOT GRID — a decode batch
of `max_slots` lanes over one dense KV cache (L, B, Hkv, S, Dh). Requests
stream in and out of slots between steps; the decode program never changes
shape, so it compiles exactly once. Prefill pads prompts to bucket lengths
(one compile per bucket) and scatters the prompt KV into the slot's cache
lane. Scheduling (admit → prefill → joint decode → retire) happens on the
host between device steps — the same loop vLLM runs, minus CUDA graphs,
plus XLA.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...models.transformer import (
    TransformerConfig,
    decode_step,
    init_cache,
    prefill,
)


@dataclasses.dataclass
class EngineConfig:
    max_slots: int = 8  # concurrent sequences = decode batch width
    max_seq: Optional[int] = None  # KV capacity per slot (default model max)
    eos_id: int = -1  # -1: never stop on a token
    prefill_bucket_min: int = 16
    # admission bound on the submit queue: overflow raises a typed
    # BackPressureError instead of queueing unboundedly. 0 = auto
    # (8 x max_slots); negative disables the bound.
    max_queued_requests: int = 0


@dataclasses.dataclass
class _Slot:
    request: Optional["_Request"] = None
    position: int = 0
    remaining: int = 0
    last_token: int = 0

    @property
    def free(self) -> bool:
        return self.request is None


@dataclasses.dataclass
class _Request:
    rid: int
    prompt: List[int]
    max_tokens: int
    temperature: float
    out: "queue.Queue[Optional[int]]"
    submitted_at: float = dataclasses.field(default_factory=time.perf_counter)
    first_token_at: Optional[float] = None
    # sampling params (vLLM SamplingParams parity; paged engine honors all)
    top_k: int = 0          # 0 = disabled
    top_p: float = 1.0      # 1.0 = disabled
    stop_token_ids: tuple = ()
    # multi-token stop sequences: generation ends when the tail of the
    # emitted tokens equals any of these (vLLM's `stop` strings, matched
    # over tokens — the byte tokenizer makes strings == token sequences)
    stop_sequences: tuple = ()
    stop_tail: list = dataclasses.field(default_factory=list)
    # observability: tokens emitted so far, and the engine.request span
    # opened at submit — TTFT/TPOT/queue-time derive from it at retire
    generated: int = 0
    span: Any = None
    # end-to-end deadline (epoch seconds): expired requests fail fast at
    # admit and are cancelled/evicted mid-generation
    deadline_ts: Optional[float] = None
    # multi-tenant admission: tenant keys the fair-queue lane and quota
    # bucket; priority (higher = more important) gates lane preemption
    tenant: str = "default"
    priority: int = 0
    # tokens emitted since (re-)admission — a preempted lane folds these
    # into its prompt so the parked request resumes token-exact
    gen_tokens: list = dataclasses.field(default_factory=list)
    # True while parked by lane preemption (waiting in the fair queue
    # with its generated prefix folded into the prompt)
    parked: bool = False
    # request forensics (serve/reqlog.py): the end-to-end public id, and
    # the TTFT-decomposition accumulators. queue_wait/preempt_wait are
    # charged at each (re-)admission from enqueued_at, so at first token
    # prefill_compute = TTFT - queue_wait - preempt_wait by construction.
    request_id: Optional[str] = None
    enqueued_at: Optional[float] = None
    queue_wait_s: float = 0.0
    preempt_wait_s: float = 0.0
    cached_tokens: int = 0
    # latch: the paged admit loop retries a page-stalled admission every
    # tick — mark engine.page_stall once per stall episode, not per retry
    stall_marked: bool = False


def _start_request_span(request: "_Request", engine_kind: str) -> None:
    """Open the request's engine.request span at submit time (caller
    thread: it nests under an active serve.route/actor.execute span).
    Shared by the dense and paged engines."""
    from ...util import tracing

    attrs = {"rid": request.rid, "engine": engine_kind,
             "prompt_tokens": len(request.prompt),
             "max_tokens": request.max_tokens}
    if request.request_id is not None:
        # joins the trace to the request-forensics timeline (reqlog)
        attrs["request_id"] = request.request_id
    request.span = tracing.tracer().start_span(
        "engine.request",
        lane=f"engine:{engine_kind}",
        attrs=attrs,
    )


def _finish_request_span(request: "_Request", status: str = "OK") -> None:
    """Close the request span at retire: TTFT/TPOT/token counts become
    span attributes, and the tracer derives raytpu_serve_ttft_seconds /
    raytpu_serve_tpot_seconds from them — serving SLOs come from spans,
    not ad-hoc timers."""
    span = request.span
    if span is None:
        return
    attrs: Dict[str, Any] = {"generated_tokens": request.generated}
    if request.first_token_at is not None:
        attrs["ttft_s"] = request.first_token_at - request.submitted_at
        if request.generated > 1:
            attrs["tpot_s"] = (
                (time.perf_counter() - request.first_token_at)
                / (request.generated - 1)
            )
    span.end(status=status, **attrs)


# ------------------------------------------- batch-occupancy accounting
#
# Both engines (dense + paged) keep per-tick occupancy numbers in their
# `metrics` dict; this registry exposes them as engine-labeled callback
# gauges so the SLO monitor and a future autoscaler can read batch
# headroom straight off /metrics. Weak values: a shut-down engine's
# series disappears instead of freezing at its last value.

_ENGINES: "weakref.WeakValueDictionary[str, Any]" = weakref.WeakValueDictionary()
_engine_seq = itertools.count()
_TICK_EWMA = 0.2  # per-tick smoothing for tick_seconds/decode_mfu


def _register_engine_metrics(engine: Any, kind: str) -> str:
    label = f"{kind}-{next(_engine_seq)}"
    _ENGINES[label] = engine
    _ensure_engine_gauges()
    return label


def _engine_metric_sampler(key: str):
    def sample():
        return [
            ({"engine": label}, float(e.metrics.get(key, 0.0)))
            for label, e in list(_ENGINES.items())
        ]

    return sample


def _ensure_engine_gauges() -> None:
    # no module-level one-shot latch: get_or_create_gauge is idempotent
    # against the LIVE registry, which tests reset with registry().clear()
    from ...util.metrics import get_or_create_gauge

    get_or_create_gauge(
        "raytpu_engine_batch_fill",
        "Fraction of the engine's decode slots occupied at the last tick "
        "(batch headroom for the SLO monitor / autoscaler).",
        tag_keys=("engine",), fn=_engine_metric_sampler("batch_fill"),
    )
    get_or_create_gauge(
        "raytpu_engine_tick_seconds",
        "EWMA wall time of one engine tick (decode round / paged loop "
        "iteration that made progress).",
        tag_keys=("engine",), fn=_engine_metric_sampler("tick_seconds"),
    )
    get_or_create_gauge(
        "raytpu_engine_decode_mfu",
        "Model-FLOPs utilization of the decode program, from its "
        "compiled cost_analysis() over the EWMA tick time.",
        tag_keys=("engine",), fn=_engine_metric_sampler("decode_mfu"),
    )

    get_or_create_gauge(
        "raytpu_engine_prefix_cache_hits",
        "Cumulative prefix-cache page hits (pages of prompt KV reused "
        "instead of re-prefilled).",
        tag_keys=("engine",), fn=_engine_metric_sampler("prefix_cache_hits"),
    )
    get_or_create_gauge(
        "raytpu_engine_prefix_cache_misses",
        "Cumulative prefix-cache page misses (page-aligned prompt pages "
        "that had to prefill).",
        tag_keys=("engine",), fn=_engine_metric_sampler("prefix_cache_misses"),
    )
    get_or_create_gauge(
        "raytpu_engine_prefix_cache_evictions",
        "Cumulative cache-pinned pages evicted back to the pool under "
        "allocation pressure.",
        tag_keys=("engine",),
        fn=_engine_metric_sampler("prefix_cache_evictions"),
    )
    get_or_create_gauge(
        "raytpu_engine_prefix_cache_pages",
        "Pages currently pinned by the prefix cache (each holds one "
        "prompt page's KV warm for reuse).",
        tag_keys=("engine",), fn=_engine_metric_sampler("prefix_cache_pages"),
    )
    get_or_create_gauge(
        "raytpu_engine_prefix_cache_hit_rate",
        "Lifetime fraction of page-aligned prompt pages served from the "
        "prefix cache.",
        tag_keys=("engine",),
        fn=_engine_metric_sampler("prefix_cache_hit_rate"),
    )

    get_or_create_gauge(
        "raytpu_engine_spec_proposed",
        "Cumulative draft tokens proposed for speculative verify rounds "
        "(zero when speculation is off).",
        tag_keys=("engine",), fn=_engine_metric_sampler("spec_proposed"),
    )
    get_or_create_gauge(
        "raytpu_engine_spec_accepted",
        "Cumulative draft tokens accepted by the exact verify step "
        "(each one is a decode launch the lane did not pay).",
        tag_keys=("engine",), fn=_engine_metric_sampler("spec_accepted"),
    )
    get_or_create_gauge(
        "raytpu_engine_spec_acceptance_rate",
        "Lifetime fraction of proposed draft tokens accepted — the knob "
        "that decides whether speculation is paying for its verify rows.",
        tag_keys=("engine",),
        fn=_engine_metric_sampler("spec_acceptance_rate"),
    )
    get_or_create_gauge(
        "raytpu_engine_spec_rollback_pages",
        "Cumulative KV pages freed by post-rejection rollback (pages "
        "allocated for speculated positions past the accepted frontier).",
        tag_keys=("engine",),
        fn=_engine_metric_sampler("spec_rollback_pages"),
    )

    def token_mix():
        out = []
        for label, e in list(_ENGINES.items()):
            out.append((
                {"engine": label, "phase": "prefill"},
                float(e.metrics.get("prefill_tokens", 0.0)),
            ))
            out.append((
                {"engine": label, "phase": "decode"},
                float(e.metrics.get("decode_tokens", 0.0)),
            ))
        return out

    get_or_create_gauge(
        "raytpu_engine_token_mix",
        "Cumulative tokens processed per phase (prefill-ingested vs "
        "decode-generated): the batch composition serving capacity "
        "planning prices against.",
        tag_keys=("engine", "phase"), fn=token_mix,
    )


def _tick_cost(fn: Any, *args: Any):
    """cost_analysis() of an engine's compiled tick program at the live
    argument shapes — called BEFORE the first dispatch (donated buffers
    are still alive), cached by the caller. Returns None when disabled
    (profile_cost_accounting — the AOT lower/compile pays one extra XLA
    compile per program) or the backend can't answer; accounting never
    fails a tick."""
    try:
        from ...core.config import cfg
        from ...util import profiling

        if not cfg.profile_cost_accounting:
            return None
        return profiling.step_cost(fn, *args)
    except Exception:  # noqa: BLE001 - accounting must not kill the engine
        return None


def _observe_tick(engine: Any, tick_s: float) -> None:
    """Fold one tick's wall time into the EWMA and refresh the decode
    MFU against the cached tick cost."""
    prev = engine.metrics.get("tick_seconds", 0.0)
    ewma = tick_s if prev <= 0 else (1 - _TICK_EWMA) * prev + _TICK_EWMA * tick_s
    engine.metrics["tick_seconds"] = ewma
    cost = getattr(engine, "_tick_cost", None)
    if cost and ewma > 0:  # False = accounting unavailable on this backend
        try:
            from ...util import profiling

            roof = profiling.roofline(cost, ewma)
            engine.metrics["decode_mfu"] = roof["mfu"]
            engine.metrics["decode_flops"] = cost.total_flops
        except Exception:  # noqa: BLE001 - accounting must not kill the engine
            pass


def _queue_bound(config) -> int:
    """Resolve the engine's admit-queue bound: explicit, auto
    (8 x max_slots when 0), or unlimited (-1)."""
    bound = getattr(config, "max_queued_requests", 0)
    if bound == 0:
        return 8 * config.max_slots
    return bound


def _check_admission(engine, deadline_ts, tenant: str = "default",
                     request_id: Optional[str] = None) -> None:
    """Shared submit-time gate for both engines: bound the queue (typed
    BackPressureError on overflow), charge the tenant's token bucket
    (typed shed carrying the bucket's refill time as Retry-After), and
    fail already-expired deadlines fast instead of queueing work nobody
    will wait for. Every exit records a TERMINAL phase mark so a shed
    request never appears forever-pending in the forensics plane."""
    from ...core.exceptions import BackPressureError, RequestTimeoutError
    from .. import reqlog, tenancy

    bound = _queue_bound(engine.config)
    backlog = engine._queue.qsize() + len(getattr(engine, "_fair", ()))
    if bound >= 0 and backlog >= bound:
        engine.metrics["shed"] = engine.metrics.get("shed", 0.0) + 1
        tenancy.count_shed(tenant)
        reqlog.mark(request_id, "engine.shed", tenant=tenant,
                    reason="queue_full", backlog=backlog)
        raise BackPressureError(
            f"engine admit queue is full ({bound} waiting requests)"
        )
    retry_after_s = tenancy.quota_check(tenant)
    if retry_after_s is not None:
        engine.metrics["shed"] = engine.metrics.get("shed", 0.0) + 1
        tenancy.count_shed(tenant, retry_after_s)
        reqlog.mark(request_id, "engine.shed", tenant=tenant,
                    reason="quota", retry_after_s=retry_after_s)
        raise BackPressureError(
            f"tenant {tenant!r} is over its token-bucket quota",
            retry_after_s=retry_after_s,
        )
    if deadline_ts is not None and time.time() >= deadline_ts:
        engine.metrics["timeouts"] = engine.metrics.get("timeouts", 0.0) + 1
        reqlog.mark(request_id, "engine.timeout", tenant=tenant,
                    reason="expired_before_submit")
        raise RequestTimeoutError("request deadline expired before submit")
    tenancy.count_request(tenant)


def _charge_wait(request: "_Request") -> float:
    """Charge the time since the request was (re-)enqueued into the
    right TTFT-decomposition bucket: preempt_wait for a parked lane
    being re-admitted, queue_wait otherwise. Called at each successful
    admission, BEFORE the admit path clears `parked`."""
    now = time.perf_counter()
    wait = max(0.0, now - (request.enqueued_at
                           if request.enqueued_at is not None
                           else request.submitted_at))
    if request.parked:
        request.preempt_wait_s += wait
    else:
        request.queue_wait_s += wait
    request.enqueued_at = None
    return wait


def _ttft_buckets(request: "_Request") -> Dict[str, float]:
    """TTFT decomposition at the first-token point. The three summed
    buckets are exact by construction (prefill_compute is the
    remainder); cache_saved is an informational estimate of the prefill
    time the prefix cache skipped, NOT part of the sum."""
    ttft = max(0.0, request.first_token_at - request.submitted_at)
    queue_wait = min(request.queue_wait_s, ttft)
    preempt_wait = min(request.preempt_wait_s, max(0.0, ttft - queue_wait))
    prefill_compute = max(0.0, ttft - queue_wait - preempt_wait)
    buckets = {
        "ttft_s": ttft,
        "queue_wait_s": queue_wait,
        "preempt_wait_s": preempt_wait,
        "prefill_compute_s": prefill_compute,
        "cache_saved_s": 0.0,
    }
    prefilled = len(request.prompt) - request.cached_tokens
    if request.cached_tokens > 0 and prefilled > 0:
        buckets["cache_saved_s"] = (
            prefill_compute * request.cached_tokens / prefilled
        )
        buckets["cached_tokens"] = request.cached_tokens
    return buckets


def _observe_tenant_ttft(request: "_Request") -> Dict[str, float]:
    """First-token hook shared by both engines: report the request's
    TTFT into the tenancy window ServeSLOMonitor drains for per-tenant
    attainment, push the decomposition into the per-tenant breakdown
    window + histograms, and return the buckets (the engines attach
    them to the engine.first_token mark). Only ever called for requests
    that actually produced a token."""
    from ...util.metrics import get_or_create_histogram
    from .. import tenancy

    if request.first_token_at is None:
        return {}
    buckets = _ttft_buckets(request)
    tenancy.observe_ttft(request.tenant, buckets["ttft_s"])
    tenancy.observe_ttft_breakdown(request.tenant, buckets)
    tags = {"tenant": request.tenant}
    bounds = (0.005, 0.025, 0.1, 0.5, 2.5, 10.0)
    get_or_create_histogram(
        "raytpu_serve_ttft_queue_wait_seconds",
        "Per-tenant TTFT bucket: time waiting in admit/fair queues.",
        boundaries=bounds, tag_keys=("tenant",),
    ).observe(buckets["queue_wait_s"], tags=tags)
    get_or_create_histogram(
        "raytpu_serve_ttft_preempt_wait_seconds",
        "Per-tenant TTFT bucket: time parked by lane preemption.",
        boundaries=bounds, tag_keys=("tenant",),
    ).observe(buckets["preempt_wait_s"], tags=tags)
    get_or_create_histogram(
        "raytpu_serve_ttft_prefill_compute_seconds",
        "Per-tenant TTFT bucket: prompt-ingest compute (TTFT minus the "
        "wait buckets).",
        boundaries=bounds, tag_keys=("tenant",),
    ).observe(buckets["prefill_compute_s"], tags=tags)
    return buckets


def _timeout_request(request: "_Request") -> None:
    """Fail a request on deadline expiry: the stream raises a typed
    RequestTimeoutError, the request span closes as TIMEOUT, and the
    forensics timeline records its terminal phase."""
    from ...core.exceptions import RequestTimeoutError
    from .. import reqlog

    _finish_request_span(request, status="TIMEOUT")
    request.span = None  # _finish must not double-close the span
    reqlog.mark(request.request_id, "engine.timeout", tenant=request.tenant,
                generated=request.generated)
    request.out.put(RequestTimeoutError(
        f"request {request.rid} cancelled: deadline exceeded after "
        f"{request.generated} generated token(s)"
    ))


def _normalize_stop_sequences(stop_sequences) -> tuple:
    seqs = tuple(
        tuple(int(t) for t in seq) for seq in (stop_sequences or ()) if seq
    )
    if any(len(s) == 0 for s in seqs):
        raise ValueError("stop sequences must be non-empty token lists")
    return seqs


def _hit_stop_sequence(request: "_Request", token: int) -> bool:
    """Per-token stop check over the decoded tail: append the emitted
    token to the request's rolling tail and report whether any stop
    sequence is now its suffix. Shared by the dense and paged engines."""
    seqs = request.stop_sequences
    if not seqs:
        return False
    tail = request.stop_tail
    tail.append(int(token))
    longest = max(len(s) for s in seqs)
    if len(tail) > longest:
        del tail[: len(tail) - longest]
    return any(
        len(tail) >= len(s) and tuple(tail[-len(s):]) == s for s in seqs
    )


class ResponseStream:
    """Per-request token stream: iterate for streaming, .result() to drain."""

    def __init__(self, request: _Request):
        self._request = request

    def __iter__(self):
        while True:
            token = self._request.out.get()
            if token is None:
                return
            if isinstance(token, BaseException):
                raise token
            yield token

    def result(self, timeout: Optional[float] = None) -> List[int]:
        tokens: List[int] = []
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
            token = self._request.out.get(timeout=remaining)
            if token is None:
                return tokens
            if isinstance(token, BaseException):
                raise token
            tokens.append(token)

    @property
    def ttft_s(self) -> Optional[float]:
        if self._request.first_token_at is None:
            return None
        return self._request.first_token_at - self._request.submitted_at

    @property
    def request_id(self) -> Optional[str]:
        """The end-to-end public request id (forensics/timeline key)."""
        return self._request.request_id


class LLMEngine:
    """Run with params on whatever mesh/devices they already live on."""

    def __init__(
        self,
        model_config: TransformerConfig,
        params: Any,
        engine_config: Optional[EngineConfig] = None,
    ):
        self.model_config = model_config
        self.params = params
        self.config = engine_config or EngineConfig()
        self.max_seq = self.config.max_seq or model_config.max_seq
        b = self.config.max_slots

        self.cache = init_cache(model_config, b, self.max_seq)
        self.slots = [_Slot() for _ in range(b)]
        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._rid = itertools.count()
        self._stop = threading.Event()
        self._wake = threading.Event()

        mc = model_config

        def _decode(params, cache, tokens, positions):
            return decode_step(params, cache, tokens, positions, mc)

        def _sample(logits, key, temps):
            greedy = jnp.argmax(logits, axis=-1)
            scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
            sampled = jax.random.categorical(key, scaled, axis=-1)
            return jnp.where(temps <= 0.0, greedy, sampled).astype(jnp.int32)

        self._decode = jax.jit(_decode, donate_argnums=(1,))
        self._sample = jax.jit(_sample)

        def _prefill_one(params, tokens, length):
            # batch-1 prefill; returns (last_logits (1,V), cache (L,1,H,Sb,D))
            small = init_cache(mc, 1, tokens.shape[1])
            return prefill(params, tokens, length, small, mc)

        def _insert(cache_k, cache_v, new_k, new_v, slot):
            k = jax.lax.dynamic_update_slice(cache_k, new_k, (0, slot, 0, 0, 0))
            v = jax.lax.dynamic_update_slice(cache_v, new_v, (0, slot, 0, 0, 0))
            return k, v

        self._prefill_one = jax.jit(_prefill_one)
        self._insert = jax.jit(_insert, donate_argnums=(0, 1))

        self._key = jax.random.PRNGKey(0)
        self.metrics: Dict[str, float] = {
            "generated_tokens": 0.0,
            "decode_steps": 0.0,
            "prefills": 0.0,
            "ongoing": 0.0,
            "shed": 0.0,
            "timeouts": 0.0,
            # batch-occupancy accounting (engine-labeled gauges above)
            "batch_fill": 0.0,
            "tick_seconds": 0.0,
            "prefill_tokens": 0.0,
            "decode_tokens": 0.0,
        }
        self._tick_cost = None  # decode program cost, set on first round
        self.metrics_label = _register_engine_metrics(self, "dense")
        self._thread = threading.Thread(target=self._loop, daemon=True, name="llm-engine")
        self._thread.start()

    # ------------------------------------------------------------------ API

    def submit(
        self,
        prompt_tokens: List[int],
        max_tokens: int = 64,
        temperature: float = 0.0,
        *,
        stop_token_ids: Optional[List[int]] = None,
        stop_sequences: Optional[List[List[int]]] = None,
        top_k: int = 0,
        top_p: float = 1.0,
        deadline_ts: Optional[float] = None,
        tenant: Optional[str] = None,
        priority: Optional[int] = None,
        request_id: Optional[str] = None,
    ) -> ResponseStream:
        from .. import reqlog

        if len(prompt_tokens) + max_tokens > self.max_seq:
            raise ValueError(
                f"prompt({len(prompt_tokens)}) + max_tokens({max_tokens}) exceeds "
                f"engine max_seq {self.max_seq}"
            )
        if top_k or top_p != 1.0:
            raise ValueError(
                "top_k/top_p sampling lives in PagedLLMEngine (the dense "
                "engine samples temperature-only); use PagedEngineConfig"
            )
        tenant = tenant or "default"
        if request_id is None and reqlog.enabled():
            request_id = reqlog.new_request_id()
        _check_admission(self, deadline_ts, tenant, request_id=request_id)
        request = _Request(
            rid=next(self._rid),
            prompt=list(prompt_tokens),
            max_tokens=max_tokens,
            temperature=temperature,
            out=queue.Queue(),
            stop_token_ids=tuple(stop_token_ids or ()),
            stop_sequences=_normalize_stop_sequences(stop_sequences),
            deadline_ts=deadline_ts,
            tenant=tenant,
            priority=int(priority or 0),
            request_id=request_id,
        )
        _start_request_span(request, "dense")
        reqlog.mark(request_id, "engine.submitted", tenant=tenant,
                    prompt_tokens=len(request.prompt),
                    max_tokens=max_tokens)
        request.enqueued_at = time.perf_counter()
        self._queue.put(request)
        _reject_if_dead(self, request)
        self._wake.set()
        return ResponseStream(request)

    def generate(
        self, prompt_tokens: List[int], max_tokens: int = 64, temperature: float = 0.0
    ) -> List[int]:
        return self.submit(prompt_tokens, max_tokens, temperature).result()

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)

    def snapshot(self) -> Dict[str, Any]:
        """Live engine introspection (`state.engine_snapshot()`): the
        dense slot grid has no page pool or fair queue, so the snapshot
        is just the lane table plus queue depth. Lock-free point-in-time
        read, same caveats as the paged engine's."""
        lanes: List[Dict[str, Any]] = []
        for idx, slot in enumerate(self.slots):
            request = slot.request
            lane: Dict[str, Any] = {"lane": idx, "free": request is None}
            if request is not None:
                lane.update(
                    rid=request.rid,
                    request_id=request.request_id,
                    tenant=request.tenant,
                    priority=request.priority,
                    position=slot.position,
                    remaining=slot.remaining,
                    generated=request.generated,
                )
            lanes.append(lane)
        return {
            "kind": "dense",
            "lanes": lanes,
            "queue_depth": self._queue.qsize(),
        }

    # ------------------------------------------------------------ scheduling

    def _bucket(self, n: int) -> int:
        b = self.config.prefill_bucket_min
        while b < n:
            b *= 2
        return min(b, self.max_seq)

    def _admit(self) -> None:
        for slot_idx, slot in enumerate(self.slots):
            if not slot.free:
                continue
            while True:
                try:
                    request = self._queue.get_nowait()
                except queue.Empty:
                    return
                if (
                    request.deadline_ts is not None
                    and time.time() >= request.deadline_ts
                ):
                    # expired while queued: fail fast, never prefill
                    self.metrics["timeouts"] = (
                        self.metrics.get("timeouts", 0.0) + 1
                    )
                    _timeout_request(request)
                    request.out.put(None)
                    continue
                break
            self._do_prefill(slot_idx, slot, request)

    def _do_prefill(self, slot_idx: int, slot: _Slot, request: _Request) -> None:
        from ...util import tracing
        from .. import reqlog

        wait = _charge_wait(request)
        reqlog.mark(request.request_id, "engine.admitted",
                    tenant=request.tenant, lane=slot_idx, wait_s=wait)
        if request.span is not None:
            # admit time: everything between submit and this slot freeing
            # up was queue wait
            request.span.set_attribute(
                "queue_s", time.perf_counter() - request.submitted_at
            )
        prefill_span = tracing.tracer().start_span(
            "engine.prefill",
            parent=request.span.context if request.span is not None else None,
            lane=f"engine:slot{slot_idx}",
            attrs={"rid": request.rid, "prompt_tokens": len(request.prompt)},
        )
        prompt = np.asarray(request.prompt, dtype=np.int32)
        bucket = self._bucket(len(prompt))
        padded = np.zeros((1, bucket), dtype=np.int32)
        padded[0, : len(prompt)] = prompt
        length = jnp.asarray([len(prompt)], dtype=jnp.int32)
        last_logits, small_cache = self._prefill_one(
            self.params, jnp.asarray(padded), length
        )
        # pad the prompt cache up to max_seq lanes? No — insert only the
        # bucket rows; the rest of the lane is stale and masked by position.
        self.cache["k"], self.cache["v"] = self._insert(
            self.cache["k"], self.cache["v"], small_cache["k"], small_cache["v"], slot_idx
        )
        self._key, sub = jax.random.split(self._key)
        temps = jnp.asarray([request.temperature], dtype=jnp.float32)
        first = int(self._sample(last_logits, sub, temps)[0])
        request.first_token_at = time.perf_counter()
        buckets = _observe_tenant_ttft(request)
        reqlog.mark(request.request_id, "engine.first_token",
                    tenant=request.tenant, **buckets)
        prefill_span.end(bucket=bucket)
        self.metrics["prefill_tokens"] += float(len(prompt))
        request.generated += 1
        request.out.put(first)
        slot.request = request
        slot.position = len(prompt)  # next write slot = first generated token
        slot.remaining = request.max_tokens - 1
        slot.last_token = first
        self.metrics["prefills"] += 1
        self.metrics["generated_tokens"] += 1
        if (
            slot.remaining <= 0
            or first == self.config.eos_id
            or first in request.stop_token_ids
            or _hit_stop_sequence(request, first)
        ):
            self._finish(slot)

    def _finish(self, slot: _Slot) -> None:
        from .. import reqlog

        if slot.request is not None:
            if slot.request.span is not None:
                # span=None means the timeout path already sealed this
                # request with its own terminal mark
                reqlog.mark(slot.request.request_id, "engine.finished",
                            tenant=slot.request.tenant,
                            generated=slot.request.generated)
            _finish_request_span(slot.request)
            slot.request.out.put(None)
        slot.request = None
        slot.remaining = 0

    def _deadline_sweep(self) -> None:
        """Cancel slots whose request outlived its deadline — the lane
        frees for queued work instead of generating into the void."""
        now = time.time()
        for slot in self.slots:
            request = slot.request
            if request is None or request.deadline_ts is None:
                continue
            if now >= request.deadline_ts:
                self.metrics["timeouts"] = self.metrics.get("timeouts", 0.0) + 1
                _timeout_request(request)
                self._finish(slot)

    def _decode_round(self) -> None:
        t0 = time.perf_counter()
        tokens = np.zeros(len(self.slots), dtype=np.int32)
        positions = np.zeros(len(self.slots), dtype=np.int32)
        temps = np.zeros(len(self.slots), dtype=np.float32)
        active = []
        for i, slot in enumerate(self.slots):
            if not slot.free:
                tokens[i] = slot.last_token
                positions[i] = slot.position
                temps[i] = slot.request.temperature
                active.append(i)
        dev_tokens, dev_positions = jnp.asarray(tokens), jnp.asarray(positions)
        if self._tick_cost is None:
            # before the first dispatch: the donated cache is still live
            self._tick_cost = _tick_cost(
                self._decode, self.params, self.cache, dev_tokens, dev_positions
            ) or False
        logits, self.cache = self._decode(
            self.params, self.cache, dev_tokens, dev_positions
        )
        self._key, sub = jax.random.split(self._key)
        sampled = np.asarray(self._sample(logits, sub, jnp.asarray(temps)))
        self.metrics["decode_steps"] += 1
        self.metrics["decode_tokens"] += float(len(active))
        _observe_tick(self, time.perf_counter() - t0)
        for i in active:
            slot = self.slots[i]
            token = int(sampled[i])
            slot.request.generated += 1
            slot.request.out.put(token)
            slot.last_token = token
            slot.position += 1
            slot.remaining -= 1
            self.metrics["generated_tokens"] += 1
            if (
                token == self.config.eos_id
                or token in slot.request.stop_token_ids
                or _hit_stop_sequence(slot.request, token)
                or slot.remaining <= 0
                or slot.position >= self.max_seq - 1
            ):
                self._finish(slot)

    def _loop(self) -> None:
        # The loop thread is the engine: if it dies, every pending stream
        # hangs forever. Fail them all with the cause instead.
        try:
            while not self._stop.is_set():
                self._admit()
                self._deadline_sweep()
                n_active = sum(1 for s in self.slots if not s.free)
                self.metrics["ongoing"] = float(n_active) + self._queue.qsize()
                self.metrics["batch_fill"] = n_active / max(len(self.slots), 1)
                if n_active == 0:
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
                    continue
                self._decode_round()
        except BaseException as exc:  # noqa: BLE001 - engine death boundary
            self._death_cause = exc
            _fail_all_requests(self.slots, self._queue, exc)
            raise


def _fail_all_requests(slots, request_queue, exc: BaseException) -> None:
    """Engine-death path: surface `exc` on every active and queued stream."""
    for slot in slots:
        if slot.request is not None:
            _finish_request_span(slot.request, status="ERROR")
            slot.request.out.put(exc)
            slot.request = None
    while True:
        try:
            request = request_queue.get_nowait()
        except queue.Empty:
            return
        _finish_request_span(request, status="ERROR")
        request.out.put(exc)


def _reject_if_dead(engine, request: "_Request") -> None:
    """Close the submit-vs-death race: the death path sets _death_cause
    BEFORE draining the queue, so a submit that enqueued after the final
    drain is guaranteed to observe _death_cause here and fail its own
    request instead of waiting on a loop that will never run."""
    cause = getattr(engine, "_death_cause", None)
    if cause is not None:
        while True:
            try:
                engine._queue.get_nowait().out.put(cause)
            except queue.Empty:
                break
        raise RuntimeError("LLM engine is dead") from cause
