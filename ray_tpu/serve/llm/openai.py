"""OpenAI-compatible serving surface: /v1/completions,
/v1/chat/completions (SSE streaming), /v1/models.

Reference parity: build_openai_app
(/root/reference/python/ray/llm/_internal/serve/ → serve/llm/__init__.py)
which mounts an OpenAI-schema FastAPI app over LLMServer deployments.
TPU-image inversion: zero egress means no tokenizer vocab files, so text
is encoded with a built-in byte-level tokenizer (UTF-8 bytes = token ids
< 256 — an exact fit for the *-tiny model family's vocab of 256; larger
models accept OpenAI's token-array `prompt` form directly, which the
real OpenAI API also supports). The HTTP layer is the same stdlib
threaded server as serve's proxy — no ASGI dependency.

Routing: the request's `model` field resolves to a serve deployment
(one app per model), so multiple models can be mounted on one port,
mirroring how build_openai_app routes by model id.
"""

from __future__ import annotations

import json
import math
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler
from typing import Any, Dict, List, Optional

from ...core.exceptions import (
    BackPressureError,
    DeploymentUnavailableError,
    GetTimeoutError,
    ReplicaDrainingError,
    RequestTimeoutError,
    unwrap_error,
)
from .. import api as serve_api
from .. import reqlog
from ..api import EgresslessHTTPServer, write_chunk


def _http_status_for(err: BaseException):
    """(status, error-type, retry_after | None) for a serve-layer typed
    error, or None when `err` is not an overload/availability/deadline
    condition. BackPressure → 429 (client should back off and retry),
    unavailability/draining → 503, deadline expiry → 504.

    The 429 Retry-After is computed from the shed's own estimate when it
    carries one (token-bucket refill time, router queue drain rate);
    otherwise the historical 1-second default."""
    cause = unwrap_error(err)
    if isinstance(cause, BackPressureError):
        return 429, "overloaded_error", _retry_after_s(cause)
    if isinstance(cause, (DeploymentUnavailableError, ReplicaDrainingError)):
        return 503, "service_unavailable_error", 1
    if isinstance(cause, (RequestTimeoutError, GetTimeoutError)):
        return 504, "timeout_error", None
    return None


def _retry_after_s(cause: BaseException) -> int:
    retry = getattr(cause, "retry_after_s", None)
    if not retry or retry <= 0:
        return 1
    return max(1, int(math.ceil(float(retry))))


class ByteTokenizer:
    """UTF-8 byte-level fallback tokenizer (token id == byte value)."""

    @staticmethod
    def encode(text: str) -> List[int]:
        return list(text.encode("utf-8"))

    @staticmethod
    def decode(tokens: List[int]) -> str:
        return bytes(t for t in tokens if 0 <= t < 256).decode(
            "utf-8", errors="replace"
        )


def _chat_prompt(messages: List[Dict[str, str]]) -> str:
    """Minimal chat template (the reference applies the model's own
    template from its tokenizer config; none ships in this image)."""
    lines = [f"{m.get('role', 'user')}: {m.get('content', '')}"
             for m in messages]
    lines.append("assistant:")
    return "\n".join(lines)


class OpenAIFrontend:
    """HTTP frontend translating the OpenAI schema onto LLMServer
    deployment handles. `models` maps a model id (the request's `model`
    field) to a serve deployment name hosting it."""

    def __init__(self, models: Dict[str, str], host: str = "127.0.0.1",
                 port: int = 0):
        self.models = dict(models)
        self.created = int(time.time())
        frontend = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                rid = getattr(self, "_request_id", None)
                if rid:
                    self.send_header("x-request-id", rid)
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, message: str, etype: str,
                       retry_after: Optional[int] = None) -> None:
                err: Dict[str, Any] = {
                    "message": message, "type": etype, "param": None,
                    "code": None,
                }
                rid = getattr(self, "_request_id", None)
                if rid:
                    # the forensics key lands NEXT TO Retry-After so a
                    # shed/timed-out client can quote it to
                    # `ray_tpu request <id>`
                    err["request_id"] = rid
                body = json.dumps({"error": err}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                if rid:
                    self.send_header("x-request-id", rid)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 - /v1/models
                if self.path.rstrip("/") == "/v1/models":
                    self._json(200, {
                        "object": "list",
                        "data": [
                            {"id": mid, "object": "model",
                             "created": frontend.created,
                             "owned_by": "ray_tpu"}
                            for mid in frontend.models
                        ],
                    })
                else:
                    self._error(404, f"no route {self.path}", "invalid_request_error")

            def do_POST(self):  # noqa: N802
                # stable end-to-end request id: the caller's x-request-id
                # wins (idempotent client retries keep one forensics
                # timeline); otherwise mint one here, at first touch
                self._request_id = (
                    self.headers.get("x-request-id")
                    or reqlog.new_request_id()
                )
                reqlog.mark(self._request_id, "http.received",
                            path=self.path.rstrip("/"))
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except Exception:
                    self._error(400, "request body is not valid JSON",
                                "invalid_request_error")
                    return
                path = self.path.rstrip("/")
                try:
                    if path == "/v1/completions":
                        frontend._completions(self, req, chat=False)
                    elif path == "/v1/chat/completions":
                        frontend._completions(self, req, chat=True)
                    else:
                        self._error(404, f"no route {path}",
                                    "invalid_request_error")
                except KeyError as e:
                    self._error(404, f"model not found: {e}",
                                "invalid_request_error")
                except ValueError as e:
                    self._error(400, str(e), "invalid_request_error")
                except Exception as e:  # noqa: BLE001 - schema'd 500
                    mapped = _http_status_for(e)
                    cause = unwrap_error(e)
                    if mapped is not None:
                        code, etype, retry_after = mapped
                        self._error(code, str(cause), etype,
                                    retry_after=retry_after)
                    elif isinstance(cause, ValueError):
                        # replica-side validation (e.g. max_tokens over the
                        # engine budget) crosses the actor boundary wrapped
                        # in TaskError: still the client's 400, not a 500
                        self._error(400, str(cause), "invalid_request_error")
                    else:
                        self._error(500, repr(e), "internal_error")

        self._server = EgresslessHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True,
            name="openai-http",
        )
        self._thread.start()

    # ------------------------------------------------------------ translate

    def _handle_for(self, model_id: str):
        if model_id not in self.models:
            raise KeyError(model_id)
        return serve_api.get_handle(self.models[model_id])

    @staticmethod
    def _to_payload(req: Dict[str, Any], chat: bool) -> Dict[str, Any]:
        if chat:
            messages = req.get("messages")
            if not isinstance(messages, list) or not messages:
                raise ValueError("'messages' must be a non-empty list")
            prompt_tokens = ByteTokenizer.encode(_chat_prompt(messages))
        else:
            prompt = req.get("prompt")
            if isinstance(prompt, str):
                prompt_tokens = ByteTokenizer.encode(prompt)
            elif isinstance(prompt, list) and all(
                isinstance(t, int) for t in prompt
            ):
                prompt_tokens = prompt  # OpenAI's token-array form
            else:
                raise ValueError("'prompt' must be a string or token list")
        payload: Dict[str, Any] = {
            "prompt_tokens": prompt_tokens,
            "max_tokens": int(req.get("max_tokens", 16)),
            "temperature": float(req.get("temperature", 1.0)),
        }
        if "top_p" in req:
            payload["top_p"] = float(req["top_p"])
        if "stop_token_ids" in req:
            payload["stop_token_ids"] = list(req["stop_token_ids"])
        stop = req.get("stop")
        if isinstance(stop, str):
            stop = [stop]
        if isinstance(stop, list):
            # single-byte stop strings map onto stop_token_ids; longer
            # ones ship as token sequences the engine matches over the
            # decoded tail (engine.py _hit_stop_sequence)
            for item in stop:
                ids = ByteTokenizer.encode(str(item))
                if not ids:
                    continue
                if len(ids) == 1:
                    payload.setdefault("stop_token_ids", []).append(ids[0])
                else:
                    payload.setdefault("stop_sequences", []).append(ids)
        return payload

    @staticmethod
    def _stop_strings(req: Dict[str, Any]) -> List[str]:
        stop = req.get("stop")
        if isinstance(stop, str):
            stop = [stop]
        if not isinstance(stop, list):
            return []
        return [str(s) for s in stop if str(s)]

    @staticmethod
    def _truncate_at_stop(text: str, stops: List[str]):
        """OpenAI semantics: the stop sequence itself is never returned.
        Returns (text up to the earliest stop occurrence, hit?)."""
        cut = None
        for s in stops:
            i = text.find(s)
            if i >= 0 and (cut is None or i < cut):
                cut = i
        if cut is None:
            return text, False
        return text[:cut], True

    def _completions(self, http, req: Dict[str, Any], chat: bool) -> None:
        from ... import api as core_api

        model_id = req.get("model") or next(iter(self.models))
        handle = self._handle_for(model_id)
        # `timeout_s` (our extension to the OpenAI schema) sets the
        # request's end-to-end deadline; cfg.serve_default_timeout_s
        # applies when absent. Expiry surfaces as HTTP 504.
        if "timeout_s" in req:
            handle = handle.options(timeout_s=float(req["timeout_s"]))
        # tenant context: the tenant header (cfg.serve_tenant_header) or
        # a registered API key resolves the caller; it rides the handle
        # into the engine's fair queue / quota bucket
        from .. import tenancy

        tenant, priority = tenancy.resolve_http_tenant(http.headers)
        if tenant is not None or priority is not None:
            handle = handle.options(tenant=tenant, priority=priority)
        request_id = getattr(http, "_request_id", None)
        if request_id:
            handle = handle.options(request_id=request_id)
        payload = self._to_payload(req, chat)
        rid = ("chatcmpl-" if chat else "cmpl-") + uuid.uuid4().hex[:24]
        created = int(time.time())
        obj = "chat.completion" if chat else "text_completion"

        stops = self._stop_strings(req)
        if req.get("stream"):
            self._stream_sse(http, handle, payload, rid, created, model_id,
                             chat, stops)
            return
        result = core_api.get(handle.generate.remote(payload), timeout=300)
        text = ByteTokenizer.decode(result["tokens"])
        text, stopped = self._truncate_at_stop(text, stops)
        finish = (
            "length"
            if not stopped
            and result["usage"]["completion_tokens"] >= payload["max_tokens"]
            else "stop"
        )
        choice: Dict[str, Any] = {"index": 0, "finish_reason": finish,
                                  "logprobs": None}
        if chat:
            choice["message"] = {"role": "assistant", "content": text}
        else:
            choice["text"] = text
        http._json(200, {
            "id": rid, "object": obj, "created": created, "model": model_id,
            "choices": [choice], "usage": result["usage"],
            "request_id": request_id,
        })

    def _stream_sse(self, http, handle, payload, rid, created, model_id,
                    chat, stops: Optional[List[str]] = None) -> None:
        """Server-sent events, OpenAI stream shape: one chunk per token,
        a final usage-bearing chunk, then `data: [DONE]`.

        Stop strings are enforced here too: decoded text that could be
        the prefix of a stop string is held back until it either
        completes the stop (dropped, stream finishes with
        finish_reason="stop") or diverges (flushed)."""
        from ... import api as core_api

        obj = "chat.completion.chunk" if chat else "text_completion"
        stream = handle.options(stream=True).stream_generate.remote(payload)
        http.send_response(200)
        http.send_header("Content-Type", "text/event-stream")
        http.send_header("Cache-Control", "no-cache")
        http.send_header("Transfer-Encoding", "chunked")
        rid_hdr = getattr(http, "_request_id", None)
        if rid_hdr:
            http.send_header("x-request-id", rid_hdr)
        http.end_headers()

        def send(data: str) -> None:
            write_chunk(http.wfile, f"data: {data}\n\n".encode())

        def chunk_body(choice: Dict[str, Any], usage=None) -> str:
            body = {
                "id": rid, "object": obj, "created": created,
                "model": model_id, "choices": [choice],
            }
            if usage is not None:
                body["usage"] = usage
            return json.dumps(body)

        import codecs

        # incremental decode: a multi-byte UTF-8 character split across
        # byte-tokens must not degrade to U+FFFD per byte — buffer until
        # the sequence completes, exactly like the non-streamed decode
        decoder = codecs.getincrementaldecoder("utf-8")("replace")
        stops = stops or []
        buf = ""  # decoded text held back as a possible stop prefix
        stopped = False

        def holdback(text: str) -> int:
            """Longest suffix of `text` that is a proper prefix of some
            stop string (must be withheld until it resolves)."""
            hold = 0
            for s in stops:
                for k in range(min(len(s) - 1, len(text)), hold, -1):
                    if text.endswith(s[:k]):
                        hold = k
                        break
            return hold

        def text_choice(text: str) -> Dict[str, Any]:
            if chat:
                return {"index": 0, "finish_reason": None,
                        "delta": {"content": text}}
            return {"index": 0, "finish_reason": None,
                    "logprobs": None, "text": text}

        try:
            for ref in stream:
                item = core_api.get(ref, timeout=300)
                if "token" in item:
                    tok = item["token"]
                    if stopped or not 0 <= tok < 256:
                        continue  # same contract as ByteTokenizer.decode
                    piece = decoder.decode(bytes([tok]))
                    if not piece:
                        continue  # mid-sequence: held back
                    buf += piece
                    buf, hit = self._truncate_at_stop(buf, stops)
                    if hit:
                        stopped = True
                        hold = 0
                    else:
                        hold = holdback(buf)
                    emit_now = buf[: len(buf) - hold] if hold else buf
                    buf = buf[len(buf) - hold:] if hold else ""
                    if emit_now:
                        send(chunk_body(text_choice(emit_now)))
                elif item.get("done"):
                    tail = "" if stopped else decoder.decode(b"", final=True)
                    # held-back text before a stop still ships; the stop
                    # string itself never does
                    tail, hit = self._truncate_at_stop(buf + tail, stops)
                    stopped = stopped or hit
                    usage = item.get("usage") or {}
                    finish = (
                        "stop" if stopped else (
                            "length"
                            if usage.get("completion_tokens", 0)
                            >= payload["max_tokens"] else "stop"
                        )
                    )
                    final = {"index": 0, "finish_reason": finish}
                    if chat:
                        final["delta"] = (
                            {"content": tail} if tail else {}
                        )
                    else:
                        final["text"] = tail
                        final["logprobs"] = None
                    send(chunk_body(final, usage=item.get("usage")))
        except Exception as e:  # noqa: BLE001 - surfaces as an SSE error event
            mapped = _http_status_for(e)
            etype = mapped[1] if mapped is not None else "internal_error"
            send(json.dumps({"error": {"message": repr(unwrap_error(e)),
                                       "type": etype}}))
        send("[DONE]")
        http.wfile.write(b"0\r\n\r\n")

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def build_openai_app(
    models: Optional[Dict[str, Any]] = None,
    *,
    model: Any = "gpt2-tiny",
    paged: bool = True,
    max_slots: int = 8,
    num_replicas: int = 1,
    tensor_parallel: int = 1,
):
    """Deploy LLM app(s) and return the (not-yet-served) route table.
    `models` maps model ids to model names/configs; the single-`model`
    form mirrors the reference's one-model build_openai_app. Run with
    `serve_openai(...)` or serve.run + OpenAIFrontend."""
    from .server import build_llm_app

    specs = models or {str(model): model}
    routes: Dict[str, str] = {}
    apps = []
    for model_id, m in specs.items():
        name = f"openai-{model_id}".replace("/", "-")
        apps.append(build_llm_app(
            m, name=name, num_replicas=num_replicas, max_slots=max_slots,
            paged=paged, tensor_parallel=tensor_parallel,
        ))
        routes[model_id] = name
    return apps, routes


def serve_openai(
    models: Optional[Dict[str, Any]] = None,
    *,
    model: Any = "gpt2-tiny",
    host: str = "127.0.0.1",
    port: int = 0,
    **build_kwargs,
) -> OpenAIFrontend:
    """One-call OpenAI endpoint: deploy the app(s) and serve /v1/* on
    `port`. Returns the frontend (``.port``, ``.stop()``)."""
    apps, routes = build_openai_app(models, model=model, **build_kwargs)
    for app, name in zip(apps, routes.values()):
        serve_api.run(app, name=name)
    return OpenAIFrontend(routes, host=host, port=port)
