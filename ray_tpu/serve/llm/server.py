"""LLM deployment: the serve-facing wrapper around LLMEngine.

Reference parity: LLMServer/VLLMEngine deployment (llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py:254) + build_openai_app
(serve/llm/__init__.py). Token-id interface: this image has no tokenizer
vocab files (zero egress), so text encode/decode is the caller's concern —
the OpenAI-style payload carries `prompt_tokens` instead of `prompt`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax

from ...models import get_config, init_params
from ...models.transformer import TransformerConfig
from ..deployment import Application, deployment
from .engine import EngineConfig, LLMEngine
from .paged_engine import PagedEngineConfig, PagedLLMEngine


class LLMServer:
    """Deployment class hosting one engine (one model replica).

    engine_config selects the engine: PagedEngineConfig → paged KV pool
    with chunked prefill (the vLLM-class default for real serving),
    EngineConfig → the dense slot-grid engine (simplest, fixed HBM)."""

    def __init__(
        self,
        model: str | TransformerConfig = "gpt2-tiny",
        params: Any = None,
        engine_config: Optional[EngineConfig | PagedEngineConfig] = None,
        seed: int = 0,
        tensor_parallel: int = 1,
    ):
        config = get_config(model) if isinstance(model, str) else model
        if params is None:
            params = init_params(config, jax.random.PRNGKey(seed))
        self.model_config = config
        mesh = None
        if tensor_parallel > 1:
            from ...parallel import MeshSpec, build_mesh

            mesh = build_mesh(
                MeshSpec(tp=tensor_parallel),
                devices=jax.devices()[:tensor_parallel],
            )
        if isinstance(engine_config, PagedEngineConfig):
            self.engine = PagedLLMEngine(config, params, engine_config, mesh=mesh)
        else:
            if mesh is not None:
                raise ValueError(
                    "tensor_parallel requires the paged engine "
                    "(engine_config=PagedEngineConfig(...))"
                )
            self.engine = LLMEngine(config, params, engine_config)

    def _submit(self, payload: Dict[str, Any]):
        """One place parses the OpenAI-ish payload for both entry points
        (sampling params flow to the paged engine). The serve request's
        ambient deadline (router timeout_s → replica context) rides into
        the engine so an expired request is cancelled/evicted instead of
        generating into the void."""
        from ..context import (
            get_request_deadline,
            get_request_id,
            get_request_priority,
            get_request_tenant,
        )

        prompt = payload["prompt_tokens"]
        kwargs = {"deadline_ts": get_request_deadline()}
        # end-to-end forensics id: ambient (threaded by the router) wins,
        # payload field is the fallback for direct callers
        request_id = get_request_id() or payload.get("request_id")
        if request_id:
            kwargs["request_id"] = str(request_id)
        # tenant context rides the same ambient channel the deadline does;
        # payload fields are the fallback for direct (non-handle) callers
        tenant = get_request_tenant() or payload.get("tenant")
        if tenant:
            kwargs["tenant"] = str(tenant)
        priority = get_request_priority()
        if priority is None and "priority" in payload:
            priority = int(payload["priority"])
        if priority is not None:
            kwargs["priority"] = int(priority)
        for name, cast in (("top_k", int), ("top_p", float),
                           ("stop_token_ids", list),
                           ("stop_sequences", list)):
            if name in payload:
                kwargs[name] = cast(payload[name])
        stream = self.engine.submit(
            prompt,
            int(payload.get("max_tokens", 64)),
            float(payload.get("temperature", 0.0)),
            **kwargs,
        )
        return prompt, stream

    @staticmethod
    def _usage(prompt, n: int) -> Dict[str, int]:
        return {
            "prompt_tokens": len(prompt),
            "completion_tokens": n,
            "total_tokens": len(prompt) + n,
        }

    def generate(self, payload: Dict[str, Any]) -> Dict[str, Any]:
        """{"prompt_tokens": [...], "max_tokens": n, "temperature": t} →
        {"tokens": [...], "usage": {...}} (OpenAI-completions shaped)."""
        prompt, stream = self._submit(payload)
        tokens = stream.result()
        return {
            "tokens": tokens,
            "usage": self._usage(prompt, len(tokens)),
            "ttft_s": stream.ttft_s,
            "request_id": stream.request_id,
        }

    def stream_generate(self, payload: Dict[str, Any]):
        """Token-streaming variant (OpenAI stream=true shape): yields one
        {"token": id} per generated token as the engine produces it, then
        a final {"done": true, "usage": ...}. Use through a streaming
        handle (serve streaming) or HTTP ?stream=1."""
        prompt, stream = self._submit(payload)
        n = 0
        for token in stream:
            n += 1
            yield {"token": token}
        yield {
            "done": True,
            "usage": self._usage(prompt, n),
            "ttft_s": stream.ttft_s,
            "request_id": stream.request_id,
        }

    def metrics(self, _payload: Optional[Dict[str, Any]] = None) -> Dict[str, float]:
        return dict(self.engine.metrics)

    def check_health(self) -> None:
        if not self.engine._thread.is_alive():
            raise RuntimeError("engine loop died")

    def __del__(self):
        try:
            self.engine.shutdown()
        except Exception:
            pass


def build_llm_app(
    model: str | TransformerConfig = "gpt2-tiny",
    *,
    name: str = "llm",
    num_replicas: int = 1,
    max_slots: int = 8,
    params: Any = None,
    paged: bool = False,
    tensor_parallel: int = 1,
) -> Application:
    """OpenAI-compatible app builder (reference build_openai_app).
    tensor_parallel > 1 shards each replica's paged engine over a tp mesh
    (reference: vLLM TP workers via placement groups, vllm_models.py:124)."""
    dep = deployment(
        LLMServer, name=name, num_replicas=num_replicas, max_ongoing_requests=max_slots * 2
    )
    if tensor_parallel > 1 and not paged:
        raise ValueError("tensor_parallel requires paged=True")
    engine_config = (
        PagedEngineConfig(max_slots=max_slots) if paged
        else EngineConfig(max_slots=max_slots)
    )
    return dep.bind(model, params, engine_config, 0, tensor_parallel)
