"""Multi-tenant serve plane: tenant registry, weighted-fair queueing,
token-bucket quotas, and per-tenant SLO accounting.

One noisy tenant must not be able to starve every other tenant's TTFT
(ROADMAP item 2). This module is the shared substrate the serve stack
composes for tenant-level graceful degradation:

- **TenantSpec registry** — weight (fair share), priority (preemption
  eligibility only, never queue order within a tier... see FairQueue),
  token-bucket quota (rate/burst), per-tenant TTFT SLO objective, and an
  API-key → tenant map the OpenAI frontend resolves bearer tokens with.
- **FairQueue** — priority-tiered start-time fair queueing (SCFQ) used
  at both admission choke points: the router's parked dispatch queue and
  the paged engine's admit queue.
- **Token buckets** — per-tenant rate limiting applied at engine
  admission; sheds raise the typed ``BackPressureError`` carrying the
  bucket's actual refill time so HTTP 429s compute ``Retry-After``
  honestly instead of a fixed constant.
- **TTFT windows** — engines report each request's time-to-first-token
  here; ``ServeSLOMonitor`` drains the window every check period and
  maintains per-tenant attainment gauges + burn, so autoscaling responds
  to paying-tenant pain rather than aggregate load.

Replicas run in-process with the router (actors share the process), so
this module-level registry is a genuinely shared control surface; in a
multi-process deployment each replica process holds its own copy seeded
from config defaults, which degrades to per-process quotas — the same
trade the engine admit bound already makes.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TENANT = "default"


# ---------------------------------------------------------------------------
# tenant registry


@dataclass
class TenantSpec:
    """Declared shape of one tenant. Zero/negative sentinel fields fall
    back to the fleet-wide config defaults at read time (``weight_of`` /
    ``quota_of`` / ``ttft_objective``)."""

    name: str
    weight: float = 0.0        # 0 = cfg.serve_tenant_default_weight
    priority: int = 0          # preemption tier; higher preempts lower
    quota_rps: float = -1.0    # -1 = cfg.serve_tenant_quota_rps; 0 = unlimited
    quota_burst: float = 0.0   # 0 = auto (max(1, 2x rate))
    ttft_slo_s: float = 0.0    # 0 = cfg.serve_slo_ttft_p99_s


class _TokenBucket:
    """Classic token bucket: ``acquire()`` returns None when a token was
    available (request admitted) or the seconds until one token refills —
    the honest Retry-After a 429 should carry."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self._lock = threading.Lock()
        self._tokens = float(burst)  # guarded-by: _lock
        self._stamp = time.monotonic()  # guarded-by: _lock

    def acquire(self) -> Optional[float]:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * self.rate
            )
            self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return None
            if self.rate <= 0:
                return 1.0
            return (1.0 - self._tokens) / self.rate


_lock = threading.Lock()
_specs: Dict[str, TenantSpec] = {}  # guarded-by: _lock
_buckets: Dict[str, _TokenBucket] = {}  # guarded-by: _lock
_api_keys: Dict[str, str] = {}  # guarded-by: _lock
_ttft_window: Dict[str, List[float]] = {}  # guarded-by: _lock
_ttft_breakdown: Dict[str, List[Dict[str, float]]] = {}  # guarded-by: _lock
_queue_wait_window: Dict[str, List[float]] = {}  # guarded-by: _lock
_last_shed_event: Dict[str, float] = {}  # guarded-by: _lock


def set_tenant(
    name: str,
    *,
    weight: Optional[float] = None,
    priority: Optional[int] = None,
    quota_rps: Optional[float] = None,
    quota_burst: Optional[float] = None,
    ttft_slo_s: Optional[float] = None,
    api_key: Optional[str] = None,
) -> TenantSpec:
    """Declare (or update) a tenant. Unspecified fields keep their
    previous value; a tenant never has to be declared to send traffic —
    undeclared tenants get the config defaults."""
    with _lock:
        spec_obj = _specs.get(name) or TenantSpec(name=name)
        if weight is not None:
            spec_obj.weight = float(weight)
        if priority is not None:
            spec_obj.priority = int(priority)
        if quota_rps is not None:
            spec_obj.quota_rps = float(quota_rps)
        if quota_burst is not None:
            spec_obj.quota_burst = float(quota_burst)
        if ttft_slo_s is not None:
            spec_obj.ttft_slo_s = float(ttft_slo_s)
        _specs[name] = spec_obj
        # quota changed: rebuild the bucket lazily on next check
        _buckets.pop(name, None)
        if api_key is not None:
            _api_keys[api_key] = name
        return spec_obj


def spec(name: str) -> TenantSpec:
    with _lock:
        return _specs.get(name) or TenantSpec(name=name)


def reset() -> None:
    """Drop all declared tenants, buckets, API keys, and TTFT windows
    (test isolation)."""
    with _lock:
        _specs.clear()
        _buckets.clear()
        _api_keys.clear()
        _ttft_window.clear()
        _ttft_breakdown.clear()
        _queue_wait_window.clear()
        _last_shed_event.clear()


def weight_of(tenant: str) -> float:
    from ..core.config import cfg

    with _lock:
        spec_obj = _specs.get(tenant)
    w = spec_obj.weight if spec_obj is not None else 0.0
    if w <= 0:
        w = float(cfg.serve_tenant_default_weight) or 1.0
    return max(w, 1e-6)


def priority_of(tenant: str) -> int:
    with _lock:
        spec_obj = _specs.get(tenant)
    return spec_obj.priority if spec_obj is not None else 0


def ttft_objective(tenant: str) -> float:
    from ..core.config import cfg

    with _lock:
        spec_obj = _specs.get(tenant)
    slo = spec_obj.ttft_slo_s if spec_obj is not None else 0.0
    if slo <= 0:
        slo = float(cfg.serve_slo_ttft_p99_s)
    return slo


def any_tenant_slo() -> bool:
    """True when at least one declared tenant carries its own TTFT
    objective (the SLO monitor must run even if fleet SLOs are off)."""
    with _lock:
        return any(s.ttft_slo_s > 0 for s in _specs.values())


# ---------------------------------------------------------------------------
# quotas


def _effective_quota(tenant: str) -> Tuple[float, float]:
    from ..core.config import cfg

    with _lock:
        spec_obj = _specs.get(tenant)
    rate = spec_obj.quota_rps if spec_obj is not None else -1.0
    if rate < 0:
        rate = float(cfg.serve_tenant_quota_rps)
    burst = spec_obj.quota_burst if spec_obj is not None else 0.0
    if burst <= 0:
        burst = max(1.0, 2.0 * rate)
    return rate, burst


def quota_check(tenant: str) -> Optional[float]:
    """Charge one request against the tenant's token bucket. Returns None
    when admitted, else the seconds until a token refills (the computed
    Retry-After). A zero rate means unlimited."""
    rate, burst = _effective_quota(tenant)
    if rate <= 0:
        return None
    with _lock:
        bucket = _buckets.get(tenant)
        if bucket is None or bucket.rate != rate or bucket.burst != burst:
            bucket = _TokenBucket(rate, burst)
            _buckets[tenant] = bucket
    return bucket.acquire()


def count_shed(tenant: str, retry_after_s: Optional[float] = None) -> None:
    """Attribute one shed to the tenant: per-tenant counter plus a
    rate-limited serve.shed event (at most one per tenant per second so a
    flooding tenant cannot flood the flight recorder too)."""
    from ..util.events import emit
    from ..util.metrics import get_or_create_counter

    get_or_create_counter(
        "raytpu_serve_tenant_shed_total",
        "Requests shed by admission control, by tenant.",
        tag_keys=("tenant",),
    ).inc(tags={"tenant": tenant})
    now = time.monotonic()
    with _lock:
        last = _last_shed_event.get(tenant, 0.0)
        if now - last < 1.0:
            return
        _last_shed_event[tenant] = now
    emit(
        "WARNING",
        "serve",
        f"shedding tenant {tenant!r} (retry_after_s={retry_after_s})",
        kind="serve.shed",
        tenant=tenant,
        retry_after_s=retry_after_s,
    )


def count_request(tenant: str) -> None:
    from ..util.metrics import get_or_create_counter

    get_or_create_counter(
        "raytpu_serve_tenant_requests_total",
        "Requests admitted to an engine, by tenant.",
        tag_keys=("tenant",),
    ).inc(tags={"tenant": tenant})


# ---------------------------------------------------------------------------
# per-tenant TTFT windows (drained by ServeSLOMonitor)


def observe_ttft(tenant: str, ttft_s: float) -> None:
    """Engines call this at first token; the SLO monitor drains the
    window each check period. Bounded per tenant so a monitor that never
    runs cannot leak."""
    with _lock:
        window = _ttft_window.setdefault(tenant, [])
        if len(window) < 100_000:
            window.append(float(ttft_s))


def drain_ttft_window() -> Dict[str, List[float]]:
    with _lock:
        out = _ttft_window.copy()
        _ttft_window.clear()
    return out


def observe_ttft_breakdown(tenant: str, buckets: Dict[str, float]) -> None:
    """Record one request's TTFT decomposition (engine._ttft_buckets:
    queue_wait / preempt_wait / prefill_compute, summing to TTFT) for the
    SLO monitor to attribute burn to the dominant bucket. Same bound and
    drain cadence as the plain TTFT window."""
    with _lock:
        window = _ttft_breakdown.setdefault(tenant, [])
        if len(window) < 100_000:
            window.append(dict(buckets))
        qw = _queue_wait_window.setdefault(tenant, [])
        if len(qw) < 100_000:
            qw.append(float(buckets.get("queue_wait_s", 0.0)))


def drain_ttft_breakdown() -> Dict[str, List[Dict[str, float]]]:
    with _lock:
        out = _ttft_breakdown.copy()
        _ttft_breakdown.clear()
    return out


def drain_queue_wait_window() -> Dict[str, List[float]]:
    """Per-tenant queue-wait samples (the queue_wait_s bucket of each
    first token), drained by the SLO monitor for queue_wait_p99."""
    with _lock:
        out = _queue_wait_window.copy()
        _queue_wait_window.clear()
    return out


# ---------------------------------------------------------------------------
# HTTP surfacing


def resolve_http_tenant(headers: Any) -> Tuple[Optional[str], Optional[int]]:
    """Resolve (tenant, priority) from HTTP request headers: the tenant
    header (cfg.serve_tenant_header, default 'x-tenant') wins, else an
    'Authorization: Bearer <key>' token registered via
    set_tenant(api_key=...). Priority comes from 'x-priority' or the
    tenant's declared spec."""
    from ..core.config import cfg

    tenant = headers.get(cfg.serve_tenant_header) if headers is not None else None
    if not tenant:
        auth = headers.get("Authorization") if headers is not None else None
        if auth and auth.lower().startswith("bearer "):
            key = auth[7:].strip()
            with _lock:
                tenant = _api_keys.get(key)
    priority: Optional[int] = None
    raw = headers.get("x-priority") if headers is not None else None
    if raw is not None:
        try:
            priority = int(raw)
        except (TypeError, ValueError):
            priority = None
    if tenant and priority is None:
        priority = priority_of(tenant)
    return tenant or None, priority


# ---------------------------------------------------------------------------
# weighted-fair queueing


class FairQueue:
    """Priority-tiered, weighted-fair queue (start-time fair queueing /
    SCFQ, per Golestani '94). Items land in a per-(priority, tenant)
    lane; each push stamps a virtual finish tag
    ``F = max(V_tier, F_lane) + cost/weight``. Pop serves the highest
    priority tier that has items; within the tier, the lane whose head
    carries the smallest finish tag wins, and the tier's virtual clock
    advances to that tag.

    Properties the serve plane leans on:
    - **weight-proportional**: a tenant with weight w accrues virtual
      time at 1/w per item, so sustained backlogs drain in proportion to
      the weights;
    - **starvation-free within a tier**: a flooding tenant's lane races
      ahead in virtual time and defers to lighter lanes — every queued
      item's finish tag is eventually the minimum;
    - **work-conserving**: an idle lane restarts at the tier's current
      virtual clock (no banked credit, no penalty), and pop never
      returns None while any lane has items.

    Thread-safe; every mutation is under ``_lock``. ``requeue`` returns
    a previously-popped item to the *front* of its lane without a fresh
    virtual-time charge — deferred admissions (page stalls, preempted
    lanes) keep their place instead of paying twice.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._lanes: Dict[Tuple[int, str], deque] = {}  # guarded-by: _lock
        self._finish: Dict[Tuple[int, str], float] = {}  # guarded-by: _lock
        self._vtime: Dict[int, float] = {}  # guarded-by: _lock
        self._count = 0  # guarded-by: _lock

    def push(
        self,
        item: Any,
        tenant: str = DEFAULT_TENANT,
        priority: int = 0,
        weight: Optional[float] = None,
        cost: float = 1.0,
    ) -> None:
        w = float(weight) if weight is not None and weight > 0 else weight_of(tenant)
        key = (int(priority), str(tenant))
        with self._lock:
            vtime = self._vtime.get(key[0], 0.0)
            start = max(vtime, self._finish.get(key, 0.0))
            fin = start + float(cost) / w
            self._finish[key] = fin
            self._lanes.setdefault(key, deque()).append((fin, item))
            self._count += 1

    def requeue(
        self, item: Any, tenant: str = DEFAULT_TENANT, priority: int = 0
    ) -> None:
        key = (int(priority), str(tenant))
        with self._lock:
            lane = self._lanes.setdefault(key, deque())
            fin = lane[0][0] if lane else self._vtime.get(key[0], 0.0)
            lane.appendleft((fin, item))
            self._count += 1

    def _head_key(self) -> Optional[Tuple[int, str]]:  # holds-lock: _lock
        best_rank = None
        best_key = None
        for key, lane in self._lanes.items():
            if not lane:
                continue
            rank = (-key[0], lane[0][0])
            if best_rank is None or rank < best_rank:
                best_rank, best_key = rank, key
        return best_key

    def peek(self) -> Optional[Any]:
        with self._lock:
            key = self._head_key()
            return self._lanes[key][0][1] if key is not None else None

    def pop(self) -> Optional[Any]:
        with self._lock:
            key = self._head_key()
            if key is None:
                return None
            return self._pop_from(key)

    def _pop_from(self, key: Tuple[int, str]) -> Any:  # holds-lock: _lock
        fin, item = self._lanes[key].popleft()
        if not self._lanes[key]:
            del self._lanes[key]
            # a drained lane's stale finish tag only matters until the
            # tier clock passes it; drop it then to bound the dict
            if self._finish.get(key, 0.0) <= self._vtime.get(key[0], 0.0):
                self._finish.pop(key, None)
        tier = key[0]
        self._vtime[tier] = max(self._vtime.get(tier, 0.0), fin)
        self._count -= 1
        return item

    def pop_if_head(self, item: Any) -> bool:
        """Pop and return True iff `item` is the current weighted-fair
        head (identity comparison). Lets an external granter dispatch
        strictly in fair order without a TOCTOU window."""
        with self._lock:
            key = self._head_key()
            if key is None or self._lanes[key][0][1] is not item:
                return False
            self._pop_from(key)
            return True

    def remove(self, item: Any) -> bool:
        with self._lock:
            for key, lane in self._lanes.items():
                for entry in lane:
                    if entry[1] is item:
                        lane.remove(entry)
                        self._count -= 1
                        if not lane:
                            del self._lanes[key]
                        return True
        return False

    def drain(self) -> List[Any]:
        """Pop everything in fair order (engine-death and shutdown
        paths)."""
        out = []
        while True:
            item = self.pop()
            if item is None:
                return out
            out.append(item)

    def depths(self) -> List[Dict[str, Any]]:
        """Per-lane queue depths for engine introspection
        (``engine.snapshot()``): one row per occupied (priority, tenant)
        lane, highest priority first."""
        with self._lock:
            rows = [
                {"priority": key[0], "tenant": key[1], "depth": len(lane)}
                for key, lane in self._lanes.items()
                if lane
            ]
        rows.sort(key=lambda r: (-r["priority"], r["tenant"]))
        return rows

    def __len__(self) -> int:
        with self._lock:
            return self._count
