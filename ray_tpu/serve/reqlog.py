"""Request forensics plane: per-request lifecycle ledger.

The serve path's aggregate observability (histograms, SLO burn) answers
"how slow is the fleet" but not "why was THIS request slow". The
RequestLog records typed PHASE MARKS with both clocks (wall for
cross-node placement, mono for intra-process interval math) along the
whole request path: router receive → fair-queue park/grant → replica
dispatch (incl. failover hops) → engine admit (prefix-cache hit pages)
→ prefill chunks → first token → decode blocks → spec rounds → COW
copies → lane preempt/resume → finish/shed/timeout.

Marks live in a bounded per-node ring plus a bounded per-request
summary index; the cluster heartbeat federates each node's tail into
the GCS ``_requests`` table (core/cluster.py, same piggyback as the
flight recorder), so the head answers ``state.request_timeline(id)`` /
``state.list_requests()`` / ``ray_tpu request <id>`` cluster-wide. The
shared request id also lands on the trace spans, joining the two views.

Phases are TYPED: every ``mark`` names a phase registered in ``PHASES``
(the raylint ``request-phase`` rule holds call sites to the registry,
mirroring ``event-kinds``), so the waterfall renderer and the TTFT
decomposition can rely on phase names instead of parsing messages.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

# ----------------------------------------------------------- phase registry
#
# phase -> one-line doc. Components may register additional phases at
# import time with register_phase (raylint's request-phase rule reads
# both this literal and register_phase("...") call sites).

PHASES: Dict[str, str] = {
    # HTTP frontends (openai.py, serve/api.py)
    "http.received": "an HTTP frontend accepted the request",
    # router (serve/router.py)
    "route.received": "the request entered the router via a handle",
    "route.shed": "the router shed the request (parked-queue bound)",
    "route.parked": "no replica had capacity; parked in the fair queue",
    "route.granted": "the fair queue granted the parked request a slot",
    "route.dispatched": "the router dispatched the call to a replica",
    "route.failover": "the router re-dispatched after a replica failure",
    "route.timeout": "the request deadline expired inside the router",
    "route.failed": "the router sealed a non-retryable failure",
    # engine admission (llm/engine.py, llm/paged_engine.py)
    "engine.submitted": "the engine accepted the request into its queue",
    "engine.shed": "engine admission control shed the request",
    "engine.timeout": "the request deadline expired inside the engine",
    "engine.admitted": "the request was seated in an engine lane",
    "engine.page_stall": "admission stalled waiting for KV pages",
    # engine execution (llm/paged_engine.py)
    "engine.prefill_chunk": "one prompt chunk was ingested",
    "engine.first_token": "the first token was emitted (TTFT point)",
    "engine.decode_block": "a fused decode block completed",
    "engine.spec_round": "a speculative verify round completed",
    "engine.cow": "a copy-on-write page copy before divergence",
    "engine.preempted": "the lane was parked for a higher-priority lane",
    "engine.resumed": "a parked lane was re-admitted",
    "engine.finished": "the request finished and emitted its last token",
}

# Phases that END a request: once one is recorded, the request is no
# longer pending (the satellite fix — shed/expired requests must never
# appear forever-pending in list_requests()).
TERMINAL_PHASES = frozenset({
    "route.shed", "route.timeout", "route.failed",
    "engine.shed", "engine.timeout", "engine.finished",
})


def register_phase(phase: str, doc: str = "") -> None:
    """Register an additional typed request phase (idempotent)."""
    PHASES.setdefault(phase, doc)


def request_phases() -> Dict[str, str]:
    """The registered phase catalog (copy)."""
    return dict(PHASES)


def new_request_id() -> str:
    """A fresh end-to-end request id (the public key threaded
    frontend→router→replica→engine and echoed in responses)."""
    return "req-" + uuid.uuid4().hex[:16]


def _default_node() -> Optional[str]:
    from ..util import logs

    return logs._node_hex


class RequestLog:
    """Per-process request recorder: a bounded mark ring plus a bounded
    per-request summary index (OrderedDict, oldest-evicted-first)."""

    def __init__(self, mark_capacity: int = 4096,
                 request_capacity: int = 1024):
        self._marks: "deque[Dict[str, Any]]" = deque(maxlen=mark_capacity)
        self._requests: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._request_capacity = request_capacity
        self._lock = threading.Lock()
        self._seq = 0

    def mark(self, request_id: str, phase: str,
             node: Optional[str] = None,
             tenant: Optional[str] = None,
             **attrs: Any) -> Dict[str, Any]:
        """Record one typed phase mark. `phase` is a registered PHASES
        name (the raylint request-phase rule enforces this statically —
        at runtime unknown phases are still recorded)."""
        if node is None:
            node = _default_node()
        with self._lock:
            self._seq += 1
            rec: Dict[str, Any] = {
                "seq": self._seq,
                "rid": request_id,
                "phase": phase,
                "ts": time.time(),
                "mono": time.perf_counter(),
                "node": node,
            }
            if tenant is not None:
                rec["tenant"] = tenant
            if attrs:
                rec["attrs"] = attrs
            self._marks.append(rec)
            self._index_locked(rec)
        return rec

    def _index_locked(self, rec: Dict[str, Any]) -> None:
        rid = rec["rid"]
        summary = self._requests.get(rid)
        if summary is None:
            summary = {
                "request_id": rid,
                "tenant": rec.get("tenant"),
                "node": rec.get("node"),
                "first_ts": rec["ts"],
                "last_ts": rec["ts"],
                "first_phase": rec["phase"],
                "last_phase": rec["phase"],
                "marks": 0,
                "terminal": None,
                "ttft_s": None,
            }
            self._requests[rid] = summary
            while len(self._requests) > self._request_capacity:
                self._requests.popitem(last=False)
        summary["marks"] += 1
        summary["last_ts"] = rec["ts"]
        summary["last_phase"] = rec["phase"]
        if rec.get("tenant") is not None:
            summary["tenant"] = rec["tenant"]
        # first terminal wins: a late straggler mark must not resurrect
        # a shed/timed-out request into a different outcome
        if rec["phase"] in TERMINAL_PHASES and summary["terminal"] is None:
            summary["terminal"] = rec["phase"]
        if rec["phase"] == "engine.first_token":
            attrs = rec.get("attrs") or {}
            summary["ttft_s"] = attrs.get("ttft_s")
            summary["buckets"] = {
                k: attrs[k]
                for k in ("queue_wait_s", "preempt_wait_s",
                          "prefill_compute_s", "cache_saved_s")
                if k in attrs
            }

    # --------------------------------------------------------------- queries

    def timeline(self, request_id: str) -> List[Dict[str, Any]]:
        """Every buffered mark of one request, oldest first."""
        with self._lock:
            return [m for m in self._marks if m["rid"] == request_id]

    def requests(self, tenant: Optional[str] = None,
                 slow_only: bool = False,
                 limit: int = 200) -> List[Dict[str, Any]]:
        """Request summaries, newest last. `slow_only` keeps requests
        whose TTFT exceeded the serve SLO objective or that timed out."""
        from ..core.config import cfg

        slo = cfg.serve_slo_ttft_p99_s
        with self._lock:
            out = [dict(s) for s in self._requests.values()]
        if tenant is not None:
            out = [s for s in out if s.get("tenant") == tenant]
        if slow_only:
            out = [
                s for s in out
                if (s.get("ttft_s") is not None and s["ttft_s"] > slo)
                or s.get("terminal") in ("route.timeout", "engine.timeout")
            ]
        return out[-limit:]

    def since(self, seq: int, max_n: int = 1000) -> List[Dict[str, Any]]:
        """The OLDEST max_n marks with seq greater than `seq` — the
        federation cursor walk (same contract as EventLog.since)."""
        with self._lock:
            return [m for m in self._marks if m["seq"] > seq][:max_n]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "seq": self._seq,
                "buffered_marks": len(self._marks),
                "indexed_requests": len(self._requests),
            }

    def clear(self) -> None:
        with self._lock:
            self._marks.clear()
            self._requests.clear()


# ------------------------------------------------------- module singleton

_reqlog: Optional[RequestLog] = None
_reqlog_lock = threading.Lock()


def log() -> RequestLog:
    global _reqlog
    with _reqlog_lock:
        if _reqlog is None:
            from ..core.config import cfg

            _reqlog = RequestLog(
                mark_capacity=cfg.serve_request_log_marks,
                request_capacity=cfg.serve_request_log_requests,
            )
        return _reqlog


def enabled() -> bool:
    from ..core.config import cfg

    return bool(cfg.serve_request_log)


def mark(request_id: Optional[str], phase: str,
         tenant: Optional[str] = None, **attrs: Any) -> None:
    """Fast-path module-level mark: no-op when the request has no id
    (recorder off at ingress) or the recorder is disabled."""
    if request_id is None or not enabled():
        return
    log().mark(request_id, phase, tenant=tenant, **attrs)


# ------------------------------------------------------- derived views


def summarize_marks(marks: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Build request summaries from a flat mark list (the federated
    path: other nodes' marks arrive via the GCS table without their
    summary index)."""
    scratch = RequestLog(mark_capacity=len(marks) + 1,
                         request_capacity=len(marks) + 1)
    with scratch._lock:
        for m in sorted(marks, key=lambda m: (m.get("ts", 0.0),
                                              m.get("seq", 0))):
            scratch._index_locked(m)
        return [dict(s) for s in scratch._requests.values()]


def decompose(marks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """TTFT decomposition of one request's timeline: the bucket attrs
    the engine attached at the first-token mark (queue_wait +
    preempt_wait + prefill_compute sum to the measured TTFT by
    construction; cache_saved is the informational estimate of what the
    prefix cache skipped, NOT part of the sum)."""
    for m in marks:
        if m.get("phase") == "engine.first_token":
            attrs = dict(m.get("attrs") or {})
            return attrs
    return {}


def render_waterfall(marks: List[Dict[str, Any]]) -> str:
    """Causally-ordered text waterfall of one request's marks: relative
    wall-clock offsets, per-mark attrs, and the TTFT decomposition
    footer. Marks from several nodes interleave on wall time (the same
    ordering the postmortem timeline uses for cross-node placement)."""
    if not marks:
        return "(no marks)"
    marks = sorted(marks, key=lambda m: (m.get("ts", 0.0), m.get("seq", 0)))
    rid = marks[0].get("rid", "?")
    tenant = next((m["tenant"] for m in marks if m.get("tenant")), None)
    t0 = marks[0].get("ts", 0.0)
    span = max(m.get("ts", t0) for m in marks) - t0
    lines = [
        f"request {rid}"
        + (f" · tenant {tenant}" if tenant else "")
        + f" · {len(marks)} mark(s) · {span:.3f}s"
    ]
    width = 28
    for m in marks:
        off = m.get("ts", t0) - t0
        bar_at = 0 if span <= 0 else int((off / span) * (width - 1))
        bar = " " * bar_at + "|"
        node = str(m.get("node") or "")[:8]
        attrs = m.get("attrs") or {}
        attr_txt = " ".join(
            f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
            for k, v in attrs.items()
        )
        lines.append(
            f"  +{off:9.4f}s {bar:<{width}} {m['phase']:<21}"
            f" {node:<8} {attr_txt}".rstrip()
        )
    d = decompose(marks)
    if d.get("ttft_s") is not None:
        parts = " + ".join(
            f"{k[:-2]} {d.get(k, 0.0):.4f}"
            for k in ("queue_wait_s", "preempt_wait_s", "prefill_compute_s")
        )
        cache = (
            f" (cache_saved ~{d['cache_saved_s']:.4f}s,"
            f" cached_tokens {d.get('cached_tokens', 0)})"
            if d.get("cache_saved_s") else ""
        )
        lines.append(f"  TTFT {d['ttft_s']:.4f}s = {parts}{cache}")
    terminal = next(
        (m["phase"] for m in marks if m["phase"] in TERMINAL_PHASES), None
    )
    if terminal:
        lines.append(f"  terminal: {terminal}")
    return "\n".join(lines)
