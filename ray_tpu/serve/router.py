"""Handle-side routing: power-of-two-choices over replicas.

Reference parity: serve/_private/router.py:340 AsyncioRouter +
replica_scheduler/pow_2_scheduler.py:52 PowerOfTwoChoicesReplicaScheduler —
sample two replicas, pick the one with the smaller ongoing-request count.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

from .. import api


class ReplicaSet:
    """Live replica handles + ongoing counts, shared router/controller."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._replicas: List[Any] = []  # ActorHandles
        self._ongoing: Dict[int, int] = {}  # id(handle) -> count

    def set_replicas(self, replicas: List[Any]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            live = {id(r) for r in replicas}
            self._ongoing = {k: v for k, v in self._ongoing.items() if k in live}
            for r in replicas:
                self._ongoing.setdefault(id(r), 0)

    def replicas(self) -> List[Any]:
        with self._lock:
            return list(self._replicas)

    def pick(self) -> Any:
        """Pow-2 choice by ongoing count."""
        with self._lock:
            if not self._replicas:
                raise RuntimeError(f"deployment {self.name!r} has no replicas")
            if len(self._replicas) == 1:
                chosen = self._replicas[0]
            else:
                a, b = random.sample(self._replicas, 2)
                chosen = a if self._ongoing[id(a)] <= self._ongoing[id(b)] else b
            self._ongoing[id(chosen)] += 1
            return chosen

    def release(self, replica: Any) -> None:
        with self._lock:
            if id(replica) in self._ongoing and self._ongoing[id(replica)] > 0:
                self._ongoing[id(replica)] -= 1

    def total_ongoing(self) -> int:
        with self._lock:
            return sum(self._ongoing.values())

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)


class DeploymentHandle:
    """What users call: handle.method.remote(args) → ObjectRef (reference
    serve/handle.py DeploymentHandle)."""

    def __init__(self, replica_set: ReplicaSet):
        self._set = replica_set

    def __getattr__(self, method: str) -> "_MethodCaller":
        if method.startswith("_"):
            raise AttributeError(method)
        return _MethodCaller(self._set, method)

    def remote(self, *args, **kwargs):
        """Callable deployments: handle.remote(x) → instance.__call__(x)."""
        return _MethodCaller(self._set, "__call__").remote(*args, **kwargs)

    @property
    def deployment_name(self) -> str:
        return self._set.name


class _MethodCaller:
    def __init__(self, replica_set: ReplicaSet, method: str):
        self._set = replica_set
        self._method = method

    def remote(self, *args, **kwargs):
        replica = self._set.pick()
        try:
            # replicas are _ReplicaWrapper actors: dispatch by method name
            ref = replica.call.remote(self._method, *args, **kwargs)
        except BaseException:
            self._set.release(replica)
            raise
        _Reaper.instance().track(ref, self._set, replica)
        return ref


class _Reaper:
    """Decrements ongoing counts when request refs complete — one background
    thread over api.wait, the in-process analogue of the reference's asyncio
    done-callbacks."""

    _inst: Optional["_Reaper"] = None
    _inst_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._tracked: List[Any] = []  # (ref, set, replica)
        self._event = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serve-reaper")
        self._thread.start()

    @classmethod
    def instance(cls) -> "_Reaper":
        with cls._inst_lock:
            if cls._inst is None:
                cls._inst = cls()
            return cls._inst

    def track(self, ref, replica_set, replica) -> None:
        with self._lock:
            self._tracked.append((ref, replica_set, replica))
        self._event.set()

    def _loop(self) -> None:
        while True:
            self._event.wait()
            with self._lock:
                tracked = list(self._tracked)
                if not tracked:
                    self._event.clear()
                    continue
            refs = [t[0] for t in tracked]
            try:
                done, _ = api.wait(refs, num_returns=1, timeout=0.1)
            except BaseException:
                done = []
            if done:
                done_set = set(done)
                with self._lock:
                    remaining = []
                    for ref, rset, replica in self._tracked:
                        if ref in done_set:
                            rset.release(replica)
                        else:
                            remaining.append((ref, rset, replica))
                    self._tracked = remaining
