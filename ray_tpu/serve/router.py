"""Handle-side routing: power-of-two-choices over replicas.

Reference parity: serve/_private/router.py:340 AsyncioRouter +
replica_scheduler/pow_2_scheduler.py:52 PowerOfTwoChoicesReplicaScheduler —
sample two replicas, pick the one with the smaller ongoing-request count.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

from .. import api


def _rkey(replica: Any) -> str:
    """Stable replica identity: the actor id. id() recycles once a
    swapped-out handle is GC'd, which let a new replica inherit false
    multiplex affinity; the actor id never does."""
    return replica._actor_id.hex()


class ReplicaSet:
    """Live replica handles + ongoing counts, shared router/controller."""

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._replicas: List[Any] = []  # ActorHandles
        self._ongoing: Dict[str, int] = {}  # actor-id hex -> count
        # model-multiplex affinity: model_id -> MRU list of replica keys
        # (reference pow_2_scheduler.py is multiplex-aware the same way)
        self._affinity: Dict[str, List[str]] = {}

    _key = staticmethod(_rkey)

    def set_replicas(self, replicas: List[Any]) -> None:
        with self._lock:
            self._replicas = list(replicas)
            live = {self._key(r) for r in replicas}
            self._ongoing = {k: v for k, v in self._ongoing.items() if k in live}
            for r in replicas:
                self._ongoing.setdefault(self._key(r), 0)
            # drop affinity for replicas that were swapped out
            for model_id in list(self._affinity):
                kept = [k for k in self._affinity[model_id] if k in live]
                if kept:
                    self._affinity[model_id] = kept
                else:
                    del self._affinity[model_id]

    def replicas(self) -> List[Any]:
        with self._lock:
            return list(self._replicas)

    def pick(self, model_id: Optional[str] = None) -> Any:
        """Pow-2 choice by ongoing count; with a multiplexed model id,
        prefer a replica that already holds the model (affinity)."""
        with self._lock:
            if not self._replicas:
                raise RuntimeError(f"deployment {self.name!r} has no replicas")
            chosen = None
            if model_id:
                cands = [
                    r for r in self._replicas
                    if self._key(r) in self._affinity.get(model_id, ())
                ]
                if cands:
                    chosen = min(cands, key=lambda r: self._ongoing[self._key(r)])
            if chosen is None:
                if len(self._replicas) == 1:
                    chosen = self._replicas[0]
                else:
                    a, b = random.sample(self._replicas, 2)
                    chosen = (
                        a
                        if self._ongoing[self._key(a)] <= self._ongoing[self._key(b)]
                        else b
                    )
            if model_id:
                mru = self._affinity.setdefault(model_id, [])
                ck = self._key(chosen)
                if ck in mru:
                    mru.remove(ck)
                mru.insert(0, ck)
                del mru[2:]  # at most 2 replicas per model keep affinity
            self._ongoing[self._key(chosen)] += 1
            return chosen

    def release(self, replica: Any) -> None:
        with self._lock:
            k = self._key(replica)
            if self._ongoing.get(k, 0) > 0:
                self._ongoing[k] -= 1

    def total_ongoing(self) -> int:
        with self._lock:
            return sum(self._ongoing.values())

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)


class DeploymentHandle:
    """What users call: handle.method.remote(args) → ObjectRef (reference
    serve/handle.py DeploymentHandle). options(stream=True) streams a
    generator method's yields; options(multiplexed_model_id=...) routes
    with model affinity and exposes the id via
    serve.get_multiplexed_model_id() inside the replica."""

    def __init__(self, replica_set: ReplicaSet, *, stream: bool = False,
                 multiplexed_model_id: Optional[str] = None):
        self._set = replica_set
        self._stream = stream
        self._model_id = multiplexed_model_id

    def options(self, *, stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._set,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=multiplexed_model_id or self._model_id,
        )

    def __getattr__(self, method: str) -> "_MethodCaller":
        if method.startswith("_"):
            raise AttributeError(method)
        return _MethodCaller(self._set, method, self._stream, self._model_id)

    def remote(self, *args, **kwargs):
        """Callable deployments: handle.remote(x) → instance.__call__(x)."""
        return _MethodCaller(
            self._set, "__call__", self._stream, self._model_id
        ).remote(*args, **kwargs)

    @property
    def deployment_name(self) -> str:
        return self._set.name


class _MethodCaller:
    def __init__(self, replica_set: ReplicaSet, method: str,
                 stream: bool = False, model_id: Optional[str] = None):
        self._set = replica_set
        self._method = method
        self._stream = stream
        self._model_id = model_id

    def remote(self, *args, **kwargs):
        from ..util import tracing

        # serve.route roots the request's trace (or nests, when called
        # from a traced region): replica pick + submission. The replica's
        # actor.call/actor.execute spans — and the engine's request span
        # inside it — parent in through the context propagation.
        with tracing.span(
            "serve.route", deployment=self._set.name, method=self._method,
            model_id=self._model_id or "",
        ) as route_span:
            replica = self._set.pick(self._model_id)
            route_span.set_attribute("replica", _rkey(replica)[:12])
            if self._model_id:
                kwargs["_multiplexed_model_id"] = self._model_id
            try:
                # replicas are _ReplicaWrapper actors: dispatch by method name
                call = replica.call
                if self._stream:
                    call = call.options(num_returns="streaming")
                ref = call.remote(self._method, *args, **kwargs)
            except BaseException:
                self._set.release(replica)
                raise
        _Reaper.instance().track(ref, self._set, replica)
        return ref


class _Reaper:
    """Decrements ongoing counts when request refs complete — one background
    thread over api.wait, the in-process analogue of the reference's asyncio
    done-callbacks."""

    _inst: Optional["_Reaper"] = None
    _inst_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._tracked: List[Any] = []  # (ref, set, replica)
        self._event = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serve-reaper")
        self._thread.start()

    @classmethod
    def instance(cls) -> "_Reaper":
        with cls._inst_lock:
            if cls._inst is None:
                cls._inst = cls()
            return cls._inst

    def track(self, ref, replica_set, replica) -> None:
        with self._lock:
            self._tracked.append((ref, replica_set, replica))
        self._event.set()

    def _loop(self) -> None:
        from ..core.streaming import ObjectRefGenerator

        while True:
            self._event.wait()
            with self._lock:
                tracked = list(self._tracked)
                if not tracked:
                    self._event.clear()
                    continue
            # streams complete on their own flag; plain refs via api.wait
            done_set = set()
            refs = []
            for ref, _, _ in tracked:
                if isinstance(ref, ObjectRefGenerator):
                    if ref.completed():
                        done_set.add(id(ref))
                else:
                    refs.append(ref)
            if refs:
                try:
                    done, _ = api.wait(refs, num_returns=1, timeout=0.1)
                    done_set.update(id(r) for r in done)
                except BaseException:
                    pass
            else:
                import time as _time

                _time.sleep(0.05)  # stream polling cadence
            if done_set:
                with self._lock:
                    remaining = []
                    for ref, rset, replica in self._tracked:
                        if id(ref) in done_set:
                            rset.release(replica)
                        else:
                            remaining.append((ref, rset, replica))
                    self._tracked = remaining
