"""Handle-side routing: power-of-two-choices over replicas, with the
serve resilience layer — end-to-end deadlines, bounded retry/failover,
and admission control with load shedding.

Reference parity: serve/_private/router.py:340 AsyncioRouter +
replica_scheduler/pow_2_scheduler.py:52 PowerOfTwoChoicesReplicaScheduler —
sample two replicas, pick the one with the smaller ongoing-request count —
plus the router-side pieces of Serve's fault tolerance: retries re-pick a
*different* live replica on replica-death-class errors, `max_queued_requests`
sheds with a typed BackPressureError, and requests carry an absolute
deadline that fails fast once expired (handle.options(timeout_s=...)).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Set

from .. import api
from ..core.chaos import ChaosInjectedError
from ..core.exceptions import (
    ActorDiedError,
    ActorUnavailableError,
    BackPressureError,
    DeploymentUnavailableError,
    GetTimeoutError,
    ReplicaDrainingError,
    RequestTimeoutError,
    unwrap_error,
)
from ..core.rpc import RpcError
from ..core.streaming import ObjectRefGenerator
from . import reqlog

logger = logging.getLogger(__name__)


def _mark_route(kwargs: Dict[str, Any], phase: str, **attrs) -> None:
    """Forensics mark keyed by the request id riding the private kwargs
    channel (`_request_id`); no-op when the call carries no id."""
    reqlog.mark(kwargs.get("_request_id"), phase,  # raylint: disable=request-phase
                tenant=kwargs.get("_tenant"), **attrs)

# Errors that indicate the REPLICA or transport failed (not the request):
# safe to fail over to a different replica. A user-code exception is not
# retryable — re-running it elsewhere would just fail the same way.
_RETRYABLE = (
    ActorDiedError,
    ActorUnavailableError,
    ReplicaDrainingError,
    RpcError,
    ConnectionError,
    ChaosInjectedError,
)


def _rkey(replica: Any) -> str:
    """Stable replica identity: the actor id. id() recycles once a
    swapped-out handle is GC'd, which let a new replica inherit false
    multiplex affinity; the actor id never does."""
    return replica._actor_id.hex()


def _counter(name: str, doc: str):
    from ..util.metrics import get_or_create_counter

    return get_or_create_counter(name, doc)


def _retryable(err: BaseException) -> bool:
    return isinstance(unwrap_error(err), _RETRYABLE)


def _head_outage_s() -> float:
    """Seconds the GCS head has currently been unreachable from this
    process (0.0 = reachable, or no cluster). The serve data plane keys
    degraded-mode behavior off this: replica calls go DIRECT to node
    agents, so dispatch works fine without the head — only membership
    updates stall."""
    from ..core.runtime import head_outage_s

    return head_outage_s()


# live deployments' replica sets, for the ongoing-requests gauge (weak:
# a deleted deployment's series disappears instead of pinning the set)
import weakref  # noqa: E402 - scoped to the telemetry plumbing below

_replica_sets: "weakref.WeakSet" = weakref.WeakSet()


def _register_replica_set(rset: "ReplicaSet") -> None:
    from ..util.metrics import get_or_create_gauge
    from ..util.watchdog import ensure_serve_slo_monitor

    _replica_sets.add(rset)
    get_or_create_gauge(
        "raytpu_serve_ongoing_requests",
        "In-flight requests per deployment, from the router's ongoing "
        "counts.",
        tag_keys=("deployment",),
        fn=lambda: [
            ({"deployment": rs.name}, float(rs.total_ongoing()))
            for rs in list(_replica_sets)
        ],
    )
    ensure_serve_slo_monitor()


def _retry_backoff_s(attempt: int) -> float:
    """Jittered exponential backoff before failover attempt N (1-based)."""
    from ..core.config import cfg

    base = float(cfg.serve_retry_backoff_s)
    return min(2.0, base * (2 ** max(0, attempt - 1))) * (0.5 + random.random())


class ReplicaSet:
    """Live replica handles + ongoing counts, shared router/controller.

    Also owns the deployment's admission bound and pending-dispatch
    order: at ongoing capacity, resilient unary calls PARK in a
    weighted-fair queue (per-tenant SCFQ lanes, serve/tenancy.FairQueue)
    that the reaper grants from as replicas free up — so dispatch order
    under overload is weight-proportional per tenant, not FIFO. When
    `max_queued` >= 0, requests beyond the parked bound are shed with
    BackPressureError carrying a drain-rate Retry-After estimate (and
    `pick` keeps its ongoing-over-capacity bound for callers that bypass
    parking). DRAINING replicas stay known (their ongoing counts must
    drain to zero before the controller reaps them) but are never
    picked."""

    def __init__(self, name: str, *, max_ongoing: int = 8,
                 max_queued: int = -1):
        from .tenancy import FairQueue

        self.name = name
        self._lock = threading.Lock()
        self._replicas: List[Any] = []  # ActorHandles  # guarded-by: _lock
        self._ongoing: Dict[str, int] = {}  # actor-id hex -> count  # guarded-by: _lock
        self._draining: Set[str] = set()  # guarded-by: _lock
        self.max_ongoing = max_ongoing
        self.max_queued = max_queued  # -1 = unlimited
        # model-multiplex affinity: model_id -> MRU list of replica keys
        # (reference pow_2_scheduler.py is multiplex-aware the same way)
        self._affinity: Dict[str, List[str]] = {}  # guarded-by: _lock
        # weighted-fair parked dispatch: _TrackedCall records waiting for
        # ongoing headroom, granted in SCFQ order (FairQueue self-locks)
        self._parked = FairQueue()
        # recent release timestamps -> queue drain-rate Retry-After
        self._release_times: "deque[float]" = deque(maxlen=32)  # guarded-by: _lock
        # telemetry: per-deployment ongoing gauge + the SLO monitor
        # (watchdog) spins up once any serve_slo_* objective is set
        _register_replica_set(self)

    def total_ongoing(self) -> int:
        """Requests currently in flight across this deployment's
        replicas (the router-side queue-depth signal)."""
        with self._lock:
            return sum(self._ongoing.values())

    _key = staticmethod(_rkey)

    def configure(self, *, max_ongoing: Optional[int] = None,
                  max_queued: Optional[int] = None) -> None:
        with self._lock:
            if max_ongoing is not None:
                self.max_ongoing = int(max_ongoing)
            if max_queued is not None:
                self.max_queued = int(max_queued)

    def set_replicas(self, replicas: List[Any]) -> None:
        from ..core.config import cfg

        with self._lock:
            if not replicas and self._replicas:
                # Degraded mode: an EMPTY membership computed while the
                # head is unreachable reflects control-plane blindness,
                # not replica death — keep dispatching on the cached
                # handles (replica calls go direct to node agents) for
                # the grace window. Past it, accept the empty set and
                # shed with typed errors.
                outage = _head_outage_s()
                if 0.0 < outage <= float(cfg.head_outage_grace_s):
                    logger.warning(
                        "deployment %r: ignoring empty replica membership "
                        "during head outage (%.1fs); serving on cached "
                        "replicas", self.name, outage)
                    return
            self._replicas = list(replicas)
            # draining replicas keep their ongoing entries: the controller
            # watches them hit zero before killing the actor
            live = {self._key(r) for r in replicas} | self._draining
            self._ongoing = {k: v for k, v in self._ongoing.items() if k in live}
            for r in replicas:
                self._ongoing.setdefault(self._key(r), 0)
            # drop affinity for replicas that were swapped out
            for model_id in list(self._affinity):
                kept = [k for k in self._affinity[model_id] if k in live]
                if kept:
                    self._affinity[model_id] = kept
                else:
                    del self._affinity[model_id]

    def replicas(self) -> List[Any]:
        with self._lock:
            return list(self._replicas)

    # ------------------------------------------------------------- draining

    def mark_draining(self, key: str) -> None:
        with self._lock:
            self._draining.add(key)
            self._ongoing.setdefault(key, 0)
            self._replicas = [r for r in self._replicas if self._key(r) != key]

    def finish_draining(self, key: str) -> None:
        with self._lock:
            self._draining.discard(key)
            self._ongoing.pop(key, None)

    def draining_keys(self) -> Set[str]:
        with self._lock:
            return set(self._draining)

    def ongoing_for(self, key: str) -> int:
        with self._lock:
            return self._ongoing.get(key, 0)

    # ----------------------------------------------------------------- pick

    def pick(self, model_id: Optional[str] = None, *,
             exclude: Optional[Set[str]] = None,
             admission: bool = True) -> Any:
        """Pow-2 choice by ongoing count; with a multiplexed model id,
        prefer a replica that already holds the model (affinity).

        exclude: replica keys a failover retry must avoid (the attempt
        that just failed there); relaxed when nothing else is alive.
        admission=False skips the queue bound (retries already held and
        released a slot — shedding them would double-count)."""
        with self._lock:
            routable = [
                r for r in self._replicas
                if self._key(r) not in self._draining
            ]
            if not routable:
                raise DeploymentUnavailableError(
                    f"deployment {self.name!r} has no routable replicas "
                    f"({len(self._draining)} draining)"
                )
            if admission and self.max_queued >= 0:
                ongoing = sum(
                    self._ongoing.get(self._key(r), 0) for r in routable
                )
                capacity = len(routable) * max(1, self.max_ongoing)
                if ongoing - capacity >= self.max_queued:
                    raise BackPressureError(
                        f"deployment {self.name!r} is overloaded: "
                        f"{ongoing} ongoing over {capacity} capacity "
                        f"(max_queued_requests={self.max_queued})"
                    )
            cands = routable
            if exclude:
                preferred = [r for r in routable if self._key(r) not in exclude]
                if preferred:
                    cands = preferred
            chosen = None
            if model_id:
                affine = [
                    r for r in cands
                    if self._key(r) in self._affinity.get(model_id, ())
                ]
                if affine:
                    chosen = min(
                        affine, key=lambda r: self._ongoing[self._key(r)]
                    )
            if chosen is None:
                if len(cands) == 1:
                    chosen = cands[0]
                else:
                    a, b = random.sample(cands, 2)
                    chosen = (
                        a
                        if self._ongoing[self._key(a)] <= self._ongoing[self._key(b)]
                        else b
                    )
            if model_id:
                mru = self._affinity.setdefault(model_id, [])
                ck = self._key(chosen)
                if ck in mru:
                    mru.remove(ck)
                mru.insert(0, ck)
                del mru[2:]  # at most 2 replicas per model keep affinity
            self._ongoing[self._key(chosen)] += 1
        if _head_outage_s() > 0.0:
            # dispatched on cached membership while the head is down —
            # the drill's "traffic rode through the outage" evidence
            _counter(
                "raytpu_serve_degraded_dispatch_total",
                "Requests dispatched while the GCS head was unreachable "
                "(served on cached replica membership).",
            ).inc()
        return chosen

    def release(self, replica: Any) -> None:
        self.release_key(self._key(replica))

    def release_key(self, key: str) -> None:
        with self._lock:
            if self._ongoing.get(key, 0) > 0:
                self._ongoing[key] -= 1
                # drain-rate sample for the Retry-After estimate
                self._release_times.append(time.monotonic())

    # --------------------------------------------------- parked dispatch

    def _dispatch_headroom(self) -> bool:
        """True when a routable replica has ongoing capacity to spare —
        the work-conserving fast path past the parked queue. With no
        routable replicas this reports True so callers reach pick() and
        get the typed DeploymentUnavailableError instead of parking."""
        with self._lock:
            routable = [
                r for r in self._replicas
                if self._key(r) not in self._draining
            ]
            if not routable:
                return True
            ongoing = sum(
                self._ongoing.get(self._key(r), 0) for r in routable
            )
            return ongoing < len(routable) * max(1, self.max_ongoing)

    def should_park(self) -> bool:
        """A resilient unary call must queue behind the weighted-fair
        parked dispatches when the deployment is at ongoing capacity, or
        when earlier arrivals are already parked (no barging past the
        fair queue)."""
        if self.max_ongoing <= 0:
            return False
        if len(self._parked):
            return True
        return not self._dispatch_headroom()

    def park_would_shed(self) -> bool:
        return 0 <= self.max_queued <= len(self._parked)

    def park(self, rec: Any, tenant: str, priority: int) -> None:
        self._parked.push(rec, tenant, priority)

    def try_grant(self, rec: Any) -> bool:
        """Reaper-side: pop `rec` from the parked queue iff it is the
        weighted-fair head AND a replica has headroom. The reaper calls
        this for every parked record each pass, so grants walk the queue
        strictly in fair order."""
        if not self._dispatch_headroom():
            return False
        return self._parked.pop_if_head(rec)

    def cancel_parked(self, rec: Any) -> bool:
        return self._parked.remove(rec)

    def parked_count(self) -> int:
        return len(self._parked)

    def drain_retry_after_s(self) -> Optional[float]:
        """Retry-After estimate from the recent release rate: roughly how
        long the current parked backlog takes to drain. None (-> the
        HTTP layers' 1s default) until enough completions are observed."""
        with self._lock:
            times = list(self._release_times)
        if len(times) < 2:
            return None
        span = times[-1] - times[0]
        if span <= 0:
            return None
        rate = (len(times) - 1) / span
        return min(60.0, max(1.0, (len(self._parked) + 1) / rate))

    def total_ongoing(self) -> int:
        with self._lock:
            return sum(self._ongoing.values())

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)


class DeploymentHandle:
    """What users call: handle.method.remote(args) → ObjectRef (reference
    serve/handle.py DeploymentHandle). options(stream=True) streams a
    generator method's yields; options(multiplexed_model_id=...) routes
    with model affinity; options(timeout_s=...) sets the request's
    end-to-end deadline (expired → typed RequestTimeoutError);
    options(max_retries=...) bounds router failover attempts."""

    def __init__(self, replica_set: ReplicaSet, *, stream: bool = False,
                 multiplexed_model_id: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 tenant: Optional[str] = None,
                 priority: Optional[int] = None,
                 request_id: Optional[str] = None):
        self._set = replica_set
        self._stream = stream
        self._model_id = multiplexed_model_id
        self._timeout_s = timeout_s
        self._max_retries = max_retries
        self._tenant = tenant
        self._priority = priority
        self._request_id = request_id

    def options(self, *, stream: Optional[bool] = None,
                multiplexed_model_id: Optional[str] = None,
                timeout_s: Optional[float] = None,
                max_retries: Optional[int] = None,
                tenant: Optional[str] = None,
                priority: Optional[int] = None,
                request_id: Optional[str] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self._set,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=multiplexed_model_id or self._model_id,
            timeout_s=self._timeout_s if timeout_s is None else timeout_s,
            max_retries=(
                self._max_retries if max_retries is None else max_retries
            ),
            tenant=self._tenant if tenant is None else tenant,
            priority=self._priority if priority is None else priority,
            request_id=(
                self._request_id if request_id is None else request_id
            ),
        )

    def __getattr__(self, method: str) -> "_MethodCaller":
        if method.startswith("_"):
            raise AttributeError(method)
        return _MethodCaller(self._set, method, self._stream, self._model_id,
                             self._timeout_s, self._max_retries,
                             self._tenant, self._priority, self._request_id)

    def remote(self, *args, **kwargs):
        """Callable deployments: handle.remote(x) → instance.__call__(x)."""
        return _MethodCaller(
            self._set, "__call__", self._stream, self._model_id,
            self._timeout_s, self._max_retries, self._tenant, self._priority,
            self._request_id,
        ).remote(*args, **kwargs)

    @property
    def deployment_name(self) -> str:
        return self._set.name


def _mint_promise():
    """A router-owned future: the reaper seals the winning attempt's
    result (or the typed failure) into it, so the ref handed to the
    caller survives replica failover."""
    from ..core.ids import ObjectID
    from ..core.runtime import ObjectRef, get_runtime

    rt = get_runtime()
    oid = ObjectID.for_put(rt.job_id)
    rt.object_store.create(oid)
    return ObjectRef(oid, rt), oid, rt


class _FailoverStream(ObjectRefGenerator):
    """Router-owned stream that survives replica failover: the feeder
    thread copies item refs from successive attempt streams into it,
    skipping the prefix already delivered to the consumer."""

    def __init__(self, first_attempt: ObjectRefGenerator):
        super().__init__(first_attempt._task_id, first_attempt._runtime)

    def _append_ref(self, ref: Any) -> None:
        with self._cond:
            self._refs.append(ref)
            self._cond.notify_all()


class _MethodCaller:
    def __init__(self, replica_set: ReplicaSet, method: str,
                 stream: bool = False, model_id: Optional[str] = None,
                 timeout_s: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 tenant: Optional[str] = None,
                 priority: Optional[int] = None,
                 request_id: Optional[str] = None):
        self._set = replica_set
        self._method = method
        self._stream = stream
        self._model_id = model_id
        self._timeout_s = timeout_s
        self._max_retries = max_retries
        self._tenant = tenant
        self._priority = priority
        self._request_id = request_id

    def _resolve_request_id(self) -> Optional[str]:
        """The end-to-end forensics id for this call: the handle's
        explicit option wins, else the ambient id when this call happens
        inside another serve request (composition hop — the hops share
        one timeline), else a fresh id when the request log is on."""
        from . import context as serve_ctx

        rid = self._request_id
        if rid is None:
            rid = serve_ctx.get_request_id()
        if rid is None and reqlog.enabled():
            rid = reqlog.new_request_id()
        return rid

    def _resolve_tenant(self):
        """(tenant | None, priority | None) for this call: the handle's
        explicit options win, else the ambient request tenant when this
        call happens inside another serve request (composition hop) —
        the same inheritance rule the deadline follows."""
        from . import context as serve_ctx

        tenant = self._tenant
        if tenant is None:
            tenant = serve_ctx.get_request_tenant()
        priority = self._priority
        if priority is None:
            priority = serve_ctx.get_request_priority()
        return tenant, priority

    def _resolve_policy(self):
        """(deadline_ts | None, max_attempts >= 1) for this call.

        The deadline is the MIN of the handle's timeout_s (default:
        cfg.serve_default_timeout_s; 0 disables) and the ambient request
        deadline when this call happens inside another serve request
        (composition hop) — a downstream hop never outlives its parent."""
        from ..core.config import cfg
        from . import context as serve_ctx

        timeout_s = self._timeout_s
        if timeout_s is None:
            timeout_s = float(cfg.serve_default_timeout_s)
        deadline = time.time() + timeout_s if timeout_s > 0 else None
        ambient = serve_ctx.get_request_deadline()
        if ambient is not None:
            deadline = ambient if deadline is None else min(deadline, ambient)
        attempts = self._max_retries
        if attempts is None:
            attempts = int(cfg.serve_retry_max_attempts)
        return deadline, max(1, attempts)

    def remote(self, *args, **kwargs):
        from ..util import tracing
        from .tenancy import DEFAULT_TENANT

        deadline, max_attempts = self._resolve_policy()
        tenant, priority = self._resolve_tenant()
        request_id = self._resolve_request_id()
        resilient = max_attempts > 1 or deadline is not None
        # serve.route roots the request's trace (or nests, when called
        # from a traced region): replica pick + submission. The replica's
        # actor.call/actor.execute spans — and the engine's request span
        # inside it — parent in through the context propagation.
        with tracing.span(
            "serve.route", deployment=self._set.name, method=self._method,
            model_id=self._model_id or "",
        ) as route_span:
            if request_id is not None:
                route_span.set_attribute("request_id", request_id)
            reqlog.mark(request_id, "route.received", tenant=tenant,
                        deployment=self._set.name, method=self._method)
            if deadline is not None:
                route_span.set_attribute("deadline_ts", deadline)
                if time.time() >= deadline:
                    _counter(
                        "raytpu_serve_timeouts_total",
                        "serve requests failed on an expired deadline",
                    ).inc()
                    reqlog.mark(request_id, "route.timeout", tenant=tenant,
                                reason="expired_before_routing")
                    raise RequestTimeoutError(
                        f"request to {self._set.name!r}.{self._method} "
                        f"expired before routing"
                    )
            if self._model_id:
                kwargs["_multiplexed_model_id"] = self._model_id
            if deadline is not None:
                kwargs["_deadline_ts"] = deadline
            if tenant is not None:
                kwargs["_tenant"] = tenant
                route_span.set_attribute("tenant", tenant)
            if priority is not None:
                kwargs["_priority"] = priority
            if request_id is not None:
                kwargs["_request_id"] = request_id
            # At ongoing capacity, resilient unary calls PARK instead of
            # dispatching: the reaper grants parked records in weighted-
            # fair order as replicas free up, so overload dispatch is
            # weight-proportional per tenant rather than FIFO. Streams
            # and non-resilient calls keep the direct path (no promise to
            # park behind).
            if resilient and not self._stream and self._set.should_park():
                if self._set.park_would_shed():
                    from . import tenancy

                    _counter(
                        "raytpu_serve_shed_total",
                        "serve requests shed by admission control",
                    ).inc()
                    tenancy.count_shed(tenant or DEFAULT_TENANT)
                    route_span.set_attribute("shed", True)
                    retry_after = self._set.drain_retry_after_s()
                    reqlog.mark(request_id, "route.shed", tenant=tenant,
                                reason="parked_queue_full",
                                retry_after_s=retry_after)
                    raise BackPressureError(
                        f"deployment {self._set.name!r} is overloaded: "
                        f"{self._set.parked_count()} parked dispatches "
                        f"(max_queued_requests={self._set.max_queued})",
                        retry_after_s=retry_after,
                    )
                promise_ref, promise_oid, rt = _mint_promise()
                rec = _TrackedCall(
                    None, self._set, "", promise_oid, rt,
                    method=self._method, args=args, kwargs=kwargs,
                    model_id=self._model_id, deadline=deadline,
                    max_attempts=max_attempts,
                )
                rec.parked = True
                rec.attempts = 0  # first dispatch is attempt 1, not a retry
                self._set.park(rec, tenant or DEFAULT_TENANT, priority or 0)
                _Reaper.instance()._track_record(rec)
                route_span.set_attribute("parked", True)
                reqlog.mark(request_id, "route.parked", tenant=tenant,
                            parked=self._set.parked_count())
                return promise_ref
            try:
                replica = self._set.pick(self._model_id)
            except BackPressureError:
                _counter(
                    "raytpu_serve_shed_total",
                    "serve requests shed by admission control",
                ).inc()
                route_span.set_attribute("shed", True)
                reqlog.mark(request_id, "route.shed", tenant=tenant,
                            reason="ongoing_capacity")
                raise
            route_span.set_attribute("replica", _rkey(replica)[:12])
            try:
                # replicas are _ReplicaWrapper actors: dispatch by method name
                call = replica.call
                if self._stream:
                    call = call.options(num_returns="streaming")
                ref = call.remote(self._method, *args, **kwargs)
            except BaseException:
                self._set.release(replica)
                raise
            reqlog.mark(request_id, "route.dispatched", tenant=tenant,
                        replica=_rkey(replica)[:12], attempt=1)
        if self._stream:
            if not resilient:
                _Reaper.instance().track(ref, self._set, replica)
                return ref
            proxy = _FailoverStream(ref)
            feeder = threading.Thread(
                target=_stream_failover_loop,
                args=(proxy, self._set, self._model_id, self._method,
                      args, kwargs, replica, ref, deadline, max_attempts),
                daemon=True,
                name=f"serve-stream-{self._set.name}",
            )
            feeder.start()
            return proxy
        if not resilient:
            _Reaper.instance().track(ref, self._set, replica)
            return ref
        promise_ref, promise_oid, rt = _mint_promise()
        _Reaper.instance().track_failover(
            ref, self._set, replica, promise_oid, rt,
            method=self._method, args=args, kwargs=kwargs,
            model_id=self._model_id, deadline=deadline,
            max_attempts=max_attempts,
        )
        return promise_ref


def _stream_failover_loop(proxy: _FailoverStream, rset: ReplicaSet,
                          model_id: Optional[str], method: str,
                          args, kwargs, replica, stream,
                          deadline: Optional[float],
                          max_attempts: int) -> None:
    """Feeder thread for resilient streaming calls: copies item refs from
    the live attempt into the proxy; on a retryable mid-stream failure it
    re-picks a different replica, replays the generator, and skips the
    prefix the consumer already saw. Deadline expiry fails the stream
    with RequestTimeoutError (the engine cancels its slot on its own)."""
    delivered = 0
    attempts = 1
    skip = 0
    key = _rkey(replica)
    while True:
        try:
            while True:
                if proxy._abandoned:
                    rset.release_key(key)
                    return
                timeout = None
                if deadline is not None:
                    timeout = deadline - time.time()
                    if timeout <= 0:
                        raise GetTimeoutError("stream deadline expired")
                try:
                    ref = stream.next_ready(timeout=timeout)
                except StopIteration:
                    rset.release_key(key)
                    proxy._finish()
                    return
                if skip > 0:
                    skip -= 1  # replayed prefix: consumer already has it
                    continue
                proxy._append_ref(ref)
                delivered += 1
        except BaseException as err:  # noqa: BLE001 - classified below
            rset.release_key(key)
            cause = unwrap_error(err)
            if isinstance(cause, (GetTimeoutError, RequestTimeoutError)):
                _counter(
                    "raytpu_serve_timeouts_total",
                    "serve requests failed on an expired deadline",
                ).inc()
                _mark_route(kwargs, "route.timeout",
                            reason="stream_deadline", delivered=delivered)
                proxy._finish(RequestTimeoutError(
                    f"stream from {rset.name!r}.{method} exceeded its "
                    f"deadline after {delivered} items"
                ))
                return
            if attempts >= max_attempts or not isinstance(cause, _RETRYABLE):
                _mark_route(kwargs, "route.failed",
                            error=type(cause).__name__, attempts=attempts)
                proxy._finish(err)
                return
            wait = _retry_backoff_s(attempts)
            if deadline is not None and time.time() + wait >= deadline:
                _counter(
                    "raytpu_serve_timeouts_total",
                    "serve requests failed on an expired deadline",
                ).inc()
                _mark_route(kwargs, "route.timeout",
                            reason="no_retry_budget", delivered=delivered)
                proxy._finish(RequestTimeoutError(
                    f"stream from {rset.name!r}.{method}: no retry budget "
                    f"left before the deadline"
                ))
                return
            time.sleep(wait)
            try:
                replica = rset.pick(model_id, exclude={key}, admission=False)
            except BaseException:
                _mark_route(kwargs, "route.failed",
                            error=type(cause).__name__, attempts=attempts)
                proxy._finish(err)
                return
            key = _rkey(replica)
            attempts += 1
            _counter(
                "raytpu_serve_failovers_total",
                "serve requests failed over to a different replica",
            ).inc()
            _mark_route(kwargs, "route.failover",
                        error=type(cause).__name__, attempt=attempts)
            try:
                stream = replica.call.options(num_returns="streaming").remote(
                    method, *args, **kwargs
                )
            except BaseException as sub_err:  # noqa: BLE001
                rset.release_key(key)
                _mark_route(kwargs, "route.failed",
                            error=type(sub_err).__name__, attempts=attempts)
                proxy._finish(sub_err)
                return
            _mark_route(kwargs, "route.dispatched", replica=key[:12],
                        attempt=attempts)
            skip = delivered


class _TrackedCall:
    """One router-tracked request: either a plain ref (release-on-done)
    or a failover call with a promise the reaper must eventually seal."""

    __slots__ = (
        "ref", "rset", "key", "promise_oid", "runtime", "method", "args",
        "kwargs", "model_id", "deadline", "max_attempts", "attempts",
        "failed_keys", "next_retry_ts", "last_error", "parked",
    )

    def __init__(self, ref, rset, key, promise_oid=None, runtime=None,
                 method=None, args=(), kwargs=None, model_id=None,
                 deadline=None, max_attempts=1):
        self.ref = ref
        self.rset = rset
        self.key = key
        self.promise_oid = promise_oid
        self.runtime = runtime
        self.method = method
        self.args = args
        self.kwargs = kwargs or {}
        self.model_id = model_id
        self.deadline = deadline
        self.max_attempts = max_attempts
        self.attempts = 1
        self.failed_keys: Set[str] = set()
        self.next_retry_ts: Optional[float] = None
        self.last_error: Optional[BaseException] = None
        # waiting in the rset's weighted-fair parked queue for dispatch
        # headroom (ref is None until the reaper grants + dispatches)
        self.parked = False


class _Reaper:
    """Request-lifecycle owner on the router side: one background thread
    that (a) releases ongoing counts when request refs complete — success
    OR error, so failed calls stop skewing least-loaded picks, (b) drives
    failover resubmission with jittered backoff onto a different replica,
    (c) enforces deadlines by sealing RequestTimeoutError into the
    promise, and (d) caps its tracked list so one stuck ref can't grow it
    unboundedly (overflow releases + fails the oldest entry and bumps
    raytpu_serve_reaper_overflow_total)."""

    _inst: Optional["_Reaper"] = None  # guarded-by: _inst_lock
    _inst_lock = threading.Lock()

    def __init__(self):
        from ..util.metrics import get_or_create_gauge

        self._lock = threading.Lock()
        self._tracked: List[_TrackedCall] = []  # guarded-by: _lock
        self._event = threading.Event()
        self._overflow_warned = False
        get_or_create_gauge(
            "raytpu_serve_reaper_tracked",
            "request refs currently tracked by the serve reaper",
            fn=lambda: float(len(self._tracked)),
        )
        self._thread = threading.Thread(target=self._loop, daemon=True, name="serve-reaper")
        self._thread.start()

    @classmethod
    def instance(cls) -> "_Reaper":
        with cls._inst_lock:
            if cls._inst is None:
                cls._inst = cls()
            return cls._inst

    # ------------------------------------------------------------- tracking

    def track(self, ref, replica_set, replica) -> None:
        self._track_record(
            _TrackedCall(ref, replica_set, _rkey(replica))
        )

    def track_failover(self, ref, replica_set, replica, promise_oid, runtime,
                       *, method, args, kwargs, model_id, deadline,
                       max_attempts) -> None:
        self._track_record(_TrackedCall(
            ref, replica_set, _rkey(replica), promise_oid, runtime,
            method=method, args=args, kwargs=kwargs, model_id=model_id,
            deadline=deadline, max_attempts=max_attempts,
        ))

    def _track_record(self, rec: _TrackedCall) -> None:
        from ..core.config import cfg

        overflow = None
        with self._lock:
            cap = int(cfg.serve_reaper_max_tracked)
            if cap > 0 and len(self._tracked) >= cap:
                overflow = self._tracked.pop(0)
            self._tracked.append(rec)
        self._event.set()
        if overflow is not None:
            if overflow.parked:
                # never leave a dropped record wedged at the fair head
                overflow.rset.cancel_parked(overflow)
            overflow.rset.release_key(overflow.key)
            _mark_route(overflow.kwargs, "route.failed",
                        reason="reaper_overflow")
            self._seal_error(overflow, RuntimeError(
                "serve reaper overflow: request dropped to bound tracking "
                f"(serve_reaper_max_tracked={cfg.serve_reaper_max_tracked})"
            ))
            _counter(
                "raytpu_serve_reaper_overflow_total",
                "tracked requests dropped by the reaper's size cap",
            ).inc()
            if not self._overflow_warned:
                self._overflow_warned = True
                logger.warning(
                    "serve reaper hit its tracked-ref cap (%d); oldest "
                    "request dropped — a replica is likely stuck",
                    cap,
                )

    # ----------------------------------------------------------- seal paths

    @staticmethod
    def _seal(rec: _TrackedCall, value: Any) -> None:
        if rec.promise_oid is not None:
            try:
                rec.runtime.object_store.seal(rec.promise_oid, value)
            except Exception:
                logger.exception("reaper failed to seal promise")

    @staticmethod
    def _seal_error(rec: _TrackedCall, err: BaseException) -> None:
        if rec.promise_oid is not None:
            try:
                rec.runtime.object_store.seal_error(rec.promise_oid, err)
            except Exception:
                logger.exception("reaper failed to seal promise error")

    # ----------------------------------------------------------------- loop

    def _loop(self) -> None:
        while True:
            with self._lock:
                tracked = list(self._tracked)
            if not tracked:
                self._event.clear()
                self._event.wait()
                continue
            # Block until SOME in-flight ref completes (api.wait returns
            # on the first completion, so request latency is not gated on
            # the poll cadence); the bounded timeout keeps deadline and
            # backoff bookkeeping ticking and picks up newly tracked refs.
            inflight = [
                rec.ref for rec in tracked
                if rec.ref is not None
                and not isinstance(rec.ref, ObjectRefGenerator)
            ]
            if inflight:
                try:
                    api.wait(inflight, num_returns=1, timeout=0.02)
                except BaseException:  # noqa: BLE001 - torn refs handled below
                    time.sleep(0.005)
            else:
                self._event.wait(timeout=0.02)
                self._event.clear()
            with self._lock:
                tracked = list(self._tracked)
            done: List[_TrackedCall] = []
            for rec in tracked:
                try:
                    if self._advance(rec):
                        done.append(rec)
                except Exception:
                    logger.exception("serve reaper: tracking entry failed")
                    rec.rset.release_key(rec.key)
                    self._seal_error(rec, RuntimeError("serve reaper error"))
                    done.append(rec)
            if done:
                done_ids = {id(r) for r in done}
                with self._lock:
                    self._tracked = [
                        r for r in self._tracked if id(r) not in done_ids
                    ]

    def _advance(self, rec: _TrackedCall) -> bool:
        """Step one tracked call; True = finished, drop it."""
        now = time.time()
        if rec.parked:
            # waiting for dispatch headroom in the rset's weighted-fair
            # queue (only this reaper thread grants/cancels, so there is
            # no pop race with other mutators — park() only pushes)
            if rec.deadline is not None and now >= rec.deadline:
                rec.rset.cancel_parked(rec)
                _counter(
                    "raytpu_serve_timeouts_total",
                    "serve requests failed on an expired deadline",
                ).inc()
                _mark_route(rec.kwargs, "route.timeout",
                            reason="parked_deadline")
                self._seal_error(rec, RequestTimeoutError(
                    f"request to {rec.rset.name!r}.{rec.method} exceeded "
                    f"its deadline while parked for dispatch"
                ))
                return True
            if not rec.rset.try_grant(rec):
                return False
            rec.parked = False
            _mark_route(rec.kwargs, "route.granted")
            return self._dispatch_parked(rec)
        # deadline enforcement (promise-backed calls fail fast; plain
        # tracked refs have no promise to seal, their caller owns timeouts)
        if (
            rec.promise_oid is not None
            and rec.deadline is not None
            and now >= rec.deadline
        ):
            rec.rset.release_key(rec.key)
            _counter(
                "raytpu_serve_timeouts_total",
                "serve requests failed on an expired deadline",
            ).inc()
            _mark_route(rec.kwargs, "route.timeout", reason="deadline",
                        attempt=rec.attempts)
            self._seal_error(rec, RequestTimeoutError(
                f"request to {rec.rset.name!r}.{rec.method} exceeded its "
                f"deadline (attempt {rec.attempts}/{rec.max_attempts})"
            ))
            return True
        if rec.next_retry_ts is not None:
            if now < rec.next_retry_ts:
                return False
            return self._resubmit(rec)
        # completion check: streams complete on their flag; refs on seal
        if isinstance(rec.ref, ObjectRefGenerator):
            if not rec.ref.completed():
                return False
            rec.rset.release_key(rec.key)
            return True
        try:
            ready = rec.ref.is_ready()
        except Exception:
            ready = True  # a torn ref must not pin the replica forever
        if not ready:
            return False
        if rec.promise_oid is None:
            rec.rset.release_key(rec.key)
            return True
        try:
            value = api.get(rec.ref, timeout=1.0)
        except BaseException as err:  # noqa: BLE001 - classified below
            return self._on_error(rec, err)
        rec.rset.release_key(rec.key)
        self._seal(rec, value)
        return True

    def _on_error(self, rec: _TrackedCall, err: BaseException) -> bool:
        rec.rset.release_key(rec.key)
        rec.failed_keys.add(rec.key)
        rec.last_error = err
        now = time.time()
        wait = _retry_backoff_s(rec.attempts)
        can_retry = (
            rec.attempts < rec.max_attempts
            and _retryable(err)
            and (rec.deadline is None or now + wait < rec.deadline)
        )
        if not can_retry:
            _mark_route(rec.kwargs, "route.failed",
                        error=type(unwrap_error(err)).__name__,
                        attempts=rec.attempts)
            self._seal_error(rec, err)
            return True
        rec.next_retry_ts = now + wait
        rec.ref = None
        _counter(
            "raytpu_serve_retries_total",
            "serve request attempts retried after a replica failure",
        ).inc()
        return False

    def _dispatch_parked(self, rec: _TrackedCall) -> bool:
        """First dispatch of a WFQ-granted parked call: mirrors _resubmit
        minus the failover counter — a park is queueing, not a retry.
        admission=False: the call already passed the shed check at park
        time, and the grant itself consumed the headroom it saw."""
        try:
            replica = rec.rset.pick(
                rec.model_id, exclude=rec.failed_keys, admission=False
            )
        except BaseException as pick_err:  # noqa: BLE001
            # nothing routable right now (controller may be restarting
            # replicas): burn one attempt waiting, or give up
            rec.attempts += 1
            now = time.time()
            wait = _retry_backoff_s(rec.attempts)
            if (
                rec.attempts < rec.max_attempts
                and (rec.deadline is None or now + wait < rec.deadline)
            ):
                rec.next_retry_ts = now + wait
                return False
            _mark_route(rec.kwargs, "route.failed", reason="no_replica",
                        attempts=rec.attempts)
            self._seal_error(rec, rec.last_error or pick_err)
            return True
        rec.key = _rkey(replica)
        rec.attempts += 1
        try:
            rec.ref = replica.call.remote(rec.method, *rec.args, **rec.kwargs)
        except BaseException as err:  # noqa: BLE001
            return self._on_error(rec, err)
        _mark_route(rec.kwargs, "route.dispatched", replica=rec.key[:12],
                    attempt=rec.attempts)
        return False

    def _resubmit(self, rec: _TrackedCall) -> bool:
        rec.next_retry_ts = None
        try:
            replica = rec.rset.pick(
                rec.model_id, exclude=rec.failed_keys, admission=False
            )
        except BaseException as pick_err:  # noqa: BLE001
            # nothing routable right now (controller may still be
            # restarting replicas): burn one attempt waiting, or give up
            rec.attempts += 1
            now = time.time()
            wait = _retry_backoff_s(rec.attempts)
            if (
                rec.attempts < rec.max_attempts
                and (rec.deadline is None or now + wait < rec.deadline)
            ):
                rec.next_retry_ts = now + wait
                return False
            _mark_route(rec.kwargs, "route.failed", reason="no_replica",
                        attempts=rec.attempts)
            self._seal_error(rec, rec.last_error or pick_err)
            return True
        rec.key = _rkey(replica)
        rec.attempts += 1
        _counter(
            "raytpu_serve_failovers_total",
            "serve requests failed over to a different replica",
        ).inc()
        _mark_route(rec.kwargs, "route.failover", attempt=rec.attempts)
        try:
            rec.ref = replica.call.remote(rec.method, *rec.args, **rec.kwargs)
        except BaseException as err:  # noqa: BLE001
            return self._on_error(rec, err)
        _mark_route(rec.kwargs, "route.dispatched", replica=rec.key[:12],
                    attempt=rec.attempts)
        return False
