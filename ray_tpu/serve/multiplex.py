"""Model multiplexing: many models per replica with LRU residency.

Reference parity: @serve.multiplexed + get_multiplexed_model_id
(/root/reference/python/ray/serve/multiplex.py, llm LoRA multiplexing in
llm/_internal/serve/deployments/llm/multiplex/). A replica hosts up to N
models; the router prefers replicas that already hold the requested
model (affinity in router.py), so hot models stay loaded — the LoRA
adapter-serving pattern.
"""

from __future__ import annotations

import collections
import functools
import threading
from typing import Any, Callable, Optional

_context = threading.local()


def get_multiplexed_model_id() -> str:
    """The model id of the CURRENT request (inside a replica method),
    '' when the request carried none."""
    return getattr(_context, "model_id", "")


def _set_model_id(model_id: Optional[str]) -> None:
    _context.model_id = model_id or ""


def multiplexed(
    func: Optional[Callable] = None, *, max_num_models_per_replica: int = 3
):
    """Decorate a `def get_model(self, model_id)` loader: calls are cached
    per replica instance with LRU eviction beyond the cap, so switching
    between ≤N models costs one load each."""

    def wrap(fn: Callable) -> Callable:
        cache_attr = f"_serve_mux_{fn.__name__}"
        lock_attr = cache_attr + "_lock"

        @functools.wraps(fn)
        def loader(self, model_id: str) -> Any:
            lock = getattr(self, lock_attr, None)
            if lock is None:
                lock = threading.Lock()
                setattr(self, lock_attr, lock)
            with lock:
                cache = getattr(self, cache_attr, None)
                if cache is None:
                    cache = collections.OrderedDict()
                    setattr(self, cache_attr, cache)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
            model = fn(self, model_id)  # load OUTSIDE the lock (slow I/O)
            with lock:
                cache[model_id] = model
                cache.move_to_end(model_id)
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)  # evict least-recently-used
            return model

        loader.__serve_multiplexed__ = True
        return loader

    return wrap(func) if func is not None else wrap
