"""Distributed queue: an actor-backed FIFO shared by tasks and actors.

Reference parity: ray.util.queue.Queue (/root/reference/python/ray/util/
queue.py) — put/get/qsize across the cluster, Empty/Full mirroring the
stdlib queue exceptions.
"""

from __future__ import annotations

import time
from typing import Any, List, Optional

from .. import api


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self._items: List[Any] = []

    def put(self, item: Any) -> bool:
        if self.maxsize > 0 and len(self._items) >= self.maxsize:
            return False
        self._items.append(item)
        return True

    def get(self) -> tuple:
        if not self._items:
            return (False, None)
        return (True, self._items.pop(0))

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)


class Queue:
    """Cluster-visible FIFO. Pass the Queue object into tasks/actors; all
    holders share the one backing actor."""

    def __init__(self, maxsize: int = 0, *, actor_options: Optional[dict] = None):
        cls = api.remote(_QueueActor)
        if actor_options:
            cls = cls.options(**actor_options)
        self._actor = cls.remote(maxsize)
        self.maxsize = maxsize

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if api.get(self._actor.put.remote(item)):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() >= deadline:
                raise Full
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = api.get(self._actor.get.remote())
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() >= deadline:
                raise Empty
            time.sleep(0.01)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        return api.get(self._actor.qsize.remote())

    def empty(self) -> bool:
        return api.get(self._actor.empty.remote())

    def full(self) -> bool:
        return api.get(self._actor.full.remote())

    def shutdown(self) -> None:
        try:
            api.kill(self._actor)
        except Exception:
            pass
