"""Postmortem bundles: one archive that explains a cluster episode.

Reference parity: Ray's GCS is the durable source of truth that makes
cluster episodes debuggable after the fact (arxiv 1712.05889); its
dashboard snapshots state for support bundles. TPU inversion: the
driver already holds every observability plane this framework built —
the flight-recorder event tail (util/events + the GCS ``_events``
table), the distributed span buffers (util/tracing), the federated
``/metrics/cluster`` exposition, per-node stats snapshots, and profile
capture metas. ``build_bundle`` snapshots them all into one ``.tgz``
whose ``timeline.json`` is the EPISODE RECONSTRUCTION: runtime spans
and typed events stitched into a single wall-clock-aligned Perfetto
timeline (slices + instant events + cross-lane flow arrows) via the
existing trace_dump merge path — open it in ui.perfetto.dev and read
the preemption → emergency checkpoint → gang restart → resume story
off one screen.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import tarfile
import time
from typing import Any, Dict, List, Optional

__all__ = ["build_bundle", "collect_planes", "load_bundle", "reconstruct_timeline"]


def _all_spans() -> List[Dict[str, Any]]:
    """Every completed span we can reach: the local ring plus each
    cluster node's (the same stitch trace_dump does for full exports)."""
    from ..core import runtime as _rt
    from .tracing import tracer

    spans = {s["span_id"]: s for s in tracer().spans()}
    if _rt.is_initialized():
        ctx = getattr(_rt.get_runtime(), "cluster", None)
        if ctx is not None:
            fanned = ctx.fanout_nodes(
                "node_spans", None, 10_000, placeholder=lambda e: []
            )
            for node_spans in fanned.values():
                for s in node_spans or []:
                    spans.setdefault(s["span_id"], s)
    return sorted(spans.values(), key=lambda s: s["start_ts"])


def collect_planes(note: str = "") -> Dict[str, Any]:
    """Gather the bundle pieces from the live runtime. Every plane is
    best-effort — a postmortem of a half-dead cluster must still build
    from whatever still answers."""
    from . import state

    pieces: Dict[str, Any] = {"note": note, "created_at": time.time()}

    def grab(key, fn, fallback):
        try:
            pieces[key] = fn()
        except Exception as exc:  # noqa: BLE001 - partial bundles beat none
            pieces[key] = fallback
            pieces.setdefault("errors", {})[key] = repr(exc)

    grab("events", lambda: state.events(limit=0), [])
    grab("spans", _all_spans, [])
    grab("metrics", lambda: state.cluster_metrics(raw=False), "")
    grab("node_stats", state.node_stats, {})
    grab("nodes", state.list_nodes, [])
    grab("profiles", state.list_profiles, [])
    grab("summary", state.summary, {})
    return pieces


def reconstruct_timeline(events: List[Dict[str, Any]],
                         spans: List[Dict[str, Any]]) -> str:
    """Stitch typed events and runtime spans into one Perfetto JSON
    string. Spans render as nested slices with cross-lane flow arrows
    (export_chrome_trace); events become global instant events on a
    per-node ``events:<node>`` track, tid'd by emitting subsystem, so
    the announcement/checkpoint/restart breadcrumbs line up against the
    span slices on the shared wall clock."""
    from .tracing import export_chrome_trace

    instants: List[Dict[str, Any]] = []
    for e in events:
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            continue
        node = str(e.get("node") or "local")
        extra = e.get("extra") or {}
        instants.append({
            "name": e.get("kind") or f"{e.get('source', '?')}",
            "cat": "events",
            "ph": "i",
            "s": "g",  # global scope: draw the line across all tracks
            "ts": ts * 1e6,
            "pid": f"events:{node[:8]}",
            "tid": e.get("source", "events"),
            "args": {
                "severity": e.get("severity"),
                "kind": e.get("kind"),
                "message": e.get("message"),
                "node": e.get("node"),
                "seq": e.get("seq"),
                "mono": e.get("mono"),
                **{k: v for k, v in extra.items()
                   if isinstance(v, (str, int, float, bool, type(None)))},
            },
        })
    return export_chrome_trace(spans, extra_events=instants)


def build_bundle(output: str, note: str = "",
                 pieces: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Write the postmortem archive to `output` (a .tgz path; parent
    dirs are created). Members:

    - ``manifest.json``       creation time, note, per-file size+sha256
    - ``events.jsonl``        the cluster-wide typed event tail
    - ``spans.jsonl``         every reachable completed span
    - ``timeline.json``       the reconstructed Perfetto episode timeline
    - ``metrics_cluster.prom``  the federated Prometheus exposition
    - ``node_stats.json`` / ``nodes.json`` / ``profiles.json`` /
      ``summary.json``        cluster state at snapshot time

    The archive lands via tmp + os.replace (atomic-write discipline: a
    crash mid-build never leaves a torn bundle at the final path).
    Returns the manifest."""
    pieces = collect_planes(note) if pieces is None else pieces
    timeline = reconstruct_timeline(pieces.get("events", []),
                                    pieces.get("spans", []))
    members: Dict[str, bytes] = {
        "events.jsonl": "\n".join(
            json.dumps(e, default=str) for e in pieces.get("events", [])
        ).encode(),
        "spans.jsonl": "\n".join(
            json.dumps(s, default=str) for s in pieces.get("spans", [])
        ).encode(),
        "timeline.json": timeline.encode(),
        "metrics_cluster.prom": str(pieces.get("metrics", "")).encode(),
        "node_stats.json": json.dumps(
            pieces.get("node_stats", {}), default=str).encode(),
        "nodes.json": json.dumps(pieces.get("nodes", []), default=str).encode(),
        "profiles.json": json.dumps(
            pieces.get("profiles", []), default=str).encode(),
        "summary.json": json.dumps(
            pieces.get("summary", {}), default=str).encode(),
    }
    manifest = {
        "created_at": pieces.get("created_at", time.time()),
        "note": note,
        "errors": pieces.get("errors", {}),
        "counts": {
            "events": len(pieces.get("events", [])),
            "spans": len(pieces.get("spans", [])),
            "nodes": len(pieces.get("nodes", [])),
            "profiles": len(pieces.get("profiles", [])),
        },
        "files": {
            name: {
                "bytes": len(data),
                "sha256": hashlib.sha256(data).hexdigest(),
            }
            for name, data in members.items()
        },
    }
    members["manifest.json"] = json.dumps(
        manifest, indent=2, default=str).encode()

    output = os.path.abspath(output)
    os.makedirs(os.path.dirname(output) or ".", exist_ok=True)
    tmp = output + ".tmp"
    with tarfile.open(tmp, "w:gz") as tar:
        for name, data in sorted(members.items()):
            info = tarfile.TarInfo(name)
            info.size = len(data)
            info.mtime = int(manifest["created_at"])
            tar.addfile(info, io.BytesIO(data))
    os.replace(tmp, output)
    return manifest


def load_bundle(path: str) -> Dict[str, Any]:
    """Read a bundle back: JSON members parsed, JSONL members as lists,
    the exposition as text — what tests and the CLI inspect."""
    out: Dict[str, Any] = {}
    with tarfile.open(path, "r:gz") as tar:
        for member in tar.getmembers():
            data = tar.extractfile(member).read()
            if member.name.endswith(".jsonl"):
                out[member.name] = [
                    json.loads(line) for line in data.decode().splitlines()
                    if line.strip()
                ]
            elif member.name.endswith(".json"):
                out[member.name] = json.loads(data.decode())
            else:
                out[member.name] = data.decode()
    return out
