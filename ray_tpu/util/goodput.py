"""Goodput accounting: where did the run's wall time actually go?

The Gemma-on-TPU fine-tuning comparisons (PAPERS.md, arxiv 2605.25645)
show that step time alone hides exactly the costs a preemptible fleet
pays: restarts, checkpoint traffic, input stalls. This module turns the
signals the runtime already has — controller phase transitions, worker
reports, preemption notices, the stall watchdog — into a wall-time
partition over named buckets:

- ``init``          gang start, placement, process/compile bring-up
- ``compile``       explicitly-reported XLA compile time (split out of
                    init when the trainer reports ``compile_s``)
- ``step_compute``  productive training steps — the GOODPUT
- ``dp_sync``       data-parallel gradient sync share of the step
                    windows (reported ``dp_sync_s``, the train/steplog
                    wire-byte estimate)
- ``input_wait``    host input pipeline stalls (reported ``input_wait_s``)
- ``ckpt_save``     checkpoint saves, incl. the emergency-save window
                    after a preemption notice
- ``ckpt_restore``  restore + restart backoff after a failure
- ``preempt_restart`` gang teardown/re-mesh after an announced preemption
- ``stall``         time the stall watchdog held the run stalled
- ``other``         anything not attributed (closed runs: ~0)

Invariant: the accountant is a STATE MACHINE over one wall clock —
``begin(bucket)`` closes the previous bucket at now and opens the next,
and ``transfer`` only moves seconds between buckets — so the bucket sums
always equal the run's wall time to float precision. That is what lets
the acceptance check "buckets sum to wall time within ±5%" hold by
construction rather than by luck.

Every ``report()`` publishes ``raytpu_train_goodput_seconds{run,bucket}``
and ``raytpu_train_goodput_fraction{run}`` so the scrape, the BENCH
JSON ``goodput`` block, and ``Result.goodput`` all show the same
numbers. The serve-side analogue is ``serve_slo_report()`` over the
PR 5 ``ServeSLOMonitor`` window ledger.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

BUCKETS = (
    "init", "compile", "step_compute", "dp_sync", "input_wait",
    "ckpt_save", "ckpt_restore", "preempt_restart", "stall", "other",
)

# the productive share — everything else is badput
PRODUCTIVE_BUCKETS = ("step_compute",)


class GoodputAccountant:
    """Partition a run's wall clock into the BUCKETS above."""

    def __init__(self, run_name: str):
        self.run_name = run_name
        self._lock = threading.Lock()
        self._seconds: Dict[str, float] = {b: 0.0 for b in BUCKETS}
        self._current: Optional[str] = None
        self._current_since = 0.0
        self._started_mono: Optional[float] = None
        self._started_wall: Optional[float] = None
        self._ended_mono: Optional[float] = None

    # ------------------------------------------------------------ transitions

    @property
    def current(self) -> Optional[str]:
        return self._current

    def begin(self, bucket: str) -> None:
        """Close the open bucket at now, open `bucket` (first call also
        starts the run clock). Unknown buckets land in `other` rather
        than raising — accounting must never kill a training run."""
        if bucket not in self._seconds:
            bucket = "other"
        now = time.monotonic()
        with self._lock:
            if self._started_mono is None:
                self._started_mono = now
                self._started_wall = time.time()
            if self._current is not None:
                self._seconds[self._current] += max(
                    0.0, now - self._current_since
                )
            self._current = bucket
            self._current_since = now

    def transfer(self, src: str, dst: str, seconds: float) -> None:
        """Re-attribute already-accounted seconds (e.g. a worker report
        says 0.3s of the last window was input wait). Clamped to what
        `src` actually holds, so the wall-time invariant survives a
        misreporting trainer."""
        if src not in self._seconds or dst not in self._seconds:
            return
        with self._lock:
            moved = max(0.0, min(float(seconds), self._seconds[src]))
            self._seconds[src] -= moved
            self._seconds[dst] += moved

    def finish(self) -> None:
        """End the run clock (idempotent)."""
        now = time.monotonic()
        with self._lock:
            if self._started_mono is None or self._ended_mono is not None:
                return
            if self._current is not None:
                self._seconds[self._current] += max(
                    0.0, now - self._current_since
                )
                self._current = None
            self._ended_mono = now

    # --------------------------------------------------------------- reading

    def wall_time_s(self) -> float:
        with self._lock:
            if self._started_mono is None:
                return 0.0
            end = self._ended_mono if self._ended_mono is not None \
                else time.monotonic()
            return max(0.0, end - self._started_mono)

    def report(self, publish: bool = True) -> Dict[str, Any]:
        """The goodput report: bucket seconds (open bucket counted up to
        now), wall time, goodput fraction. With publish=True (default)
        the same numbers land on the run-labeled gauges."""
        now = time.monotonic()
        with self._lock:
            buckets = dict(self._seconds)
            if self._current is not None and self._ended_mono is None:
                buckets[self._current] += max(0.0, now - self._current_since)
            if self._started_mono is None:
                wall = 0.0
            else:
                end = self._ended_mono if self._ended_mono is not None else now
                wall = max(0.0, end - self._started_mono)
            started_wall = self._started_wall
        goodput_s = sum(buckets[b] for b in PRODUCTIVE_BUCKETS)
        out = {
            "run": self.run_name,
            "started_at": started_wall,
            "wall_time_s": round(wall, 6),
            "buckets": {b: round(s, 6) for b, s in buckets.items()},
            "goodput_s": round(goodput_s, 6),
            "badput_s": round(max(0.0, wall - goodput_s), 6),
            "goodput_fraction": round(goodput_s / wall, 6) if wall > 0 else 0.0,
            # the streaming-data acceptance number: share of wall time
            # the gang spent waiting on its input pipeline
            "input_wait_fraction": (
                round(buckets["input_wait"] / wall, 6) if wall > 0 else 0.0
            ),
        }
        if publish:
            self._publish(out)
        return out

    def _publish(self, report: Dict[str, Any]) -> None:
        from .metrics import get_or_create_gauge

        try:
            gauge = get_or_create_gauge(
                "raytpu_train_goodput_seconds",
                "Wall-time attribution of a training run by bucket "
                "(step_compute is the goodput; buckets sum to wall time).",
                tag_keys=("run", "bucket"),
            )
            for bucket, seconds in report["buckets"].items():
                gauge.set(float(seconds),
                          tags={"run": self.run_name, "bucket": bucket})
            get_or_create_gauge(
                "raytpu_train_goodput_fraction",
                "Productive (step_compute) share of a training run's "
                "wall time.",
                tag_keys=("run",),
            ).set(float(report["goodput_fraction"]),
                  tags={"run": self.run_name})
        except Exception:  # noqa: BLE001 - accounting must not kill training
            pass

    # ------------------------------------------------------- report plumbing

    # metrics keys a worker report may carry, mapped to (src, dst)
    # re-attributions of the window they were measured in
    _REPORT_TRANSFERS = {
        "input_wait_s": ("step_compute", "input_wait"),
        "ckpt_save_s": ("step_compute", "ckpt_save"),
        # the steplog-estimated gradient-sync share of the window: sync
        # seconds stop being silently folded into step_compute (still
        # summing to wall time — transfer only moves seconds)
        "dp_sync_s": ("step_compute", "dp_sync"),
        "compile_s": ("init", "compile"),
    }

    def observe_report_metrics(self, metrics: Any) -> None:
        """Fold a rank-0 report's self-measured phase seconds into the
        partition (trainers that report input_wait_s / ckpt_save_s /
        compile_s get them split out of the enclosing bucket)."""
        if not isinstance(metrics, dict):
            return
        for key, (src, dst) in self._REPORT_TRANSFERS.items():
            value = metrics.get(key)
            if isinstance(value, (int, float)) and value > 0:
                self.transfer(src, dst, float(value))


# ------------------------------------------------------------ serve analogue


def serve_slo_report() -> Dict[str, Any]:
    """Serve-side SLO attainment (the serving analogue of the train
    goodput report), read off the ServeSLOMonitor window ledger: for
    each configured SLO, windows evaluated vs violated and the
    attainment fraction (also exported as
    raytpu_serve_slo_attainment{slo})."""
    from .watchdog import serve_slo_monitor

    slos = serve_slo_monitor().attainment_report()
    return {
        "slos": slos,
        "attainment": (
            min(s["attainment"] for s in slos.values()) if slos else 1.0
        ),
    }
