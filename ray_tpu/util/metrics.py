"""Metrics: Counter/Gauge/Histogram registry with Prometheus exposition.

Reference parity: python/ray/util/metrics.py (user-facing metric types) +
the per-node metrics agent exporting OpenCensus → Prometheus
(_private/metrics_agent.py). Single-process inversion: one registry, a
stdlib HTTP /metrics endpoint, and callback gauges that sample runtime
internals (scheduler/object-store/serve stats) at scrape time instead of a
push pipeline.
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

TagDict = Dict[str, str]


def _tags_key(tags: Optional[TagDict]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((tags or {}).items()))


def _escape_label(value: Any) -> str:
    """Escape a label VALUE per the Prometheus exposition spec
    (backslash, double-quote, newline) — raw occurrences of any of these
    make the whole scrape payload unparseable."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(text: str) -> str:
    """HELP text escaping: backslash and newline only (quotes are legal)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Sequence[str] = ()):
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._lock = threading.Lock()
        _registry().register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, description="", tag_keys=()):
        super().__init__(name, description, tag_keys)
        self._values: Dict[tuple, float] = {}  # guarded-by: _lock

    def inc(self, value: float = 1.0, tags: Optional[TagDict] = None) -> None:
        key = _tags_key(tags)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def collect(self):
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, description="", tag_keys=(), fn: Optional[Callable[[], Any]] = None):
        super().__init__(name, description, tag_keys)
        self._values: Dict[tuple, float] = {}
        self._fn = fn  # callback gauge: sampled at scrape time
        self._fn_warned = False

    def set(self, value: float, tags: Optional[TagDict] = None) -> None:
        with self._lock:
            self._values[_tags_key(tags)] = float(value)

    def collect(self):
        if self._fn is not None:
            try:
                sampled = self._fn()
            except Exception as exc:  # noqa: BLE001 - a sampler must not kill the scrape
                # One WARNING event per gauge lifetime: a permanently
                # broken sampler used to return [] forever, silently.
                if not self._fn_warned:
                    self._fn_warned = True
                    from .events import emit

                    emit("WARNING", "metrics",
                         f"callback gauge {self.name} sampler raised; "
                         f"series suppressed until it recovers: {exc!r}",
                         kind="metrics.sampler_error", metric=self.name)
                return []
            # A callback may honor tag_keys by returning tagged samples:
            # an iterable of (tags_dict, value) pairs. A bare number stays
            # the single untagged series.
            if isinstance(sampled, (int, float)):
                return [({}, float(sampled))]
            return [(dict(tags or {}), float(value)) for tags, value in sampled]
        with self._lock:
            return [(dict(k), v) for k, v in self._values.items()]


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, description="", boundaries: Sequence[float] = (), tag_keys=()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries) or [0.01, 0.1, 1.0, 10.0]
        self._counts: Dict[tuple, List[int]] = {}
        self._sums: Dict[tuple, float] = {}
        self._totals: Dict[tuple, int] = {}

    def observe(self, value: float, tags: Optional[TagDict] = None) -> None:
        key = _tags_key(tags)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * (len(self.boundaries) + 1))
            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._totals[key] = self._totals.get(key, 0) + 1

    def collect(self):
        with self._lock:
            out = []
            for key, counts in self._counts.items():
                out.append(
                    (dict(key), {
                        "buckets": list(zip(self.boundaries, counts)),
                        "sum": self._sums[key],
                        "count": self._totals[key],
                    })
                )
            return out


class MetricsRegistry:
    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> None:
        with self._lock:
            self._metrics[metric.name] = metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()

    def prometheus_text(self) -> str:
        """Prometheus exposition format (the /metrics payload)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.append(f"# HELP {m.name} {_escape_help(m.description)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for tags, value in m.collect():
                label = (
                    "{" + ",".join(
                        f'{k}="{_escape_label(v)}"' for k, v in sorted(tags.items())
                    ) + "}"
                    if tags
                    else ""
                )
                if m.kind == "histogram":
                    # bucket lines carry the metric's tag labels plus le, so
                    # tagged histograms stay distinct series
                    tag_part = "".join(
                        f'{k}="{_escape_label(v)}",' for k, v in sorted(tags.items())
                    )
                    cumulative = 0
                    for bound, count in value["buckets"]:
                        cumulative += count
                        lines.append(
                            f'{m.name}_bucket{{{tag_part}le="{bound}"}} {cumulative}'
                        )
                    lines.append(
                        f'{m.name}_bucket{{{tag_part}le="+Inf"}} {value["count"]}'
                    )
                    lines.append(f"{m.name}_sum{label} {value['sum']}")
                    lines.append(f"{m.name}_count{label} {value['count']}")
                else:
                    lines.append(f"{m.name}{label} {value}")
        return "\n".join(lines) + "\n"


_REGISTRY: Optional[MetricsRegistry] = None
_REG_LOCK = threading.Lock()


def _registry() -> MetricsRegistry:
    global _REGISTRY
    with _REG_LOCK:
        if _REGISTRY is None:
            _REGISTRY = MetricsRegistry()
        return _REGISTRY


def registry() -> MetricsRegistry:
    return _registry()


def get_or_create_counter(name: str, description: str = "",
                          tag_keys: Sequence[str] = ()) -> Counter:
    """Idempotent Counter accessor for emitters that may re-run (runtime
    re-init, module reload): returns the registered series instead of
    shadowing it with a fresh zeroed one."""
    existing = _registry().get(name)
    if isinstance(existing, Counter):
        return existing
    return Counter(name, description, tag_keys)


def get_or_create_gauge(name: str, description: str = "",
                        tag_keys: Sequence[str] = (),
                        fn: Optional[Callable[[], Any]] = None) -> Gauge:
    """Idempotent Gauge accessor (see get_or_create_counter)."""
    existing = _registry().get(name)
    if isinstance(existing, Gauge):
        return existing
    return Gauge(name, description, tag_keys, fn=fn)


# Shared boundaries for per-phase step-time histograms
# (raytpu_train_step_seconds{run,bucket}, train/steplog): phase durations
# span sub-millisecond host bookkeeping up to multi-second checkpoint
# saves, so the grid is log-spaced across five decades.
STEP_SECONDS_BOUNDARIES = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)


def get_or_create_histogram(name: str, description: str = "",
                            boundaries: Sequence[float] = (),
                            tag_keys: Sequence[str] = ()) -> Histogram:
    """Idempotent Histogram accessor (see get_or_create_counter) — the
    span-derived latency observers run on every task/request, so they
    must hit the registered series, never shadow it with a zeroed one."""
    existing = _registry().get(name)
    if isinstance(existing, Histogram):
        return existing
    return Histogram(name, description, boundaries, tag_keys)


def register_runtime_gauges() -> None:
    """Callback gauges over live runtime internals (scrape-time sampling)."""
    from ..core import runtime as rt

    def usage(key):
        def sample():
            if not rt.is_initialized():
                return 0.0
            return float(rt.get_runtime().object_store.usage()[key])

        return sample

    Gauge("raytpu_object_store_host_bytes", "host-tier bytes", fn=usage("host_bytes"))
    Gauge("raytpu_object_store_num_objects", "objects in store", fn=usage("num_objects"))

    def tasks_finished():
        if not rt.is_initialized():
            return 0.0
        return float(len(rt.get_runtime().task_events()))

    Gauge("raytpu_tasks_finished_total", "completed task events", fn=tasks_finished)


# ------------------------------------------------------ head-side federation


def _inject_label(line: str, key: str, value: str) -> str:
    """Add one label to a Prometheus sample line. Label VALUES may
    contain spaces/braces inside quotes, but metric NAMES cannot — so
    the first '{' (when it precedes the first space) marks an existing
    label set, else the first space splits name from value."""
    brace = line.find("{")
    space = line.find(" ")
    pair = f'{key}="{_escape_label(value)}"'
    if brace != -1 and (space == -1 or brace < space):
        return f"{line[:brace + 1]}{pair},{line[brace + 1:]}"
    if space == -1:
        return line  # malformed; pass through untouched
    return f"{line[:space]}{{{pair}}}{line[space:]}"


def merge_cluster_expositions(parts: Dict[str, str],
                              label: str = "node_id") -> str:
    """Merge per-node Prometheus expositions into ONE parseable payload:
    every sample line gains a `node_id` label, HELP/TYPE headers are
    emitted once per metric family, and each family's samples stay
    grouped under its header (the exposition-format grouping rule).

    `parts` maps node id hex -> that node's /metrics text (the
    `metrics_snapshot` RPC payload)."""
    families: List[str] = []          # first-seen order
    headers: Dict[str, List[str]] = {}  # family -> [# HELP, # TYPE]
    samples: Dict[str, List[str]] = {}  # family -> labeled sample lines
    for node_hex, text in parts.items():
        family = None
        for line in (text or "").splitlines():
            if not line.strip():
                continue
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                name = line.split(" ", 3)[2]
                if name not in headers:
                    headers[name] = []
                    families.append(name)
                    samples[name] = []
                # keep the first node's header text (identical by
                # construction; divergence would mean version skew)
                if len(headers[name]) < 2 and line not in headers[name]:
                    headers[name].append(line)
                family = name
                continue
            labeled = _inject_label(line, label, node_hex)
            if family is not None:
                samples[family].append(labeled)
            else:  # headerless line (foreign exporter): own family
                name = line.split("{", 1)[0].split(" ", 1)[0]
                if name not in headers:
                    headers[name] = []
                    families.append(name)
                    samples[name] = []
                samples[name].append(labeled)
    lines: List[str] = []
    for fam in families:
        lines.extend(headers[fam])
        lines.extend(samples[fam])
    return "\n".join(lines) + "\n"


def cluster_prometheus_text() -> str:
    """The federated /metrics/cluster payload: this process's registry
    plus every reachable node agent's (over the `metrics_snapshot` RPC),
    merged with per-sample node_id labels. Degrades to the local
    registry (labeled with the local node id) without a cluster."""
    from ..core import runtime as rt

    local_text = registry().prometheus_text()
    if not rt.is_initialized():
        return merge_cluster_expositions({"local": local_text})
    runtime = rt.get_runtime()
    ctx = getattr(runtime, "cluster", None)
    if ctx is None:
        local_hex = runtime.scheduler.head_node().node_id.hex()
        return merge_cluster_expositions({local_hex: local_text})
    parts: Dict[str, str] = {ctx.node_id.hex(): local_text}
    fanned = ctx.fanout_nodes("metrics_snapshot", placeholder=lambda e: None)
    for node_hex, text in fanned.items():
        if text:
            parts[node_hex] = text
    return merge_cluster_expositions(parts)


def start_metrics_server(host: str = "127.0.0.1", port: int = 0) -> int:
    """Expose /metrics (this process) and /metrics/cluster (federated,
    node_id-labeled); returns the bound port."""
    import socketserver
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            path = self.path.rstrip("/") or "/metrics"
            if path == "/metrics/cluster":
                body = cluster_prometheus_text().encode()
            elif path in ("", "/metrics"):
                body = registry().prometheus_text().encode()
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    class Server(ThreadingHTTPServer):
        daemon_threads = True

        def server_bind(self):
            # skip getfqdn (hangs without DNS egress)
            socketserver.TCPServer.server_bind(self)
            self.server_name = self.server_address[0]
            self.server_port = self.server_address[1]

    server = Server((host, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True, name="metrics-http")
    thread.start()
    return server.server_address[1]
