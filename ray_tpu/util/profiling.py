"""Device-level profiling: the jax.profiler bridge.

Reference parity: the tracing/profiling aux subsystem (SURVEY.md §5 —
the reference wires OpenTelemetry spans through its workers and `ray
timeline` dumps chrome traces). TPU inversion: the interesting timeline
is on the DEVICE, and XLA already has a first-class profiler. This
module is the thin, always-importable bridge:

- ``device_trace(logdir)`` captures a TensorBoard-loadable XLA trace
  (HLO timings, memory, ICI collectives) around any block of work.
- ``start_profiler_server(port)`` exposes the live profiling endpoint
  that `tensorboard --logdir` / `xprof` can attach to on demand.
- ``annotate(name)`` labels host-side regions so device traces line up
  with runtime phases (engine ticks, train steps).

Host-side task timelines remain in util/state.py (`chrome_tracing_dump`,
`ray_tpu timeline`); the two views compose — state.py tells you WHAT the
runtime ran, this module tells you what the CHIP did during it.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


def start_device_trace(logdir: str) -> None:
    """Begin capturing an XLA device trace into `logdir` (view with
    TensorBoard's profile plugin)."""
    import jax

    jax.profiler.start_trace(logdir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Context manager form: everything dispatched inside is captured.
    Remember to block_until_ready/fetch inside the block — work still in
    flight when the trace stops is cut off."""
    start_device_trace(logdir)
    try:
        yield
    finally:
        stop_device_trace()


def start_profiler_server(port: int = 9999):
    """Serve the live profiling endpoint (attach with TensorBoard:
    capture profile -> 'localhost:<port>')."""
    import jax

    return jax.profiler.start_server(port)


def annotate(name: str, **kwargs):
    """Named host-side region that shows up in device traces
    (jax.profiler.TraceAnnotation) — use around engine ticks/train steps
    so runtime phases line up with HLO activity."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """StepTraceAnnotation wrapper: marks step boundaries so the profile
    viewer's per-step breakdown works."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield
