"""Profiling plane: device/host capture + compiled-graph cost accounting.

Reference parity: the tracing/profiling aux subsystem (SURVEY.md §5 —
the reference ships `ray timeline` and per-worker profiling as a
first-class subsystem). TPU inversion: the interesting timeline is on
the DEVICE, and XLA already has a first-class profiler *and* a
first-class cost model — so this module is three things:

1. The **jax.profiler bridge** (`device_trace`, `start_profiler_server`,
   `annotate`) with typed errors (`ProfilingError`) instead of raw jax
   exceptions, an idempotent profiler server whose port rides the node
   stats snapshot, and `capture_local_profile` — a time-boxed device
   trace plus a host-side sampling profile, collected as bounded
   artifact bytes the cluster capture RPC ships back to the head.
2. The **cost-model layer**: `step_cost` reads
   ``compiled.cost_analysis()`` FLOPs/bytes off any jitted/compiled
   step, `device_peaks` prices them against the detected chip's peak
   FLOPs/HBM bandwidth, and `roofline` turns (cost, step time) into
   MFU + roofline fractions — the currency every TPU perf claim is
   quoted in. bench.py and the train/serve MFU gauges all go through
   here instead of hand-maintained constants.
3. The **ProfileStore**: captured artifacts registered on the driver so
   `state.list_profiles()/get_profile()`, `ray_tpu profile`, and the
   dashboard download route can reach them, and `trace_dump` can merge
   a capture's device events into the Perfetto export.

Import discipline: jax imports stay FUNCTION-LOCAL so this module (and
core/stats.py, which reads `node_snapshot()`) imports on jax-less
observer hosts — enforced by scripts/check_lazy_jax.py.
"""

from __future__ import annotations

import contextlib
import dataclasses
import gzip
import json
import os
import sys
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ..core.exceptions import ProfilingError

# ----------------------------------------------------- device trace (typed)

# Module-level latch: jax.profiler allows one trace per process, and its
# double-start/orphan-stop failures are raw RuntimeErrors with
# backend-specific strings. The latch lets us raise typed errors BEFORE
# touching jax, and lets captures report "busy" instead of colliding.
_trace_lock = threading.Lock()
_trace_logdir: Optional[str] = None


def start_device_trace(logdir: str, *, perfetto: bool = True) -> None:
    """Begin capturing an XLA device trace into `logdir` (view with
    TensorBoard's profile plugin or ui.perfetto.dev). Raises
    `ProfilingError` when a trace is already active or jax is missing."""
    global _trace_logdir
    with _trace_lock:
        if _trace_logdir is not None:
            raise ProfilingError(
                f"a device trace into {_trace_logdir!r} is already active; "
                f"stop it before starting another"
            )
        try:
            import jax
        except ImportError as exc:
            raise ProfilingError(f"device tracing requires jax: {exc!r}") from exc
        try:
            jax.profiler.start_trace(logdir, create_perfetto_trace=perfetto)
        except Exception as exc:  # noqa: BLE001 - typed boundary
            raise ProfilingError(f"start_trace failed: {exc!r}") from exc
        _trace_logdir = logdir


def stop_device_trace() -> None:
    """Stop the active device trace. Raises `ProfilingError` (not a raw
    jax RuntimeError) when no trace is active."""
    global _trace_logdir
    with _trace_lock:
        if _trace_logdir is None:
            raise ProfilingError("no active device trace to stop")
        import jax

        try:
            jax.profiler.stop_trace()
        except Exception as exc:  # noqa: BLE001 - typed boundary
            raise ProfilingError(f"stop_trace failed: {exc!r}") from exc
        finally:
            _trace_logdir = None


def device_trace_active() -> bool:
    return _trace_logdir is not None


@contextlib.contextmanager
def device_trace(logdir: str) -> Iterator[None]:
    """Context manager form: everything dispatched inside is captured.
    Remember to block_until_ready/fetch inside the block — work still in
    flight when the trace stops is cut off."""
    start_device_trace(logdir)
    try:
        yield
    finally:
        stop_device_trace()


# --------------------------------------------------- profiler server (xprof)

_server_lock = threading.Lock()
_profiler_server: Any = None
_profiler_server_port: Optional[int] = None


def start_profiler_server(port: int = 9999):
    """Serve the live profiling endpoint (attach with TensorBoard/xprof:
    capture profile -> 'localhost:<port>'). Idempotent: repeat calls
    return the existing server (jax allows one per process); the bound
    port is advertised in the node stats snapshot (`node_snapshot`) so
    operators can attach on demand."""
    global _profiler_server, _profiler_server_port
    with _server_lock:
        if _profiler_server is not None:
            return _profiler_server
        try:
            import jax
        except ImportError as exc:
            raise ProfilingError(
                f"the profiler server requires jax: {exc!r}"
            ) from exc
        try:
            _profiler_server = jax.profiler.start_server(port)
        except Exception as exc:  # noqa: BLE001 - typed boundary
            raise ProfilingError(
                f"profiler server failed to start on port {port}: {exc!r}"
            ) from exc
        _profiler_server_port = port
        return _profiler_server


def profiler_server_port() -> Optional[int]:
    """Port of the live profiler server, or None when not started."""
    return _profiler_server_port


# ----------------------------------------------------------- annotations

def annotate(name: str, **kwargs):
    """Named host-side region that shows up in device traces
    (jax.profiler.TraceAnnotation) — use around engine ticks/train steps
    so runtime phases line up with HLO activity."""
    import jax

    return jax.profiler.TraceAnnotation(name, **kwargs)


@contextlib.contextmanager
def step_annotation(step: int, name: str = "train") -> Iterator[None]:
    """StepTraceAnnotation wrapper: marks step boundaries so the profile
    viewer's per-step breakdown works."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=step):
        yield


# ------------------------------------------------------ host-side profiling


class HostProfiler:
    """Time-boxed sampling profiler over EVERY thread of this process
    (``sys._current_frames()`` at a fixed interval). cProfile instruments
    only the installing thread, which is useless for profiling an agent
    whose work happens on RPC/worker/engine threads — sampling sees them
    all, stdlib-only, at bounded overhead."""

    def __init__(self, interval_s: float = 0.005):
        self.interval_s = interval_s
        self._counts: Dict[Tuple[str, str], int] = {}
        self._samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ray_tpu-host-profiler"
        )
        self._thread.start()

    def _loop(self) -> None:
        names = {}
        while not self._stop.wait(self.interval_s):
            if not names:
                names = {t.ident: t.name for t in threading.enumerate()}
            self._samples += 1
            for tid, frame in list(sys._current_frames().items()):
                if frame is None:
                    continue
                stack: List[str] = []
                depth = 0
                while frame is not None and depth < 24:
                    code = frame.f_code
                    stack.append(
                        f"{os.path.basename(code.co_filename)}:"
                        f"{frame.f_lineno}:{code.co_name}"
                    )
                    frame = frame.f_back
                    depth += 1
                key = (names.get(tid, str(tid)), ";".join(reversed(stack)))
                self._counts[key] = self._counts.get(key, 0) + 1

    def stop(self) -> str:
        """Stop sampling; returns a text report: per-thread top stacks by
        sample count (a flamegraph collapses from the same lines)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
        lines = [
            f"# host sampling profile: {self._samples} samples @ "
            f"{self.interval_s * 1e3:.1f}ms"
        ]
        ranked = sorted(self._counts.items(), key=lambda kv: -kv[1])[:200]
        for (tname, stack), count in ranked:
            lines.append(f"{count}\t{tname}\t{stack}")
        return "\n".join(lines) + "\n"


# --------------------------------------------------------- local capture

# latch naming the capture currently running in this process (None = idle)
_capture_lock = threading.Lock()
_active_capture: Optional[str] = None
# summary of the most recent finished capture: shown by `ray_tpu status
# --verbose` via the node stats snapshot
_last_capture: Optional[Dict[str, Any]] = None


def capture_local_profile(duration_s: Optional[float] = None, *,
                          device: bool = True, host: bool = True,
                          profile_id: str = "",
                          workload: Optional[Callable[[], Any]] = None,
                          ) -> Dict[str, Any]:
    """One time-boxed capture of THIS process: a jax device trace and/or
    a host sampling profile, returned as bounded artifact bytes. This is
    the agent side of the cluster `profile_capture` RPC and the whole of
    the in-process path.

    Returns {"meta": {...}, "artifacts": {name: bytes}}. Never raises
    for a degraded capture (no jax, trace busy): the meta records what
    was skipped and why, so a fan-out over mixed nodes still returns."""
    import shutil
    import tempfile

    from ..core.config import cfg

    global _active_capture, _last_capture
    if duration_s is None:
        duration_s = cfg.profile_default_duration_s
    duration_s = max(0.05, float(duration_s))
    meta: Dict[str, Any] = {
        "profile_id": profile_id,
        "started_at": time.time(),
        "duration_s": duration_s,
        "pid": os.getpid(),
        "profiler_port": profiler_server_port(),
        "device": "skipped",
        "host": "skipped",
    }
    artifacts: Dict[str, bytes] = {}
    with _capture_lock:
        if _active_capture is not None:
            meta["device"] = meta["host"] = f"busy: capture {_active_capture}"
            return {"meta": meta, "artifacts": artifacts}
        _active_capture = profile_id or "local"
    logdir = None
    sampler = None
    try:
        if device:
            if sys.modules.get("jax") is None:
                # an observer/agent that never imported jax must not pay
                # the import (nor fail the host half of the capture)
                meta["device"] = "skipped: jax not imported in this process"
            else:
                logdir = tempfile.mkdtemp(prefix="ray_tpu_prof_")
                try:
                    start_device_trace(logdir)
                    meta["device"] = "ok"
                except ProfilingError as exc:
                    meta["device"] = f"error: {exc}"
                    logdir = None
        if host:
            sampler = HostProfiler(interval_s=cfg.profile_host_sample_s)
            sampler.start()
            meta["host"] = "ok"
        if workload is not None:
            deadline = time.time() + duration_s
            while time.time() < deadline:
                workload()
        else:
            time.sleep(duration_s)
    finally:
        if logdir is not None:
            try:
                stop_device_trace()
                artifacts.update(_collect_trace_artifacts(
                    logdir, cfg.profile_max_artifact_bytes
                ))
            except ProfilingError as exc:
                meta["device"] = f"error: {exc}"
            shutil.rmtree(logdir, ignore_errors=True)
        if sampler is not None:
            artifacts["host_profile.txt"] = sampler.stop().encode()
        with _capture_lock:
            _active_capture = None
    meta["bytes"] = sum(len(b) for b in artifacts.values())
    meta["artifact_names"] = sorted(artifacts)
    _last_capture = {
        "profile_id": profile_id, "ts": meta["started_at"],
        "duration_s": duration_s, "bytes": meta["bytes"],
        "device": meta["device"], "host": meta["host"],
    }
    return {"meta": meta, "artifacts": artifacts}


def _collect_trace_artifacts(logdir: str, max_bytes: int) -> Dict[str, bytes]:
    """Gather the profiler's output files (xplane, trace.json.gz,
    perfetto) as {relative_name: bytes}, bounded: the chrome-trace and
    perfetto files (the mergeable/viewable ones) are collected first,
    xplane blobs only with remaining budget."""
    files: List[Tuple[str, str]] = []
    for root, _dirs, names in os.walk(logdir):
        for name in names:
            full = os.path.join(root, name)
            files.append((os.path.relpath(full, logdir), full))
    # mergeable JSON traces first, then everything else by size ascending
    files.sort(key=lambda t: (
        0 if t[0].endswith(".trace.json.gz") else
        1 if t[0].endswith("perfetto_trace.json.gz") else 2,
        os.path.getsize(t[1]),
    ))
    out: Dict[str, bytes] = {}
    budget = max_bytes
    for rel, full in files:
        size = os.path.getsize(full)
        if size > budget:
            continue
        with open(full, "rb") as f:
            out[rel.replace(os.sep, "/")] = f.read()
        budget -= size
    return out


def node_snapshot() -> Dict[str, Any]:
    """This process's profiling status for the node stats snapshot
    (core/stats.py): profiler-server port, whether a capture is running,
    and the last finished capture's summary."""
    with _capture_lock:
        active = _active_capture
    return {
        "server_port": _profiler_server_port,
        "active_capture": active,
        "last_capture": dict(_last_capture) if _last_capture else None,
    }


# ------------------------------------------------- device trace -> Perfetto


def load_device_trace_events(artifacts: Dict[str, bytes], *,
                             started_at: float, lane_prefix: str = "device",
                             max_events: Optional[int] = None,
                             ) -> List[Dict[str, Any]]:
    """Parse a capture's chrome-trace artifact (`*.trace.json.gz`) into
    trace events aligned to wall-clock time, ready to merge into the
    span export: the profiler's timestamps are microseconds relative to
    trace start, so each event is offset by the capture's `started_at`.
    Lanes become "<lane_prefix>:<process name>" (e.g. `device:/device:
    TPU:0`), so runtime spans and chip activity sit side by side in one
    Perfetto view. Events are capped (largest durations win) to keep the
    export loadable."""
    from ..core.config import cfg

    if max_events is None:
        max_events = cfg.profile_merge_max_events
    raw = None
    for name in sorted(artifacts):
        if name.endswith(".trace.json.gz"):
            raw = artifacts[name]
            break
    if raw is None:
        return []
    try:
        data = json.loads(gzip.decompress(raw))
    except Exception as exc:  # noqa: BLE001 - corrupt artifact boundary
        raise ProfilingError(f"undecodable device trace artifact: {exc!r}")
    events = data.get("traceEvents", []) if isinstance(data, dict) else []
    proc_names: Dict[Any, str] = {}
    thread_names: Dict[Tuple[Any, Any], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e.get("pid")] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            thread_names[(e.get("pid"), e.get("tid"))] = (
                e.get("args", {}).get("name", "")
            )
    xs = [e for e in events if e.get("ph") == "X"]
    # device tracks are the point; host-python tracks only ride along
    # when there is budget left after them
    xs.sort(key=lambda e: (
        0 if "/device:" in proc_names.get(e.get("pid"), "") else 1,
        -float(e.get("dur", 0.0)),
    ))
    xs = xs[:max_events]
    offset_us = started_at * 1e6
    out: List[Dict[str, Any]] = []
    for e in xs:
        pid = e.get("pid")
        proc = proc_names.get(pid) or str(pid)
        out.append({
            "name": e.get("name", "?"),
            "cat": "device",
            "ph": "X",
            "ts": offset_us + float(e.get("ts", 0.0)),
            "dur": float(e.get("dur", 0.0)),
            "pid": f"{lane_prefix}:{proc}",
            "tid": thread_names.get((pid, e.get("tid")), str(e.get("tid"))),
            "args": e.get("args", {}),
        })
    out.sort(key=lambda e: e["ts"])
    return out


# ------------------------------------------------------------ profile store


class ProfileStore:
    """Driver-side registry of captures: bounded LRU of records (meta +
    per-node artifact bytes). The state API (`list_profiles`,
    `get_profile`, `profile_artifact`), the CLI, and the dashboard
    download route all read from here; capture metas are additionally
    mirrored into the GCS `_profiles` table for cluster visibility."""

    def __init__(self, capacity: Optional[int] = None):
        from ..core.config import cfg

        self._capacity = capacity or cfg.profile_store_capacity
        self._records: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._artifacts: Dict[str, Dict[Tuple[str, str], bytes]] = {}
        self._lock = threading.Lock()

    def add(self, record: Dict[str, Any],
            artifacts: Dict[Tuple[str, str], bytes]) -> None:
        with self._lock:
            pid = record["profile_id"]
            self._records[pid] = record
            self._artifacts[pid] = dict(artifacts)
            while len(self._records) > self._capacity:
                old, _ = self._records.popitem(last=False)
                self._artifacts.pop(old, None)

    def list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(r) for r in self._records.values()]

    def get(self, profile_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._records.get(profile_id)
            return dict(rec) if rec is not None else None

    def artifact(self, profile_id: str, node_hex: str,
                 name: str) -> Optional[bytes]:
        with self._lock:
            return self._artifacts.get(profile_id, {}).get((node_hex, name))

    def artifacts_for(self, profile_id: str,
                      node_hex: Optional[str] = None) -> Dict[str, bytes]:
        """All of one capture's artifacts (optionally one node's), keyed
        `node_hex/name` — what the Perfetto merge and `--output` read."""
        with self._lock:
            blobs = self._artifacts.get(profile_id, {})
            return {
                f"{nh}/{name}": data
                for (nh, name), data in blobs.items()
                if node_hex is None or nh == node_hex
            }


# ----------------------------------------------------- cost model / roofline

# Peak dense bf16 FLOPs/s and HBM bandwidth per chip generation. This is
# the ONE table every MFU/roofline number in the repo prices against
# (bench.py used to carry its own copy).
_PEAK_FLOPS: Dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,   # v6e
    "TPU v6e": 918e12,
}
_PEAK_HBM_BPS: Dict[str, float] = {
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}
# Unknown chips (and the CPU test backend) get nominal peaks so the
# fractions stay defined; `estimated` flags them as not a hardware claim.
_FALLBACK_PEAK_FLOPS = 1e12
_FALLBACK_HBM_BPS = 100e9


def device_peaks(device: Any = None) -> Dict[str, Any]:
    """Peak FLOPs/s and HBM bandwidth of the attached (or given) device.
    `estimated=True` marks the fallback used for unknown kinds/CPU."""
    kind = "unknown"
    if device is not None:
        kind = getattr(device, "device_kind", "unknown")
    else:
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                kind = getattr(jax.devices()[0], "device_kind", "unknown")
            except Exception:  # noqa: BLE001 - no backend: fall back
                kind = "unknown"
    known = kind in _PEAK_FLOPS
    return {
        "device_kind": kind,
        "peak_flops": _PEAK_FLOPS.get(kind, _FALLBACK_PEAK_FLOPS),
        "peak_hbm_bps": _PEAK_HBM_BPS.get(kind, _FALLBACK_HBM_BPS),
        "estimated": not known,
    }


@dataclasses.dataclass
class StepCost:
    """cost_analysis() of one compiled program, normalized. XLA reports
    PER-DEVICE numbers for a sharded program (verified against an 8-way
    sharded matmul: per-device flops = total/8), so `flops`/`bytes
    _accessed` here are per device per invocation and MFU divides by the
    per-device peak — `total_flops` is the whole-program count."""

    flops: float
    bytes_accessed: float
    buckets: Dict[str, float]   # the raw analysis entries (numeric only)
    device_kind: str
    n_devices: int
    peak_flops: float           # per device
    peak_hbm_bps: float         # per device
    estimated_peaks: bool

    @property
    def total_flops(self) -> float:
        return self.flops * self.n_devices

    @property
    def total_bytes(self) -> float:
        return self.bytes_accessed * self.n_devices

    def top_buckets(self, k: int = 5) -> List[Tuple[str, float]]:
        ranked = sorted(self.buckets.items(), key=lambda kv: -abs(kv[1]))
        return ranked[:k]


def compiled_cost(compiled: Any) -> Tuple[float, float, Dict[str, float]]:
    """Normalize `compiled.cost_analysis()` (a dict on new jax, a
    one-element list of dicts on the pinned 0.4.x) into
    (flops, bytes_accessed, raw_numeric_buckets)."""
    try:
        analysis = compiled.cost_analysis()
    except Exception as exc:  # noqa: BLE001 - typed boundary
        raise ProfilingError(f"cost_analysis failed: {exc!r}") from exc
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        raise ProfilingError(
            f"cost_analysis returned {type(analysis).__name__}, not a dict"
        )
    buckets = {
        k: float(v) for k, v in analysis.items()
        if isinstance(v, (int, float))
    }
    return (
        float(analysis.get("flops", 0.0)),
        float(analysis.get("bytes accessed", 0.0)),
        buckets,
    )


def step_cost(fn: Any, *args: Any, **kwargs: Any) -> StepCost:
    """FLOPs/bytes of one invocation of a jitted function at the given
    example arguments, priced against the attached chip. `fn` may be a
    jitted callable (lowered+compiled here via the AOT path — one extra
    XLA compile, so callers cache the result) or an already-compiled
    object exposing `cost_analysis()`."""
    jax = sys.modules.get("jax")
    if hasattr(fn, "cost_analysis"):
        compiled = fn
    elif hasattr(fn, "lower"):
        try:
            compiled = fn.lower(*args, **kwargs).compile()
        except Exception as exc:  # noqa: BLE001 - typed boundary
            raise ProfilingError(f"lower/compile failed: {exc!r}") from exc
    else:
        raise ProfilingError(
            f"step_cost needs a jitted or compiled callable, got "
            f"{type(fn).__name__}"
        )
    flops, nbytes, buckets = compiled_cost(compiled)
    if flops <= 0 and nbytes <= 0:
        raise ProfilingError(
            "cost_analysis reported no flops/bytes for this program"
        )
    # devices the program actually spans (pjit over a mesh): read the
    # first input sharding's device set, falling back to single-device
    device = None
    n_devices = 1
    if jax is not None:
        try:
            leaves = jax.tree_util.tree_leaves(compiled.input_shardings)
            device_set = getattr(leaves[0], "device_set", None) if leaves else None
            if device_set:
                n_devices = len(device_set)
                device = next(iter(device_set))
            else:
                device = jax.devices()[0]
        except Exception:  # noqa: BLE001 - peaks fall back below
            device = None
            n_devices = 1
    peaks = device_peaks(device)
    return StepCost(
        flops=flops,
        bytes_accessed=nbytes,
        buckets=buckets,
        device_kind=peaks["device_kind"],
        n_devices=n_devices,
        peak_flops=peaks["peak_flops"],
        peak_hbm_bps=peaks["peak_hbm_bps"],
        estimated_peaks=peaks["estimated"],
    )


def roofline(cost: StepCost, step_time_s: float) -> Dict[str, Any]:
    """Price one step against the chip roofline. `mfu` is the model-
    FLOPs-utilization (achieved / peak matmul throughput), `hbm_fraction`
    the share of peak HBM bandwidth the program's byte traffic implies;
    whichever fraction is higher names the binding resource. Per-device
    cost over per-device peak: the step time is wall time, every device
    runs its shard concurrently."""
    if step_time_s <= 0:
        raise ProfilingError(f"step_time_s must be positive, got {step_time_s}")
    mfu = cost.flops / (step_time_s * cost.peak_flops)
    hbm = cost.bytes_accessed / (step_time_s * cost.peak_hbm_bps)
    return {
        "mfu": mfu,
        "hbm_fraction": hbm,
        "bound": "memory" if hbm > mfu else "compute",
        "flops_per_device": cost.flops,
        "total_flops": cost.total_flops,
        "bytes_per_device": cost.bytes_accessed,
        "step_time_s": step_time_s,
        "n_devices": cost.n_devices,
        "device_kind": cost.device_kind,
        "estimated_peaks": cost.estimated_peaks,
    }
