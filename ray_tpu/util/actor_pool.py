"""ActorPool: load-balance a stream of work over a fixed set of actors.

Reference parity: ray.util.ActorPool (/root/reference/python/ray/util/
actor_pool.py) — submit/map/map_unordered/get_next over pre-created
actors, reusing each as soon as it frees up.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List

from .. import api


class ActorPool:
    def __init__(self, actors: List[Any]):
        if not actors:
            raise ValueError("ActorPool needs at least one actor")
        self._idle = list(actors)
        self._future_to_actor = {}
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0

    def submit(self, fn: Callable[[Any, Any], Any], value: Any) -> None:
        """fn(actor, value) -> ObjectRef; blocks if no actor is idle."""
        if not self._idle:
            self._wait_for_one()
        actor = self._idle.pop()
        ref = fn(actor, value)
        self._future_to_actor[ref] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def has_next(self) -> bool:
        return self._next_return_index < self._next_task_index

    def get_next(self, timeout: float = None) -> Any:
        """Next result IN SUBMISSION ORDER."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        value = api.get(ref, timeout=timeout)
        _, actor = self._future_to_actor.pop(ref)
        if actor is not None:  # None = already freed by a blocking submit
            self._idle.append(actor)
        return value

    def get_next_unordered(self, timeout: float = None) -> Any:
        """Whichever pending result finishes first."""
        if not self.has_next():
            raise StopIteration("no pending results")
        ready, _ = api.wait(
            list(self._future_to_actor), num_returns=1, timeout=timeout
        )
        if not ready:
            from ..core.exceptions import GetTimeoutError

            raise GetTimeoutError(f"no result within {timeout}s")
        ref = ready[0]
        index, actor = self._future_to_actor.pop(ref)
        self._index_to_future.pop(index, None)
        # keep ordered bookkeeping consistent: skip this index when the
        # ordered cursor reaches it
        if index == self._next_return_index:
            self._next_return_index += 1
        if actor is not None:
            self._idle.append(actor)
        return api.get(ref, timeout=timeout)

    def map(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]) -> Iterator[Any]:
        """Ordered streaming map (backpressured by pool size)."""
        for value in values:
            self.submit(fn, value)
            # drain eagerly once saturated so results stream out
            while not self._idle and self.has_next():
                yield self.get_next()
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, Any], Any], values: Iterable[Any]) -> Iterator[Any]:
        for value in values:
            if not self._idle:
                yield self.get_next_unordered()
            self.submit(fn, value)
        while self.has_next():
            yield self.get_next_unordered()

    def _wait_for_one(self) -> None:
        """Free ONE actor whose task completed, without consuming its
        result (it stays retrievable through get_next by index)."""
        candidates = [
            ref for ref, (_, actor) in self._future_to_actor.items()
            if actor is not None
        ]
        ready, _ = api.wait(candidates, num_returns=1)
        ref = ready[0]
        index, actor = self._future_to_actor[ref]
        self._future_to_actor[ref] = (index, None)
        self._idle.append(actor)

    @property
    def num_idle(self) -> int:
        return len(self._idle)
