"""Watchdogs over the telemetry plane: training stalls, stragglers,
serve SLO burn.

Two detectors, both fed by signals earlier PRs already emit:

- ``StallWatchdog``: the TrainController streams per-worker step
  reports (rank, wall timestamp) into it. A gang with NO report inside
  ``train_stall_window_s``, or a worker whose report gap regresses past
  ``train_stall_factor`` x its EWMA step time, flips the
  ``raytpu_train_stalled`` gauge to 1 and emits a WARNING event naming
  the straggler rank (MegaScale-style per-step straggler detection —
  silent slowdowns surface before they become outages). Recovery flips
  the gauge back and emits an INFO event.
- ``ServeSLOMonitor``: periodically evaluates the PR-2 latency
  histograms (raytpu_serve_ttft_seconds, raytpu_serve_queue_seconds)
  over the window since its last check; a window whose p99 exceeds the
  configured SLO increments ``raytpu_serve_slo_burn_total{slo=...}``
  and emits a WARNING event.

Both are pure consumers of the metrics/events plane: no RPC, no
threads of their own unless started.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .events import emit
from .metrics import get_or_create_counter, get_or_create_gauge, registry


def _stalled_gauge():
    return get_or_create_gauge(
        "raytpu_train_stalled",
        "1 while the training stall watchdog considers the run stalled "
        "(no progress in the window, or an EWMA step-time regression).",
        tag_keys=("run",),
    )


class StallWatchdog:
    """Training stall + straggler detection from gang step timestamps.

    Feed it with ``observe_report(rank, ts)`` for every worker report
    the controller drains, and call ``check()`` each poll cycle. All
    thresholds come from config (``train_stall_*`` flags) unless
    overridden."""

    def __init__(self, run_name: str, num_workers: int, *,
                 window_s: Optional[float] = None,
                 factor: Optional[float] = None,
                 alpha: Optional[float] = None,
                 min_s: Optional[float] = None):
        from ..core.config import cfg

        self.run_name = run_name
        self.num_workers = num_workers
        self.window_s = cfg.train_stall_window_s if window_s is None else window_s
        self.factor = cfg.train_stall_factor if factor is None else factor
        self.alpha = cfg.train_stall_ewma_alpha if alpha is None else alpha
        self.min_s = cfg.train_stall_min_s if min_s is None else min_s
        now = time.time()
        self._started = now
        self._lock = threading.Lock()
        self._last_ts: Dict[int, float] = {}   # rank -> last report wall ts
        # monotonic plumbing (the wall-skew fix): _mono is the WORKER's
        # perf_counter carried in its report (valid for per-rank
        # intervals; never comparable across hosts), _rx the controller-
        # local perf_counter at receipt (the one shared monotonic basis
        # every rank's lag can be measured on)
        self._mono: Dict[int, float] = {}
        self._rx: Dict[int, float] = {}
        self._ewma: Dict[int, float] = {}      # rank -> EWMA step interval
        self._reports: Dict[int, int] = {}
        self._done: set = set()  # finished ranks are not stragglers
        # rank -> latest sampled-step phase buckets (train/steplog):
        # lets a stall warning name WHERE the straggler's time goes
        self._buckets: Dict[int, Dict[str, float]] = {}
        self.stalled = False
        self.stall_reason = ""
        self.straggler: Optional[int] = None
        self.straggler_bucket: Optional[str] = None
        _stalled_gauge().set(0, tags={"run": run_name})

    # ------------------------------------------------------------- feeding

    def observe_report(self, rank: int, ts: Optional[float] = None,
                       mono: Optional[float] = None) -> None:
        """One drained worker report. `mono` is the WORKER's monotonic
        clock at report time (reserved metrics key `_mono`): when
        carried, step intervals and straggler lags run on monotonic
        clocks, so cross-host wall-clock skew cannot misrank stragglers.
        Without it (legacy feeds, unit drives) the wall path applies."""
        ts = time.time() if ts is None else float(ts)
        rx = time.perf_counter()
        with self._lock:
            prev = self._last_ts.get(rank)
            prev_mono = self._mono.get(rank)
            if mono is not None:
                mono = float(mono)
                # per-rank interval on the rank's OWN monotonic clock
                # (a worker restart resets it; negative deltas skipped)
                if prev_mono is not None and mono > prev_mono:
                    interval = mono - prev_mono
                    ewma = self._ewma.get(rank)
                    self._ewma[rank] = (
                        interval if ewma is None
                        else self.alpha * interval + (1 - self.alpha) * ewma
                    )
                self._mono[rank] = max(mono, prev_mono or mono)
                self._rx[rank] = rx
            elif prev is not None and ts > prev:
                interval = ts - prev
                ewma = self._ewma.get(rank)
                self._ewma[rank] = (
                    interval if ewma is None
                    else self.alpha * interval + (1 - self.alpha) * ewma
                )
            self._last_ts[rank] = max(ts, prev or 0.0)
            self._reports[rank] = self._reports.get(rank, 0) + 1

    def observe_step_buckets(self, rank: int,
                             buckets: Optional[Dict[str, Any]]) -> None:
        """Latest sampled-step phase decomposition of one rank (the
        `_steplog` records the controller drains): kept so the stall
        warning names the straggler's dominant bucket, not just the
        rank."""
        if not isinstance(buckets, dict):
            return
        clean = {
            str(phase): dur for phase, dur in buckets.items()
            if isinstance(dur, (int, float))
        }
        if clean:
            with self._lock:
                self._buckets[rank] = clean

    def dominant_bucket(self, rank: int
                        ) -> Optional[Tuple[str, float]]:
        """(phase, excess_s) that best explains this rank's step time
        vs its peers: the bucket where its latest sampled step exceeds
        the fastest other rank's the most. With no peer samples it
        degenerates to the rank's largest bucket. None before any
        sampled step arrived."""
        with self._lock:
            mine = self._buckets.get(rank)
            others = [
                dict(b) for r, b in self._buckets.items() if r != rank
            ]
        if not mine:
            return None
        best: Optional[str] = None
        best_excess = -math.inf
        for phase, dur in mine.items():
            floor = min((o.get(phase, 0.0) for o in others), default=0.0)
            excess = dur - floor
            if excess > best_excess:
                best, best_excess = phase, excess
        if best is None:
            return None
        return best, max(best_excess, 0.0)

    def mark_done(self, rank: int) -> None:
        """A worker finished its loop cleanly: silence from it is
        completion, not a stall."""
        with self._lock:
            self._done.add(rank)

    # ----------------------------------------------------------- evaluation

    def straggler_ranking(self, now: Optional[float] = None
                          ) -> List[Tuple[int, float]]:
        """Ranks ordered most-behind first: (rank, seconds since its
        last report). A rank whose reports carry the monotonic clock is
        measured on the controller's RECEIPT perf_counter — the one
        monotonic basis every rank shares — so a gang host with a
        skewed wall clock can no longer be misranked as (or hide as)
        the straggler. Ranks without monotonic feeds (legacy planes,
        unit drives) fall back to wall timestamps; workers that never
        reported rank by time since watchdog start."""
        now = time.time() if now is None else now
        rx_now = time.perf_counter()
        with self._lock:
            lags = []
            for rank in range(self.num_workers):
                if rank in self._done:
                    continue
                rx = self._rx.get(rank)
                if rx is not None:
                    lags.append((rank, rx_now - rx))
                else:
                    lags.append(
                        (rank, now - self._last_ts.get(rank, self._started))
                    )
        return sorted(lags, key=lambda rl: -rl[1])

    def check(self, now: Optional[float] = None) -> bool:
        """Evaluate the stall conditions; flip gauge + events on state
        transitions. Returns the current stalled verdict."""
        if self.window_s <= 0:
            return False
        now = time.time() if now is None else now
        ranking = self.straggler_ranking(now)
        if not ranking:  # every rank finished: nothing left to stall
            self._transition(False, None, "")
            return False
        straggler = ranking[0][0]
        reason = ""
        # (1) no progress anywhere (among unfinished ranks) in the
        # window: the SMALLEST per-rank lag (each measured on that
        # rank's correct clock basis) is how long the gang's freshest
        # rank has been silent
        with self._lock:
            reported = {
                r for r, n in self._reports.items()
                if n and r not in self._done
            }
        gang_gap = min(
            (lag for rank, lag in ranking if rank in reported),
            default=now - self._started,
        )
        if gang_gap > self.window_s:
            reason = (
                f"no worker reported for {gang_gap:.1f}s "
                f"(window {self.window_s:.1f}s); slowest is rank {straggler}"
            )
        else:
            # (2) EWMA regression of one worker against its own history
            with self._lock:
                ewmas = dict(self._ewma)
            for rank, lag in ranking:
                ewma = ewmas.get(rank)
                if ewma is None:
                    continue
                threshold = max(self.min_s, self.factor * ewma)
                if lag > threshold:
                    straggler = rank
                    reason = (
                        f"rank {rank} step gap {lag:.2f}s exceeds "
                        f"{self.factor:.1f}x its EWMA step time "
                        f"({ewma:.3f}s)"
                    )
                    break
        self._transition(bool(reason), straggler, reason)
        return self.stalled

    def _transition(self, stalled: bool, straggler: Optional[int],
                    reason: str) -> None:
        dom = (
            self.dominant_bucket(straggler)
            if stalled and straggler is not None else None
        )
        if stalled == self.stalled:
            self.straggler = straggler if stalled else None
            self.straggler_bucket = dom[0] if dom else None
            self.stall_reason = reason
            return
        self.stalled = stalled
        self.straggler = straggler if stalled else None
        self.straggler_bucket = dom[0] if dom else None
        self.stall_reason = reason
        _stalled_gauge().set(1.0 if stalled else 0.0,
                             tags={"run": self.run_name})
        if stalled:
            where = (
                f", dominant bucket {dom[0]} (+{dom[1]:.3f}s vs fastest "
                f"peer)" if dom else ""
            )
            emit("WARNING", "watchdog",
                 f"run {self.run_name} STALLED: {reason} "
                 f"(straggler rank {straggler}{where})",
                 kind="watchdog.stall",
                 run=self.run_name, straggler_rank=straggler,
                 dominant_bucket=dom[0] if dom else None)
        else:
            emit("INFO", "watchdog",
                 f"run {self.run_name} recovered from stall",
                 kind="watchdog.recovered", run=self.run_name)

    def close(self) -> None:
        """Run over: clear the stalled gauge so a finished run never
        reads as permanently stalled."""
        self._transition(False, None, "")
        _stalled_gauge().set(0, tags={"run": self.run_name})


# --------------------------------------------------------------- serve SLO


def _histogram_quantile(buckets: List[Tuple[float, int]], total: int,
                        q: float) -> float:
    """Estimate a quantile from cumulative-ized histogram bucket deltas
    (Prometheus-style linear interpolation within the landing bucket;
    +Inf landings return inf — above every finite boundary)."""
    if total <= 0:
        return 0.0
    target = q * total
    cumulative = 0
    prev_bound = 0.0
    for bound, count in buckets:
        if count:
            if cumulative + count >= target:
                frac = (target - cumulative) / count
                return prev_bound + frac * (bound - prev_bound)
            cumulative += count
        prev_bound = bound
    return math.inf  # landed in the +Inf overflow bucket


def _dominant_ttft_bucket(breakdowns: List[Dict[str, float]]):
    """(bucket, share) of the largest TTFT component across a window of
    per-request decompositions, or None with no samples. Buckets are the
    engine's exact-sum split: queue_wait + preempt_wait + prefill_compute
    == TTFT, so the shares answer WHERE the window's latency went."""
    totals = {"queue_wait": 0.0, "preempt_wait": 0.0,
              "prefill_compute": 0.0}
    for b in breakdowns:
        for key in totals:
            totals[key] += float(b.get(key + "_s", 0.0) or 0.0)
    spent = sum(totals.values())
    if spent <= 0:
        return None
    dominant = max(totals, key=totals.get)
    return dominant, totals[dominant] / spent


class ServeSLOMonitor:
    """p99 burn detection over the span-derived serve histograms.

    Each ``check()`` diffs the histograms against the previous check
    (so the p99 is of the WINDOW, not all time) and burns the SLO
    counter when the window's p99 exceeds the configured objective."""

    def __init__(self):
        self._lock = threading.Lock()
        # histogram name -> previous cumulative (bucket counts, total)
        self._prev: Dict[str, Tuple[List[int], int]] = {}
        # slo -> {"windows", "violated", "requests", "last_p99_s"} — the
        # attainment ledger the serve goodput report reads
        self._attainment: Dict[str, Dict[str, Any]] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _slos(self) -> List[Tuple[str, str, float]]:
        from ..core.config import cfg

        return [
            ("ttft_p99", "raytpu_serve_ttft_seconds",
             float(cfg.serve_slo_ttft_p99_s)),
            ("queue_p99", "raytpu_serve_queue_seconds",
             float(cfg.serve_slo_queue_p99_s)),
        ]

    def _window_delta(self, name: str, hist) -> Tuple[List[Tuple[float, int]], int]:
        """Aggregate the histogram across its tag series and diff
        against the last check's cumulative counts."""
        bounds = list(hist.boundaries)
        counts = [0] * (len(bounds) + 1)
        total = 0
        for _tags, data in hist.collect():
            for i, (_b, c) in enumerate(data["buckets"]):
                counts[i] += c
            total += data["count"]
        # overflow bucket = total - finite-bucket sum
        counts[len(bounds)] = total - sum(counts[: len(bounds)])
        with self._lock:
            prev_counts, prev_total = self._prev.get(
                name, ([0] * len(counts), 0)
            )
            self._prev[name] = (list(counts), total)
        delta = [c - p for c, p in zip(counts, prev_counts)]
        finite = list(zip(bounds, delta[: len(bounds)]))
        # the +Inf overflow rides as a trailing (inf, n) entry
        finite.append((math.inf, max(0, delta[len(bounds)])))
        return finite, max(0, total - prev_total)

    def check(self) -> Dict[str, float]:
        """One evaluation round. Returns {slo: window_p99} for every SLO
        that had samples this window (enabled or not — callers/tests can
        inspect); burns counters/events only for enabled, violated SLOs."""
        out: Dict[str, float] = {}
        for slo, hist_name, objective in self._slos():
            hist = registry().get(hist_name)
            if hist is None or getattr(hist, "kind", "") != "histogram":
                continue
            buckets, n = self._window_delta(hist_name, hist)
            if n <= 0:
                continue
            p99 = _histogram_quantile(buckets, n, 0.99)
            out[slo] = p99
            violated = objective > 0 and p99 > objective
            with self._lock:
                led = self._attainment.setdefault(slo, {
                    "windows": 0, "violated": 0, "requests": 0,
                    "objective_s": objective, "last_p99_s": 0.0,
                })
                led["windows"] += 1
                led["requests"] += n
                led["violated"] += 1 if violated else 0
                led["objective_s"] = objective
                led["last_p99_s"] = p99
                attained = 1.0 - led["violated"] / led["windows"]
            get_or_create_gauge(
                "raytpu_serve_slo_attainment",
                "Fraction of evaluation windows whose p99 met the "
                "configured SLO objective (the serve-side goodput).",
                tag_keys=("slo",),
            ).set(attained, tags={"slo": slo})
            if violated:
                get_or_create_counter(
                    "raytpu_serve_slo_burn_total",
                    "SLO-violating windows observed by the serve SLO "
                    "monitor (p99 over objective).",
                    tag_keys=("slo",),
                ).inc(tags={"slo": slo})
                emit("WARNING", "watchdog",
                     f"serve SLO burn: {slo} = "
                     f"{'inf' if math.isinf(p99) else f'{p99:.3f}s'} over "
                     f"objective {objective:.3f}s "
                     f"({n} request(s) this window)",
                     kind="watchdog.slo_burn",
                     slo=slo, objective=objective, samples=n)
        out.update(self._check_tenants())
        return out

    def _check_tenants(self) -> Dict[str, float]:
        """Per-tenant TTFT attainment pass: drains the tenancy TTFT
        window (raw samples reported by the engines, attributed at
        first-token time) and evaluates each tenant against its own
        objective (TenantSpec.ttft_slo_s, falling back to the global
        serve_slo_ttft_p99_s). Ledger entries ride the same
        ``_attainment`` map — keyed ``ttft_p99:<tenant>`` — so the
        controller's burn-delta scan (and hence the SLO autoscaler)
        sees tenant-attributed burn with no extra plumbing."""
        try:
            from ..serve import tenancy
        except Exception:  # serve plane not imported in this process
            return {}
        samples = tenancy.drain_ttft_window()
        breakdowns = tenancy.drain_ttft_breakdown()
        queue_waits = tenancy.drain_queue_wait_window()
        out: Dict[str, float] = {}
        for tenant, ttfts in samples.items():
            if not ttfts:
                continue
            objective = tenancy.ttft_objective(tenant)
            ordered = sorted(ttfts)
            p99 = ordered[min(len(ordered) - 1,
                              int(0.99 * len(ordered)))]
            slo = f"ttft_p99:{tenant}"
            out[slo] = p99
            violated = objective > 0 and p99 > objective
            with self._lock:
                led = self._attainment.setdefault(slo, {
                    "windows": 0, "violated": 0, "requests": 0,
                    "objective_s": objective, "last_p99_s": 0.0,
                })
                led["windows"] += 1
                led["requests"] += len(ttfts)
                led["violated"] += 1 if violated else 0
                led["objective_s"] = objective
                led["last_p99_s"] = p99
                attained = 1.0 - led["violated"] / led["windows"]
            get_or_create_gauge(
                "raytpu_serve_tenant_slo_attainment",
                "Fraction of evaluation windows whose per-tenant TTFT "
                "p99 met the tenant's objective.",
                tag_keys=("tenant",),
            ).set(attained, tags={"tenant": tenant})
            get_or_create_gauge(
                "raytpu_serve_tenant_ttft_p99_seconds",
                "Window TTFT p99 per tenant, as observed by the serve "
                "SLO monitor.",
                tag_keys=("tenant",),
            ).set(p99, tags={"tenant": tenant})
            if violated:
                get_or_create_counter(
                    "raytpu_serve_slo_burn_total",
                    "SLO-violating windows observed by the serve SLO "
                    "monitor (p99 over objective).",
                    tag_keys=("slo",),
                ).inc(tags={"slo": slo})
                # the forensics decomposition turns "tenant X burned"
                # into "…and it burned in the QUEUE, not the engine"
                dom = _dominant_ttft_bucket(breakdowns.get(tenant, []))
                dom_txt = (
                    f"; dominant bucket: {dom[0]} ({dom[1]:.0%} of TTFT)"
                    if dom else ""
                )
                extra = {"dominant_bucket": dom[0]} if dom else {}
                emit("WARNING", "watchdog",
                     f"serve SLO burn: tenant {tenant!r} ttft p99 = "
                     f"{p99:.3f}s over objective {objective:.3f}s "
                     f"({len(ttfts)} request(s) this window){dom_txt}",
                     kind="watchdog.slo_burn",
                     slo=slo, objective=objective, samples=len(ttfts),
                     **extra)
        out.update(self._check_tenant_queue_waits(queue_waits))
        return out

    def _check_tenant_queue_waits(
        self, queue_waits: Dict[str, List[float]]
    ) -> Dict[str, float]:
        """Per-tenant queue-wait p99 ledger (``queue_wait_p99:<tenant>``
        in attainment_report): the queue-wait share of each request's
        TTFT as decomposed by the engine, evaluated against the global
        queue objective. Burn here with TTFT attained means admission
        latency is being earned back by prefill headroom — a capacity
        signal, not a latency incident, so no burn counter/event."""
        from ..core.config import cfg

        objective = float(cfg.serve_slo_queue_p99_s)
        out: Dict[str, float] = {}
        for tenant, waits in queue_waits.items():
            if not waits:
                continue
            ordered = sorted(waits)
            p99 = ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))]
            slo = f"queue_wait_p99:{tenant}"
            out[slo] = p99
            violated = objective > 0 and p99 > objective
            with self._lock:
                led = self._attainment.setdefault(slo, {
                    "windows": 0, "violated": 0, "requests": 0,
                    "objective_s": objective, "last_p99_s": 0.0,
                })
                led["windows"] += 1
                led["requests"] += len(waits)
                led["violated"] += 1 if violated else 0
                led["objective_s"] = objective
                led["last_p99_s"] = p99
            get_or_create_gauge(
                "raytpu_serve_tenant_queue_wait_p99_seconds",
                "Window queue-wait p99 per tenant (the queue_wait bucket "
                "of the engine's TTFT decomposition).",
                tag_keys=("tenant",),
            ).set(p99, tags={"tenant": tenant})
        return out

    def attainment_report(self) -> Dict[str, Dict[str, Any]]:
        """Per-SLO window attainment ledger (the serve analogue of the
        train goodput report): windows evaluated, windows violated,
        requests covered, attainment fraction."""
        with self._lock:
            out = {}
            for slo, led in self._attainment.items():
                windows = led["windows"]
                out[slo] = {
                    **led,
                    "attainment": (
                        1.0 - led["violated"] / windows if windows else 1.0
                    ),
                }
            return out

    # -------------------------------------------------------- background run

    def start(self, period_s: Optional[float] = None) -> None:
        """Start the periodic evaluator (idempotent)."""
        from ..core.config import cfg

        if self._thread is not None:
            return
        period = cfg.serve_slo_check_period_s if period_s is None else period_s
        if period <= 0:
            return

        def loop():
            from ..core.runtime import head_outage_s

            while not self._stop.wait(period):
                if head_outage_s() > 0.0:
                    # head outage stalls sample federation: a window's
                    # p99 computed now would burn SLOs (and drive the
                    # autoscaler) on missing data, not real latency
                    continue
                try:
                    self.check()
                except Exception:  # noqa: BLE001 - the monitor must not die
                    pass

        self._thread = threading.Thread(
            target=loop, daemon=True, name="serve-slo-monitor"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._thread = None


_slo_monitor: Optional[ServeSLOMonitor] = None
_slo_lock = threading.Lock()


def serve_slo_monitor() -> ServeSLOMonitor:
    global _slo_monitor
    with _slo_lock:
        if _slo_monitor is None:
            _slo_monitor = ServeSLOMonitor()
        return _slo_monitor


def ensure_serve_slo_monitor() -> Optional[ServeSLOMonitor]:
    """Start the singleton monitor when any serve SLO is configured
    (called from the serve router on first deployment; a no-op without
    configured objectives keeps idle deployments thread-free)."""
    from ..core.config import cfg

    tenant_slo = False
    try:
        from ..serve import tenancy

        tenant_slo = tenancy.any_tenant_slo()
    except Exception:
        pass
    if (cfg.serve_slo_ttft_p99_s <= 0 and cfg.serve_slo_queue_p99_s <= 0
            and not tenant_slo):
        return None
    monitor = serve_slo_monitor()
    monitor.start()
    return monitor
