"""State API: list/summarize cluster state + chrome-trace timeline.

Reference parity: python/ray/util/state (`ray list tasks/actors/objects`)
and GlobalState.chrome_tracing_dump (_private/state.py:442) feeding
`ray timeline` — load the JSON in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..core import runtime as _rt


def _runtime():
    if not _rt.is_initialized():
        raise RuntimeError("ray_tpu is not initialized")
    return _rt.get_runtime()


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Completed task events, newest last."""
    return list(_runtime().task_events())[-limit:]


def list_actors(limit: int = 1000) -> List[Dict[str, Any]]:
    return _runtime().list_actors()[:limit]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    store = _runtime().object_store
    out = []
    with store._lock:
        entries = list(store._entries.items())[:limit]
    for oid, entry in entries:
        out.append(
            {
                "object_id": oid.hex(),
                "state": entry.state.name,
                "tier": entry.tier.value if entry.tier else None,
                "nbytes": entry.nbytes,
                "pin_count": entry.pin_count,
            }
        )
    return out


def list_nodes() -> List[Dict[str, Any]]:
    out = []
    for node in _runtime().scheduler.nodes():
        avail = node.resources.available()
        total = node.resources.total  # property
        out.append(
            {
                "node_id": node.node_id.hex(),
                "alive": node.alive,
                "is_head": node.is_head,
                # ALIVE | PREEMPTING | DEAD: PREEMPTING nodes announced
                # their death and take no new placements (dashboard shows
                # this column verbatim)
                "state": (
                    "PREEMPTING" if node.alive and node.draining
                    else ("ALIVE" if node.alive else "DEAD")
                ),
                "draining": bool(node.draining),
                "drain_reason": node.drain_reason,
                "drain_deadline": node.drain_deadline,
                "resources_total": dict(total),
                "resources_available": dict(avail),
            }
        )
    return out


def summary() -> Dict[str, Any]:
    runtime = _runtime()
    events = runtime.task_events()
    return {
        "nodes": len(list_nodes()),
        "actors": len(runtime.list_actors()),
        "tasks_finished": sum(1 for e in events if e["ok"]),
        "tasks_failed": sum(1 for e in events if not e["ok"]),
        "object_store": runtime.object_store.usage(),
        "scheduler": dict(runtime.scheduler.stats),
    }


def list_traces(limit: int = 100) -> List[Dict[str, Any]]:
    """Trace summaries of THIS process's tracer (newest last): trace_id,
    root span name, span count, wall duration. Works without a live
    runtime — the tracer is per-process."""
    from .tracing import tracer

    return tracer().list_traces(limit=limit)


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """Every span of one trace, stitched cluster-wide: local ring buffer
    plus each node agent's (node_spans RPC), sorted by start time. A
    remote task's execute/result spans live on the agent that ran it —
    this is where the cross-process trace becomes one waterfall."""
    from .tracing import tracer

    spans = {s["span_id"]: s for s in tracer().spans(trace_id)}
    if _rt.is_initialized():
        ctx = getattr(_rt.get_runtime(), "cluster", None)
        if ctx is not None:
            fanned = ctx.fanout_nodes(
                "node_spans", trace_id, 10_000, placeholder=lambda e: []
            )
            for node_spans in fanned.values():
                for s in node_spans or []:
                    spans.setdefault(s["span_id"], s)
    return sorted(spans.values(), key=lambda s: s["start_ts"])


def trace_dump(path: Optional[str] = None,
               trace_id: Optional[str] = None) -> str:
    """Perfetto/chrome-trace JSON of runtime SPANS (util/tracing) — the
    causal, nested view that supersedes and subsumes the completed-task
    `chrome_tracing_dump`: spans nest, one lane per node/actor/engine
    slot, and remote spans are stitched in cluster-wide. Exported by
    `ray_tpu timeline --trace` and the dashboard's trace endpoints."""
    from .tracing import export_chrome_trace, tracer

    if trace_id is not None:
        spans = get_trace(trace_id)
    else:
        spans = {s["span_id"]: s for s in tracer().spans()}
        if _rt.is_initialized():
            ctx = getattr(_rt.get_runtime(), "cluster", None)
            if ctx is not None:
                fanned = ctx.fanout_nodes(
                    "node_spans", None, 10_000, placeholder=lambda e: []
                )
                for node_spans in fanned.values():
                    for s in node_spans or []:
                        spans.setdefault(s["span_id"], s)
        spans = sorted(spans.values(), key=lambda s: s["start_ts"])
    return export_chrome_trace(spans, path)


def chrome_tracing_dump(path: Optional[str] = None) -> str:
    """Chrome trace-event JSON of completed tasks (one lane per node).

    Returns the JSON string; writes it to `path` when given. Open in
    chrome://tracing or https://ui.perfetto.dev. Superseded by
    `trace_dump`, which exports the full span tree (queue/dispatch/
    execute/result causality) instead of flat completed-task intervals;
    this stays for the legacy `ray_tpu timeline` shape.
    """
    events = []
    for e in list_tasks(limit=100_000):
        if not e.get("start_ts"):
            continue
        events.append(
            {
                "name": e["name"],
                "cat": "task",
                "ph": "X",
                "ts": e["start_ts"] * 1e6,
                "dur": max(0.0, (e["end_ts"] - e["start_ts"]) * 1e6),
                "pid": e.get("node", "node")[:8] or "node",
                "tid": e["task_id"][:8],
                "args": {"ok": e["ok"], "attempt": e["attempt"]},
            }
        )
    payload = json.dumps({"traceEvents": events})
    if path:
        with open(path, "w") as f:
            f.write(payload)
    return payload


def list_events(limit: int = 500, severity: Optional[str] = None,
                source: Optional[str] = None) -> List[Dict[str, Any]]:
    """Structured runtime events of THIS process (util/events.py)."""
    from .events import events

    return events().list(limit=limit, severity=severity, source=source)


def cluster_events(limit: int = 500) -> Dict[str, List[Dict[str, Any]]]:
    """Event tails for every cluster node, keyed by node id hex."""
    rt = _runtime()
    ctx = getattr(rt, "cluster", None)
    if ctx is None:
        return {"local": list_events(limit=limit)}
    out = ctx.fanout_nodes(
        "node_events", 0, limit,
        placeholder=lambda e: [
            {"severity": "ERROR", "source": "state",
             "message": f"unreachable: {e!r}"}
        ],
    )
    out[ctx.node_id.hex()] = list_events(limit=limit)
    return out


def cluster_logs(tail: int = 200) -> Dict[str, List[str]]:
    """Log tails for every cluster node, keyed by node id hex
    (reference: `ray logs` over the dashboard's per-node log routes)."""
    from . import logs

    return logs.cluster_tail(_runtime(), tail)
