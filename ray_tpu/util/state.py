"""State API: list/summarize cluster state + chrome-trace timeline.

Reference parity: python/ray/util/state (`ray list tasks/actors/objects`)
and GlobalState.chrome_tracing_dump (_private/state.py:442) feeding
`ray timeline` — load the JSON in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ..core import runtime as _rt


def _runtime():
    if not _rt.is_initialized():
        raise RuntimeError("ray_tpu is not initialized")
    return _rt.get_runtime()


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Completed task events, newest last."""
    return list(_runtime().task_events())[-limit:]


def list_actors(limit: int = 1000) -> List[Dict[str, Any]]:
    return _runtime().list_actors()[:limit]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    store = _runtime().object_store
    out = []
    with store._lock:
        entries = list(store._entries.items())[:limit]
    for oid, entry in entries:
        out.append(
            {
                "object_id": oid.hex(),
                "state": entry.state.name,
                "tier": entry.tier.value if entry.tier else None,
                "nbytes": entry.nbytes,
                "pin_count": entry.pin_count,
            }
        )
    return out


def list_nodes() -> List[Dict[str, Any]]:
    out = []
    for node in _runtime().scheduler.nodes():
        avail = node.resources.available()
        total = node.resources.total  # property
        out.append(
            {
                "node_id": node.node_id.hex(),
                "alive": node.alive,
                "is_head": node.is_head,
                "resources_total": dict(total),
                "resources_available": dict(avail),
            }
        )
    return out


def summary() -> Dict[str, Any]:
    runtime = _runtime()
    events = runtime.task_events()
    return {
        "nodes": len(list_nodes()),
        "actors": len(runtime.list_actors()),
        "tasks_finished": sum(1 for e in events if e["ok"]),
        "tasks_failed": sum(1 for e in events if not e["ok"]),
        "object_store": runtime.object_store.usage(),
        "scheduler": dict(runtime.scheduler.stats),
    }


def chrome_tracing_dump(path: Optional[str] = None) -> str:
    """Chrome trace-event JSON of completed tasks (one lane per node).

    Returns the JSON string; writes it to `path` when given. Open in
    chrome://tracing or https://ui.perfetto.dev.
    """
    events = []
    for e in list_tasks(limit=100_000):
        if not e.get("start_ts"):
            continue
        events.append(
            {
                "name": e["name"],
                "cat": "task",
                "ph": "X",
                "ts": e["start_ts"] * 1e6,
                "dur": max(0.0, (e["end_ts"] - e["start_ts"]) * 1e6),
                "pid": e.get("node", "node")[:8] or "node",
                "tid": e["task_id"][:8],
                "args": {"ok": e["ok"], "attempt": e["attempt"]},
            }
        )
    payload = json.dumps({"traceEvents": events})
    if path:
        with open(path, "w") as f:
            f.write(payload)
    return payload


def list_events(limit: int = 500, severity: Optional[str] = None,
                source: Optional[str] = None) -> List[Dict[str, Any]]:
    """Structured runtime events of THIS process (util/events.py)."""
    from .events import events

    return events().list(limit=limit, severity=severity, source=source)


def cluster_events(limit: int = 500) -> Dict[str, List[Dict[str, Any]]]:
    """Event tails for every cluster node, keyed by node id hex."""
    rt = _runtime()
    ctx = getattr(rt, "cluster", None)
    if ctx is None:
        return {"local": list_events(limit=limit)}
    out = ctx.fanout_nodes(
        "node_events", 0, limit,
        placeholder=lambda e: [
            {"severity": "ERROR", "source": "state",
             "message": f"unreachable: {e!r}"}
        ],
    )
    out[ctx.node_id.hex()] = list_events(limit=limit)
    return out


def cluster_logs(tail: int = 200) -> Dict[str, List[str]]:
    """Log tails for every cluster node, keyed by node id hex
    (reference: `ray logs` over the dashboard's per-node log routes)."""
    from . import logs

    return logs.cluster_tail(_runtime(), tail)
