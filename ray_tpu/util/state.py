"""State API: list/summarize cluster state + chrome-trace timeline.

Reference parity: python/ray/util/state (`ray list tasks/actors/objects`)
and GlobalState.chrome_tracing_dump (_private/state.py:442) feeding
`ray timeline` — load the JSON in chrome://tracing or Perfetto.
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Dict, List, Optional

from ..core import runtime as _rt


def _runtime():
    if not _rt.is_initialized():
        raise RuntimeError("ray_tpu is not initialized")
    return _rt.get_runtime()


def list_tasks(limit: int = 1000) -> List[Dict[str, Any]]:
    """Completed task events, newest last."""
    return list(_runtime().task_events())[-limit:]


def list_actors(limit: int = 1000) -> List[Dict[str, Any]]:
    return _runtime().list_actors()[:limit]


def list_objects(limit: int = 1000) -> List[Dict[str, Any]]:
    store = _runtime().object_store
    out = []
    with store._lock:
        entries = list(store._entries.items())[:limit]
    for oid, entry in entries:
        out.append(
            {
                "object_id": oid.hex(),
                "state": entry.state.name,
                "tier": entry.tier.value if entry.tier else None,
                "nbytes": entry.nbytes,
                "pin_count": entry.pin_count,
            }
        )
    return out


def list_nodes() -> List[Dict[str, Any]]:
    out = []
    for node in _runtime().scheduler.nodes():
        avail = node.resources.available()
        total = node.resources.total  # property
        out.append(
            {
                "node_id": node.node_id.hex(),
                "alive": node.alive,
                "is_head": node.is_head,
                # ALIVE | PREEMPTING | DEAD: PREEMPTING nodes announced
                # their death and take no new placements (dashboard shows
                # this column verbatim)
                "state": (
                    "PREEMPTING" if node.alive and node.draining
                    else ("ALIVE" if node.alive else "DEAD")
                ),
                "draining": bool(node.draining),
                "drain_reason": node.drain_reason,
                "drain_deadline": node.drain_deadline,
                "resources_total": dict(total),
                "resources_available": dict(avail),
            }
        )
    return out


def node_stats() -> Dict[str, Dict[str, Any]]:
    """Per-node telemetry snapshots, keyed by node id hex: this
    process's collector live, plus every cluster member's latest
    heartbeat-piggybacked snapshot from the GCS node table."""
    runtime = _runtime()
    local_hex = runtime.scheduler.head_node().node_id.hex()
    out: Dict[str, Dict[str, Any]] = {}
    collector = getattr(runtime, "node_stats", None)
    if collector is not None:
        out[local_hex] = collector.snapshot()
    ctx = getattr(runtime, "cluster", None)
    if ctx is not None:
        for info in ctx.nodes():
            stats = info.get("stats")
            if stats and info.get("node_id") not in out:
                out[info["node_id"]] = stats
    return out


def summary() -> Dict[str, Any]:
    runtime = _runtime()
    events = runtime.task_events()
    return {
        "nodes": len(list_nodes()),
        "actors": len(runtime.list_actors()),
        "tasks_finished": sum(1 for e in events if e["ok"]),
        "tasks_failed": sum(1 for e in events if not e["ok"]),
        "object_store": runtime.object_store.usage(),
        "scheduler": dict(runtime.scheduler.stats),
        "pending_tasks": len(runtime.scheduler.pending_task_demand()),
        "pending_demand": len(runtime.scheduler.pending_demand()),
        "autoscaler": autoscaler_summary(),
        "node_stats": node_stats(),
    }


def head_summary() -> Optional[Dict[str, Any]]:
    """Head fault-tolerance health: cluster epoch, WAL lag/size, last
    snapshot age, restore/reconcile provenance, plus each node's
    buffered-federation depth (how many events/reqlog marks are waiting
    to ship — grows during a head outage, drains after reconnect).
    None when nothing durability-related is on (no WAL, no cluster)."""
    runtime = _runtime()
    ctx = getattr(runtime, "cluster", None)
    out: Dict[str, Any]
    if ctx is None or getattr(ctx, "is_head", False):
        gcs = runtime.gcs
        out = {
            "epoch": gcs.current_epoch(),
            "wal": gcs.wal_stats(),
            "last_snapshot_ts": gcs.last_snapshot_ts,
            "restore": dict(gcs.last_restore),
            "reconcile": dict(getattr(runtime, "_reconcile_state", {})),
        }
        if ctx is None and out["wal"] is None and not out["epoch"]:
            return None  # single-process, no durability armed: stay quiet
    else:
        try:
            out = ctx.gcs.head_info()
        except (Exception,):  # noqa: BLE001 - degraded mode is a valid answer
            return {"unreachable_s": round(ctx.gcs.outage_s(), 2)}
    if ctx is not None:
        lag = {}
        for info in ctx.nodes():
            depth = info.get("federation_lag")
            if depth:
                lag[info["node_id"]] = depth
        if lag:
            out["federation_lag"] = lag
        out["head_outage_s"] = round(ctx.gcs.outage_s(), 2)
    return out


def autoscaler_summary() -> Optional[Dict[str, Any]]:
    """status() of the active capacity-plane autoscaler, or None when
    no autoscaler is running in this process."""
    from ..core.capacity import active_autoscaler

    scaler = active_autoscaler()
    return scaler.status() if scaler is not None else None


def cluster_metrics(raw: bool = False):
    """Federated cluster metrics. Default: ONE merged Prometheus
    exposition where every sample carries a `node_id` label (what
    /metrics/cluster serves). `raw=True`: the unmerged per-node
    expositions keyed by node id hex."""
    from .metrics import cluster_prometheus_text, registry

    if not raw:
        return cluster_prometheus_text()
    runtime = _runtime()
    ctx = getattr(runtime, "cluster", None)
    local_hex = runtime.scheduler.head_node().node_id.hex()
    parts = {local_hex: registry().prometheus_text()}
    if ctx is not None:
        for node_hex, text in ctx.fanout_nodes(
            "metrics_snapshot", placeholder=lambda e: None
        ).items():
            if text:
                parts[node_hex] = text
    return parts


def _fmt_bytes(n: float) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def status_report(verbose: bool = False) -> str:
    """Autoscaler-style debug summary (reference: the `ray status`
    output assembled from the GCS resource + autoscaler reports): nodes
    with usage/state, telemetry snapshots, pending demand, actors, PG
    states, object-store totals, and recent warnings."""
    runtime = _runtime()
    nodes = list_nodes()
    stats = node_stats()
    s = summary()
    lines: List[str] = []
    lines.append("======== ray_tpu status ========")
    lines.append(time.strftime("%Y-%m-%d %H:%M:%S"))
    by_state: Dict[str, int] = {}
    for n in nodes:
        by_state[n["state"]] = by_state.get(n["state"], 0) + 1
    lines.append("")
    lines.append(
        f"Nodes: {len(nodes)} ("
        + ", ".join(f"{v} {k}" for k, v in sorted(by_state.items()))
        + ")"
    )
    for n in nodes:
        head = " head" if n["is_head"] else ""
        drain = (
            f" draining({n['drain_reason']})" if n.get("draining") else ""
        )
        lines.append(f"  node {n['node_id'][:12]} {n['state']}{head}{drain}")
        total = n["resources_total"]
        avail = n["resources_available"]
        usage = ", ".join(
            f"{k}: {total.get(k, 0.0) - avail.get(k, 0.0):g}/{total.get(k, 0.0):g} used"
            for k in sorted(total)
        )
        lines.append(f"    resources: {usage or '(none)'}")
        snap = stats.get(n["node_id"])
        if snap:
            store = snap.get("object_store", {})
            lines.append(
                f"    object store: {_fmt_bytes(store.get('host_bytes', 0))}"
                f" in {store.get('num_objects', 0)} object(s)"
            )
            wp = snap.get("worker_pool", {})
            tq = snap.get("task_queues", {})
            lines.append(
                f"    worker pool: {wp.get('busy', 0)} busy / "
                f"{wp.get('idle', 0)} idle; queues: "
                + " ".join(f"{k}={v}" for k, v in sorted(tq.items()))
            )
            lines.append(
                f"    cpu: {snap.get('cpu_percent', 0.0):.1f}%  "
                f"rss: {_fmt_bytes(snap.get('rss_bytes', 0))}"
            )
            for dev in snap.get("tpu", ()):
                if "hbm_used_bytes" in dev:
                    lines.append(
                        f"    tpu[{dev.get('id')}] {dev.get('kind')}: HBM "
                        f"{_fmt_bytes(dev['hbm_used_bytes'])}/"
                        f"{_fmt_bytes(dev.get('hbm_limit_bytes', 0))} "
                        f"duty={dev.get('duty', 0.0):.2f}"
                    )
            prof = snap.get("profiling") or {}
            if verbose and prof:
                port = prof.get("server_port")
                parts = [
                    "profiler: "
                    + (f"server on :{port}" if port else "server not started")
                ]
                if prof.get("active_capture"):
                    parts.append(f"capturing {prof['active_capture']}")
                last = prof.get("last_capture")
                if last:
                    parts.append(
                        f"last capture {last.get('profile_id') or '(local)'} "
                        f"{last.get('duration_s', 0.0):.1f}s "
                        f"{_fmt_bytes(last.get('bytes', 0))}"
                    )
                lines.append("    " + "; ".join(parts))
    head = head_summary()
    if head:
        lines.append("")
        if "unreachable_s" in head:
            lines.append(
                f"Head: UNREACHABLE for {head['unreachable_s']:.1f}s "
                f"(degraded mode: buffering federation, cached membership)"
            )
        else:
            wal = head.get("wal") or {}
            snap_ts = head.get("last_snapshot_ts") or 0.0
            snap_age = (
                f"{time.time() - snap_ts:.1f}s ago" if snap_ts else "never"
            )
            lines.append(
                f"Head: epoch {head.get('epoch', 0)}; "
                f"wal seq={wal.get('last_seq', 0)} "
                f"size={_fmt_bytes(wal.get('size_bytes', 0))}"
                + (f" quarantined={_fmt_bytes(wal['quarantined_bytes'])}"
                   if wal.get("quarantined_bytes") else "")
                + f"; last snapshot {snap_age}"
            )
            restore = head.get("restore") or {}
            if restore:
                lines.append(
                    f"  restored: {restore.get('wal_records_applied', 0)} "
                    f"WAL record(s) replayed over snapshot "
                    f"(cutoff seq {restore.get('snapshot_wal_seq', -1)})"
                )
            rec = head.get("reconcile") or {}
            if rec:
                lines.append(
                    "  reconcile: " + ", ".join(
                        f"{k}={v}" for k, v in sorted(rec.items())
                        if k != "completed_ts"
                    )
                )
            for node_hex, depth in sorted(
                    (head.get("federation_lag") or {}).items()):
                lines.append(
                    f"  node {node_hex[:12]} buffered federation: "
                    + ", ".join(f"{k}={v}" for k, v in sorted(depth.items()))
                )
    task_demand = runtime.scheduler.pending_task_demand()
    gang_demand = runtime.scheduler.pending_gang_demand()
    lines.append("")
    if task_demand:
        lines.append(
            f"Pending tasks: {len(task_demand)} (demand: {task_demand[:8]}"
            f"{'...' if len(task_demand) > 8 else ''})"
        )
    else:
        lines.append("Pending tasks: 0")
    if gang_demand:
        lines.append(f"Pending gang demand: {len(gang_demand)} group(s)")
        for gang in gang_demand[:4]:
            lines.append(
                f"  pg {gang['pg'][:12]} [{gang['state']}] "
                f"{gang['name'] or ''}: {len(gang['bundles'])} bundle(s) "
                f"unplaced"
            )
    scaler = autoscaler_summary()
    if scaler is not None:
        lines.append(
            "Autoscaler: "
            f"{scaler['managed_nodes']} managed node(s) "
            f"({', '.join(f'{k}={v}' for k, v in sorted(scaler['per_class'].items())) or 'none'}), "
            f"{scaler['retiring']} retiring, "
            f"{scaler['pending_demands']} pending demand(s), "
            f"ups={scaler['scale_ups']} downs={scaler['scale_downs']} "
            f"replacements={scaler['replacements']} "
            f"blocked={scaler['blocked']}"
        )
    actors = runtime.list_actors()
    actor_states: Dict[str, int] = {}
    for a in actors:
        actor_states[a["state"]] = actor_states.get(a["state"], 0) + 1
    lines.append(
        f"Actors: {len(actors)}"
        + (" (" + ", ".join(f"{k}={v}" for k, v in sorted(actor_states.items())) + ")"
           if actors else "")
    )
    pgs = list(getattr(runtime.scheduler, "_placement_groups", {}).values())
    pg_states: Dict[str, int] = {}
    for pg in pgs:
        pg_states[pg.state] = pg_states.get(pg.state, 0) + 1
    lines.append(
        f"Placement groups: {len(pgs)}"
        + (" (" + ", ".join(f"{k}={v}" for k, v in sorted(pg_states.items())) + ")"
           if pgs else "")
    )
    store = s["object_store"]
    lines.append(
        f"Object store: {_fmt_bytes(store.get('host_bytes', 0))} host"
        f" / {store.get('num_objects', 0)} object(s)"
    )
    sched = s["scheduler"]
    lines.append(
        "Scheduler: " + " ".join(f"{k}={v}" for k, v in sorted(sched.items()))
    )
    warn = [
        e for e in list_events(limit=200)
        if e["severity"] in ("WARNING", "ERROR")
    ][-8:]
    lines.append("")
    lines.append(f"Recent warnings ({len(warn)}):")
    for e in warn:
        ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0)))
        lines.append(f"  {ts} {e['severity']:7s} [{e['source']}] {e['message']}")
    if not warn:
        lines.append("  (none)")
    if verbose:
        lines.append("")
        lines.append("Logs (per node):")
        for node_hex, tail in cluster_logs(tail=20).items():
            lines.append(f"  --- node {node_hex[:12]} ---")
            for line in tail:
                lines.append(f"  {line}")
    return "\n".join(lines)


def profile(nodes: Optional[List[str]] = None,
            duration_s: Optional[float] = None,
            device: bool = True, host: bool = True) -> Dict[str, Any]:
    """Run a coordinated profile capture (device trace + host sampling
    profile) over the selected nodes (hex prefixes; None = all) and
    register it; returns the capture record. The CLI command `ray_tpu
    profile` is a thin wrapper over this."""
    return _runtime().profile_capture(
        nodes=nodes, duration_s=duration_s, device=device, host=host
    )


def list_profiles() -> List[Dict[str, Any]]:
    """Registered capture records, newest last: this driver's profile
    store plus any capture other drivers registered in the GCS
    `_profiles` table (meta only — their artifacts live with them)."""
    from ..core.gcs import PROFILE_NS

    runtime = _runtime()
    records = {r["profile_id"]: r for r in runtime.profiles.list()}
    ctx = getattr(runtime, "cluster", None)
    try:
        if ctx is not None:
            for key in ctx.gcs.kv_keys(namespace=PROFILE_NS):
                rec = ctx.gcs.kv_get(key, namespace=PROFILE_NS)
                if rec:
                    records.setdefault(key, rec)
        else:
            for key in runtime.gcs.kv.keys(namespace=PROFILE_NS):
                rec = runtime.gcs.kv.get(key, namespace=PROFILE_NS)
                if rec:
                    records.setdefault(key, rec)
    except Exception:  # noqa: BLE001 - the local store still answers
        pass
    return sorted(records.values(), key=lambda r: r.get("started_at", 0.0))


def get_profile(profile_id: str) -> Dict[str, Any]:
    """One capture's record: per-node status, artifact names, sizes."""
    for rec in list_profiles():
        if rec.get("profile_id") == profile_id:
            return rec
    raise ValueError(f"no registered profile {profile_id!r}")


def profile_artifact(profile_id: str, node_hex: str, name: str) -> bytes:
    """Raw bytes of one captured artifact (this driver's store only —
    artifacts are not replicated into the GCS)."""
    data = _runtime().profiles.artifact(profile_id, node_hex, name)
    if data is None:
        raise ValueError(
            f"no artifact {name!r} for node {node_hex[:12]} in profile "
            f"{profile_id!r} (captured by another driver?)"
        )
    return data


def list_traces(limit: int = 100) -> List[Dict[str, Any]]:
    """Trace summaries of THIS process's tracer (newest last): trace_id,
    root span name, span count, wall duration. Works without a live
    runtime — the tracer is per-process."""
    from .tracing import tracer

    return tracer().list_traces(limit=limit)


def get_trace(trace_id: str) -> List[Dict[str, Any]]:
    """Every span of one trace, stitched cluster-wide: local ring buffer
    plus each node agent's (node_spans RPC), sorted by start time. A
    remote task's execute/result spans live on the agent that ran it —
    this is where the cross-process trace becomes one waterfall."""
    from .tracing import tracer

    spans = {s["span_id"]: s for s in tracer().spans(trace_id)}
    if _rt.is_initialized():
        ctx = getattr(_rt.get_runtime(), "cluster", None)
        if ctx is not None:
            fanned = ctx.fanout_nodes(
                "node_spans", trace_id, 10_000, placeholder=lambda e: []
            )
            for node_spans in fanned.values():
                for s in node_spans or []:
                    spans.setdefault(s["span_id"], s)
    return sorted(spans.values(), key=lambda s: s["start_ts"])


def trace_dump(path: Optional[str] = None,
               trace_id: Optional[str] = None,
               profile_id: Optional[str] = None) -> str:
    """Perfetto/chrome-trace JSON of runtime SPANS (util/tracing) — the
    causal, nested view that supersedes and subsumes the completed-task
    `chrome_tracing_dump`: spans nest, one lane per node/actor/engine
    slot, and remote spans are stitched in cluster-wide. Exported by
    `ray_tpu timeline --trace` and the dashboard's trace endpoints.

    `profile_id` names a registered capture (state.profile / `ray_tpu
    profile`): its device-trace events merge in as per-device tracks,
    wall-clock aligned with the runtime spans — one file shows what the
    runtime asked for and what the chip did during it."""
    from .tracing import export_chrome_trace, tracer

    if trace_id is not None:
        spans = get_trace(trace_id)
    else:
        spans = {s["span_id"]: s for s in tracer().spans()}
        if _rt.is_initialized():
            ctx = getattr(_rt.get_runtime(), "cluster", None)
            if ctx is not None:
                fanned = ctx.fanout_nodes(
                    "node_spans", None, 10_000, placeholder=lambda e: []
                )
                for node_spans in fanned.values():
                    for s in node_spans or []:
                        spans.setdefault(s["span_id"], s)
        spans = sorted(spans.values(), key=lambda s: s["start_ts"])
    extra = _device_trace_events(profile_id) if profile_id else None
    return export_chrome_trace(spans, path, extra_events=extra)


def _device_trace_events(profile_id: str):
    """Load a registered capture's device-trace events for the Perfetto
    merge: one `device:<name>` lane set per captured node."""
    from . import profiling

    store = _runtime().profiles
    record = store.get(profile_id)
    if record is None:
        raise ValueError(f"no registered profile {profile_id!r}")
    events = []
    for node_hex, meta in record.get("nodes", {}).items():
        if meta.get("artifacts_at"):
            continue  # logical-node alias: artifacts live under the head
        artifacts = {
            name.split("/", 1)[1]: data
            for name, data in store.artifacts_for(
                profile_id, node_hex=node_hex
            ).items()
        }
        if not artifacts:
            continue
        events.extend(profiling.load_device_trace_events(
            artifacts,
            started_at=meta.get("started_at", record["started_at"]),
            lane_prefix=f"device:{node_hex[:8]}",
        ))
    return events


# one-shot latch for the chrome_tracing_dump deprecation warning
# (a list so tests can reset it without reaching into module globals)
_chrome_dump_warned = [False]


def chrome_tracing_dump(path: Optional[str] = None) -> str:
    """DEPRECATED: thin wrapper over `trace_dump`. The two exports used
    to be parallel implementations (flat completed-task intervals here,
    the span tree there) and could drift; now this delegates so there is
    exactly one Perfetto/chrome-trace encoder. Emits one
    DeprecationWarning per process; new code should call `trace_dump`
    (optionally with `trace_id=`) directly."""
    if not _chrome_dump_warned[0]:
        _chrome_dump_warned[0] = True
        warnings.warn(
            "chrome_tracing_dump is deprecated; use trace_dump (same "
            "chrome-trace JSON, full span causality)",
            DeprecationWarning, stacklevel=2,
        )
    return trace_dump(path)


def list_events(limit: int = 500, severity: Optional[str] = None,
                source: Optional[str] = None) -> List[Dict[str, Any]]:
    """Structured runtime events of THIS process (util/events.py)."""
    from .events import events as _events

    return _events().list(limit=limit, severity=severity, source=source)


def events(limit: int = 1000, *, kind: Optional[str] = None,
           node: Optional[str] = None, since: float = 0.0,
           severity: Optional[str] = None,
           source: Optional[str] = None) -> List[Dict[str, Any]]:
    """The cluster-wide flight-recorder tail, sorted by wall time: this
    process's event ring merged with every node's federated tail from
    the GCS `_events` table (core/cluster.py ships them on the stats
    piggyback). Filters: `kind` (registered event kind), `node` (id hex
    prefix), `since` (wall ts), `severity` (case-insensitive), `source`.
    Deduped by (node, seq) — the head's own events appear both locally
    and in the table."""
    from .events import events as _events
    from .events import normalize_severity

    merged: Dict[Any, Dict[str, Any]] = {}
    for e in _events().list(limit=10_000):
        merged[(e.get("node"), e["seq"])] = e
    if _rt.is_initialized():
        from ..core.gcs import EVENT_NS

        runtime = _rt.get_runtime()
        ctx = getattr(runtime, "cluster", None)
        try:
            if ctx is not None:
                for key in ctx.gcs.kv_keys(namespace=EVENT_NS):
                    for e in ctx.gcs.kv_get(key, namespace=EVENT_NS) or []:
                        merged.setdefault((e.get("node"), e.get("seq")), e)
            else:
                kv = runtime.gcs.kv
                for key in kv.keys(namespace=EVENT_NS):
                    for e in kv.get(key, namespace=EVENT_NS) or []:
                        merged.setdefault((e.get("node"), e.get("seq")), e)
        except Exception:  # noqa: BLE001 - the local ring still answers
            pass
    sev = normalize_severity(severity) if severity is not None else None
    out = [
        e for e in merged.values()
        if e.get("ts", 0.0) >= since
        and (kind is None or e.get("kind") == kind)
        and (node is None or str(e.get("node") or "").startswith(node))
        and (sev is None or e.get("severity") == sev)
        and (source is None or e.get("source") == source)
    ]
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
    return out[-limit:] if limit else out


def _federated_request_marks() -> List[Dict[str, Any]]:
    """Every request-forensics mark visible from this process: the local
    reqlog ring merged with every node's federated tail in the GCS
    `_requests` table (core/cluster.py ships them on the same stats
    piggyback as the flight recorder). Deduped by (node, seq), sorted by
    wall time."""
    from ..serve import reqlog

    merged: Dict[Any, Dict[str, Any]] = {}
    for m in reqlog.log().since(0, max_n=1_000_000):
        merged[(m.get("node"), m.get("seq"))] = m
    if _rt.is_initialized():
        from ..core.gcs import REQLOG_NS

        runtime = _rt.get_runtime()
        ctx = getattr(runtime, "cluster", None)
        try:
            if ctx is not None:
                for key in ctx.gcs.kv_keys(namespace=REQLOG_NS):
                    for m in ctx.gcs.kv_get(key, namespace=REQLOG_NS) or []:
                        merged.setdefault((m.get("node"), m.get("seq")), m)
            else:
                kv = runtime.gcs.kv
                for key in kv.keys(namespace=REQLOG_NS):
                    for m in kv.get(key, namespace=REQLOG_NS) or []:
                        merged.setdefault((m.get("node"), m.get("seq")), m)
        except Exception:  # noqa: BLE001 - the local ring still answers
            pass
    out = list(merged.values())
    out.sort(key=lambda m: (m.get("ts", 0.0), m.get("seq", 0)))
    return out


def request_timeline(request_id: str) -> List[Dict[str, Any]]:
    """Every recorded mark of ONE request, cluster-wide, in causal
    (wall-clock) order: router marks from the caller's node interleaved
    with engine marks from the replica's node on the shared request id.
    Render with `serve.reqlog.render_waterfall(marks)` — the CLI command
    `ray_tpu request <id>` is a thin wrapper."""
    return [
        m for m in _federated_request_marks()
        if m.get("rid") == request_id
    ]


def list_requests(tenant: Optional[str] = None, slow_only: bool = False,
                  limit: int = 200) -> List[Dict[str, Any]]:
    """Cluster-wide request summaries (newest last): request id, tenant,
    first/last phase, terminal outcome, TTFT and its decomposition
    buckets. `slow_only` keeps requests whose TTFT exceeded the serve
    objective or that timed out — the on-call's worklist."""
    from ..core.config import cfg
    from ..serve import reqlog

    merged: Dict[str, Dict[str, Any]] = {
        s["request_id"]: s
        for s in reqlog.summarize_marks(_federated_request_marks())
    }
    # the local summary index survives mark-ring eviction: it wins over
    # a summary rebuilt from a truncated federated tail
    for s in reqlog.log().requests(limit=1_000_000):
        merged[s["request_id"]] = s
    out = list(merged.values())
    if tenant is not None:
        out = [s for s in out if s.get("tenant") == tenant]
    if slow_only:
        slo = cfg.serve_slo_ttft_p99_s
        out = [
            s for s in out
            if (s.get("ttft_s") is not None and s["ttft_s"] > slo)
            or s.get("terminal") in ("route.timeout", "engine.timeout")
        ]
    out.sort(key=lambda s: (s.get("last_ts", 0.0), s.get("request_id", "")))
    return out[-limit:] if limit else out


def _federated_step_marks() -> List[Dict[str, Any]]:
    """Every training-forensics step mark visible from this process: the
    local steplog ring merged with every node's federated tail in the
    GCS `_steps` table (core/cluster.py ships them on the same stats
    piggyback as the flight recorder). Deduped by the SEMANTIC key
    (run, rank, step, phase) — one sampled step's mark can reach the
    table both via its worker node's own federation and via the
    controller's re-ring after ingest — and sorted by wall time."""
    from ..train import steplog

    def _key(m: Dict[str, Any]) -> Any:
        return (m.get("run"), m.get("rank"), m.get("step"), m.get("phase"))

    merged: Dict[Any, Dict[str, Any]] = {}
    for m in steplog.log().since(0, max_n=1_000_000):
        merged[_key(m)] = m
    if _rt.is_initialized():
        from ..core.gcs import STEPLOG_NS

        runtime = _rt.get_runtime()
        ctx = getattr(runtime, "cluster", None)
        try:
            if ctx is not None:
                for key in ctx.gcs.kv_keys(namespace=STEPLOG_NS):
                    for m in ctx.gcs.kv_get(key, namespace=STEPLOG_NS) or []:
                        merged.setdefault(_key(m), m)
            else:
                kv = runtime.gcs.kv
                for key in kv.keys(namespace=STEPLOG_NS):
                    for m in kv.get(key, namespace=STEPLOG_NS) or []:
                        merged.setdefault(_key(m), m)
        except Exception:  # noqa: BLE001 - the local ring still answers
            pass
    out = list(merged.values())
    out.sort(key=lambda m: (m.get("ts", 0.0), m.get("seq", 0)))
    return out


def step_timeline(run: str, rank: Optional[int] = None) -> List[Dict[str, Any]]:
    """Per-rank step-phase summaries of ONE training run, cluster-wide
    (sampled steps only), ordered by (step, rank). Each summary's
    buckets sum to its step wall time exactly — render with
    `train.steplog.render_waterfall(summaries)`; the CLI command
    `ray_tpu steps <run>` is a thin wrapper."""
    from ..train import steplog

    out = [
        s for s in steplog.summarize_steps(_federated_step_marks())
        if s.get("run") == run and (rank is None or s.get("rank") == rank)
    ]
    out.sort(key=lambda s: (s.get("step", 0), s.get("rank", 0)))
    return out


def list_steps(run: Optional[str] = None,
               limit: int = 200) -> List[Dict[str, Any]]:
    """Cluster-wide sampled-step summaries (newest last): run, rank,
    step, wall seconds, phase buckets. The local summary index survives
    mark-ring eviction, so it wins over a summary rebuilt from a
    truncated federated tail."""
    from ..train import steplog

    merged: Dict[Any, Dict[str, Any]] = {
        (s.get("run"), s.get("rank"), s.get("step")): s
        for s in steplog.summarize_steps(_federated_step_marks())
    }
    for s in steplog.log().steps(run=run, limit=1_000_000):
        merged[(s.get("run"), s.get("rank"), s.get("step"))] = s
    out = list(merged.values())
    if run is not None:
        out = [s for s in out if s.get("run") == run]
    out.sort(key=lambda s: (s.get("ts", 0.0), s.get("step", 0),
                            s.get("rank", 0)))
    return out[-limit:] if limit else out


def step_skew(run: str) -> List[Dict[str, Any]]:
    """Cross-rank skew matrix of one run's sampled steps: per step, each
    rank's wall time and buckets, the spread, the straggler rank, and
    the phase bucket where that rank lost the time vs its fastest peer
    (`train.steplog.skew_matrix`)."""
    from ..train import steplog

    return steplog.skew_matrix(step_timeline(run))


def engine_snapshot() -> Dict[str, Any]:
    """Live introspection of every LLM engine in THIS process, keyed by
    engine label: lane table (who holds each lane, position, pages,
    in-flight blocks), page-pool occupancy, prefix-cache chain heads,
    and per-tenant fair-queue depths. Point-in-time and lock-free on the
    engine side — a forensics read never stalls the serving loop."""
    from ..serve.llm import engine as llm_engine

    out: Dict[str, Any] = {}
    for label, eng in list(llm_engine._ENGINES.items()):
        try:
            out[label] = eng.snapshot()
        except Exception as e:  # noqa: BLE001 - one bad engine ≠ no answer
            out[label] = {"error": repr(e)}
    return out


def postmortem(output: str, note: str = "") -> Dict[str, Any]:
    """Snapshot the cluster's observability planes — events, span
    buffers, /metrics/cluster, node stats, profile metas — into one
    postmortem bundle archive at `output`, including the reconstructed
    wall-clock-aligned Perfetto timeline. Returns the bundle manifest.
    The CLI command `ray_tpu postmortem` is a thin wrapper."""
    from .postmortem import build_bundle

    return build_bundle(output, note=note)


def cluster_events(limit: int = 500) -> Dict[str, List[Dict[str, Any]]]:
    """Event tails for every cluster node, keyed by node id hex."""
    rt = _runtime()
    ctx = getattr(rt, "cluster", None)
    if ctx is None:
        return {"local": list_events(limit=limit)}
    out = ctx.fanout_nodes(
        "node_events", 0, limit,
        placeholder=lambda e: [
            {"severity": "ERROR", "source": "state",
             "message": f"unreachable: {e!r}"}
        ],
    )
    out[ctx.node_id.hex()] = list_events(limit=limit)
    return out


def cluster_logs(tail: int = 200) -> Dict[str, List[str]]:
    """Log tails for every cluster node, keyed by node id hex
    (reference: `ray logs` over the dashboard's per-node log routes)."""
    from . import logs

    return logs.cluster_tail(_runtime(), tail)
