"""In-process log capture for cross-node log aggregation.

Reference parity: the per-node log directory + dashboard log routes
(`ray logs`, dashboard/modules/log/) — every raylet's worker logs are
fetchable from any driver. TPU inversion: one process per node means
one Python logging stream per node; a ring-buffer Handler captures the
tail, the node agent serves it over its existing RPC server
(`node_logs`), and `ray_tpu logs` / the dashboard aggregate across the
cluster view. Nothing is written to disk unless the user configures
logging to do so."""

from __future__ import annotations

import contextlib
import contextvars
import logging
import threading
from collections import deque
from typing import Dict, Iterator, List, Optional

# -------------------------------------------------------------- attribution
#
# Captured lines carry their ORIGIN: a [node:...] prefix (set once per
# process) and, when the record was emitted from inside a task/actor
# execution path, a [task:...]/[actor:...] tag from the context-local
# attribution — so cluster-aggregated tails (`ray_tpu status --verbose`,
# the dashboard) can group lines even after nodes' tails are merged.

_node_hex: Optional[str] = None
_log_ctx: "contextvars.ContextVar[Optional[str]]" = contextvars.ContextVar(
    "ray_tpu_log_attribution", default=None
)


def set_node_id(node_hex: str) -> None:
    """Record this process's node id; captured lines get a
    [node:<prefix>] tag from here on (idempotent, runtime init calls it)."""
    global _node_hex
    _node_hex = node_hex


@contextlib.contextmanager
def attribution(tag: str) -> Iterator[None]:
    """Tag log records emitted inside the block with their originating
    task/actor (e.g. "task:ab12cd34", "actor:Trainer"). Set by the
    executing thread, so it composes with the reused task threads."""
    token = _log_ctx.set(tag)
    try:
        yield
    finally:
        _log_ctx.reset(token)


class RingBufferHandler(logging.Handler):
    """Keeps the last N formatted log lines in memory, each prefixed
    with its origin ([node:...] and the active task/actor attribution)."""

    def __init__(self, capacity: int = 5000):
        super().__init__()
        self._buf: "deque[str]" = deque(maxlen=capacity)
        self._lock2 = threading.Lock()
        self.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s: %(message)s"
        ))

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
            prefix = ""
            if _node_hex:
                prefix += f"[node:{_node_hex[:8]}] "
            ctx = _log_ctx.get()
            if ctx:
                prefix += f"[{ctx}] "
            line = prefix + line
        except Exception:  # noqa: BLE001 - logging must never raise
            return
        with self._lock2:
            self._buf.append(line)

    def tail(self, n: int = 200) -> List[str]:
        with self._lock2:
            return list(self._buf)[-n:]


_handler: Optional[RingBufferHandler] = None
_install_lock = threading.Lock()


def install(capacity: int = 5000) -> RingBufferHandler:
    """Attach the capture handler (idempotent). It hangs off the
    "ray_tpu" logger — whose level is raised to INFO if unset, since the
    root default of WARNING would filter the runtime's INFO records at
    the LOGGER before any handler ran — plus the root logger for
    WARNING+ from everything else. User console verbosity is untouched:
    the stdlib lastResort console handler still gates at WARNING."""
    global _handler
    with _install_lock:
        if _handler is None:
            _handler = RingBufferHandler(capacity)
            _handler.setLevel(logging.INFO)
            # Logger levels gate at the EMITTING logger; propagation then
            # reaches ancestor HANDLERS unconditionally — so raising the
            # package logger to INFO + one handler on root captures
            # ray_tpu INFO and everyone's WARNING+ exactly once.
            pkg = logging.getLogger("ray_tpu")
            if pkg.level == logging.NOTSET:
                pkg.setLevel(logging.INFO)
            logging.getLogger().addHandler(_handler)
        return _handler


def tail(n: int = 200) -> List[str]:
    """Last n captured lines of THIS process."""
    return _handler.tail(n) if _handler is not None else []


def cluster_tail(runtime, n: int = 200) -> Dict[str, List[str]]:
    """Log tails for every cluster node, keyed by node id hex: this
    process's buffer plus each agent's over the node_logs RPC."""
    ctx = getattr(runtime, "cluster", None)
    if ctx is None:
        return {"local": tail(n)}
    out = ctx.fanout_nodes(
        "node_logs", n, placeholder=lambda e: [f"<unreachable: {e!r}>"]
    )
    out[ctx.node_id.hex()] = tail(n)
    return out
