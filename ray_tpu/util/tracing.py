"""End-to-end distributed tracing: spans, context propagation, export.

Reference parity: the reference wires OpenTelemetry spans through its
workers (python/ray/util/tracing/tracing_helper.py — every task/actor
submission and execution gets a span whose context rides the TaskSpec)
and ships `ray timeline` for post-hoc chrome traces. TPU inversion: no
OpenTelemetry dependency in this image, so this is a lock-cheap
in-process tracer with the same wire semantics — 64-bit hex
trace_id/span_id/parent_id, a context-local "current span", and a
`_trace_ctx` dict that crosses the cluster RPC boundary (core/rpc.py
injects it into call frames; the serving agent extracts it and parents
its execution spans back to the driver's submit span, so one trace_id
spans processes).

Spans land in a per-process ring buffer (capacity
``cfg.trace_buffer_spans``) and are sampled per TRACE at the root
(``cfg.trace_sample_ratio``): an unsampled root hands every descendant —
local or remote — an unsampled context, so a whole request is either
fully recorded or free. Ending a span derives latency histograms
(raytpu_task_queue_seconds, raytpu_task_exec_seconds,
raytpu_serve_ttft_seconds, raytpu_serve_tpot_seconds,
raytpu_transfer_seconds) so the /metrics scrape and the trace waterfall
always agree. Export is chrome-trace/Perfetto JSON — spans nest, one
process lane per node, one thread lane per actor/engine slot/thread —
superseding the completed-task-only `chrome_tracing_dump`.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import Any, Dict, Iterator, List, Optional

__all__ = [
    "Span",
    "Tracer",
    "tracer",
    "span",
    "start_span",
    "current_context",
    "use_context",
    "inject_context",
    "extract_context",
    "export_chrome_trace",
    "device_annotate",
]


def _new_id() -> str:
    return os.urandom(8).hex()


# The context-local current span context: {"trace_id", "span_id",
# "sampled"}. contextvars follow the thread that set them; hops across
# threads/processes are EXPLICIT — carry `current_context()` with the
# work item and re-enter it with `use_context`/`start_span(parent=...)`.
_current: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None
)


class Span:
    """One timed operation. Not thread-safe for concurrent mutation, but
    start/end may happen on different threads (engine submit thread vs.
    loop thread) — `end()` is idempotent."""

    __slots__ = (
        "trace_id", "span_id", "parent_id", "name", "start_ts", "end_ts",
        "attrs", "status", "lane", "sampled", "_tracer", "_token", "_ended",
    )

    def __init__(self, trace_id: str, span_id: str, parent_id: Optional[str],
                 name: str, *, attrs: Optional[Dict[str, Any]] = None,
                 lane: str = "", sampled: bool = True,
                 start_ts: Optional[float] = None,
                 tracer_: "Optional[Tracer]" = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_ts = time.time() if start_ts is None else start_ts
        self.end_ts = 0.0
        self.attrs = dict(attrs or {})
        self.status = "OK"
        self.lane = lane
        self.sampled = sampled
        self._tracer = tracer_
        self._token = None
        self._ended = False

    @property
    def context(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "sampled": self.sampled,
        }

    def set_attribute(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    def end(self, status: str = "OK",
            end_ts: Optional[float] = None, **attrs: Any) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_ts = time.time() if end_ts is None else end_ts
        self.status = status
        if attrs:
            self.attrs.update(attrs)
        if self.sampled and self._tracer is not None:
            self._tracer._record(self)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_ts": self.start_ts,
            "end_ts": self.end_ts,
            "duration_s": max(0.0, self.end_ts - self.start_ts),
            "status": self.status,
            "lane": self.lane,
            "attrs": dict(self.attrs),
        }

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}, "
                f"span={self.span_id[:8]}, parent={str(self.parent_id)[:8]})")


# ------------------------------------------------------- span-derived metrics

# span name -> (histogram name, description, bucket boundaries). Observed
# at end() so the waterfall and the /metrics scrape tell the same story.
_DURATION_METRICS: Dict[str, tuple] = {
    "task.queue": (
        "raytpu_task_queue_seconds",
        "Submit-to-dispatch queue latency of tasks, from spans.",
        (0.001, 0.01, 0.1, 1.0, 10.0),
    ),
    "task.execute": (
        "raytpu_task_exec_seconds",
        "Wall-clock execution time of tasks, from spans.",
        (0.001, 0.01, 0.1, 1.0, 10.0, 60.0),
    ),
    "transfer.pull": (
        "raytpu_transfer_seconds",
        "Node-to-node object transfer latency, from spans.",
        (0.001, 0.01, 0.1, 1.0, 10.0),
    ),
    "transfer.push": (
        "raytpu_transfer_seconds",
        "Node-to-node object transfer latency, from spans.",
        (0.001, 0.01, 0.1, 1.0, 10.0),
    ),
}

# attribute of an ending "serve.request"/"engine.request" span ->
# histogram. TTFT/TPOT/queue-time fall out of the request span instead of
# ad-hoc timers (the Gemma-on-TPU comparison reports exactly these).
_SERVE_ATTR_METRICS: Dict[str, tuple] = {
    "ttft_s": (
        "raytpu_serve_ttft_seconds",
        "Time to first generated token, from engine request spans.",
        (0.005, 0.025, 0.1, 0.5, 2.0, 10.0),
    ),
    "tpot_s": (
        "raytpu_serve_tpot_seconds",
        "Time per output token after the first, from engine request spans.",
        (0.001, 0.005, 0.025, 0.1, 0.5),
    ),
    "queue_s": (
        "raytpu_serve_queue_seconds",
        "Engine admission queue wait, from engine request spans.",
        (0.001, 0.01, 0.1, 1.0, 10.0),
    ),
}


def _observe_derived(span_: Span) -> None:
    from .metrics import get_or_create_histogram

    spec = _DURATION_METRICS.get(span_.name)
    if spec is not None:
        name, desc, bounds = spec
        tags = None
        if span_.name.startswith("transfer."):
            tags = {"direction": span_.name.split(".", 1)[1]}
        get_or_create_histogram(name, desc, boundaries=bounds,
                                tag_keys=("direction",) if tags else ()).observe(
            max(0.0, span_.end_ts - span_.start_ts), tags=tags
        )
    if span_.name in ("engine.request", "serve.request"):
        for attr, (name, desc, bounds) in _SERVE_ATTR_METRICS.items():
            value = span_.attrs.get(attr)
            if isinstance(value, (int, float)) and value >= 0:
                get_or_create_histogram(name, desc, boundaries=bounds).observe(
                    float(value)
                )


# ------------------------------------------------------------------- tracer


class Tracer:
    """Per-process span sink: a ring buffer plus the sampling decision.

    Lock discipline: one mutex guards only the deque/index bookkeeping in
    `_record`; span creation takes no lock at all (ids are os.urandom,
    the sampling roll is thread-local random), so tracing stays off the
    hot path's contention profile."""

    def __init__(self, capacity: Optional[int] = None,
                 sample_ratio: Optional[float] = None):
        from ..core.config import cfg

        self._capacity = capacity or cfg.trace_buffer_spans
        self._sample_ratio = sample_ratio
        self._buf: "deque[Dict[str, Any]]" = deque(maxlen=self._capacity)
        self._lock = threading.Lock()

    # -------------------------------------------------------------- creation

    def _sampled(self) -> bool:
        ratio = self._sample_ratio
        if ratio is None:
            from ..core.config import cfg

            ratio = cfg.trace_sample_ratio
        if ratio >= 1.0:
            return True
        if ratio <= 0.0:
            return False
        return random.random() < ratio

    def start_span(self, name: str, *, parent: Optional[Dict[str, Any]] = None,
                   attrs: Optional[Dict[str, Any]] = None, lane: str = "",
                   start_ts: Optional[float] = None) -> Span:
        """Open a span. `parent` is a context dict (wire-shaped); when
        None the context-local current span is the parent; when there is
        no current span either, this span roots a new trace and rolls
        the sampling decision for the whole trace."""
        if parent is None:
            parent = _current.get()
        if parent is None:
            return Span(_new_id(), _new_id(), None, name, attrs=attrs,
                        lane=lane, sampled=self._sampled(),
                        start_ts=start_ts, tracer_=self)
        return Span(parent["trace_id"], _new_id(), parent["span_id"], name,
                    attrs=attrs, lane=lane,
                    sampled=bool(parent.get("sampled", True)),
                    start_ts=start_ts, tracer_=self)

    def record_span(self, name: str, start_ts: float, end_ts: float, *,
                    parent: Optional[Dict[str, Any]] = None,
                    attrs: Optional[Dict[str, Any]] = None,
                    lane: str = "", status: str = "OK") -> Span:
        """Record an already-finished interval (e.g. queue time measured
        after the fact) as one span."""
        span_ = self.start_span(name, parent=parent, attrs=attrs, lane=lane,
                                start_ts=start_ts)
        span_.end(status=status, end_ts=end_ts)
        return span_

    def _record(self, span_: Span) -> None:
        rec = span_.to_dict()
        with self._lock:
            self._buf.append(rec)
        try:
            _observe_derived(span_)
        except Exception:  # noqa: BLE001 - metrics must not break tracing
            pass

    # --------------------------------------------------------------- queries

    def spans(self, trace_id: Optional[str] = None,
              limit: int = 10_000) -> List[Dict[str, Any]]:
        with self._lock:
            out = [
                s for s in self._buf
                if trace_id is None or s["trace_id"] == trace_id
            ]
        return out[-limit:]

    def list_traces(self, limit: int = 100) -> List[Dict[str, Any]]:
        """Newest-last trace summaries: root name, span count, duration."""
        with self._lock:
            snapshot = list(self._buf)
        traces: Dict[str, Dict[str, Any]] = {}
        for s in snapshot:
            t = traces.setdefault(s["trace_id"], {
                "trace_id": s["trace_id"],
                "root": s["name"],
                "start_ts": s["start_ts"],
                "end_ts": s["end_ts"],
                "spans": 0,
                "errors": 0,
            })
            t["spans"] += 1
            t["start_ts"] = min(t["start_ts"], s["start_ts"])
            t["end_ts"] = max(t["end_ts"], s["end_ts"])
            if s["status"] != "OK":
                t["errors"] += 1
            if s["parent_id"] is None:
                t["root"] = s["name"]
        out = sorted(traces.values(), key=lambda t: t["start_ts"])
        for t in out:
            t["duration_s"] = max(0.0, t["end_ts"] - t["start_ts"])
        return out[-limit:]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


_tracer: Optional[Tracer] = None
_tracer_lock = threading.Lock()


def tracer() -> Tracer:
    global _tracer
    if _tracer is None:  # double-checked: creation is rare, reads are hot
        with _tracer_lock:
            if _tracer is None:
                _tracer = Tracer()
    return _tracer


# --------------------------------------------------------- context plumbing


def current_context() -> Optional[Dict[str, Any]]:
    """The active span's wire context, or None outside any span."""
    return _current.get()


@contextlib.contextmanager
def use_context(ctx: Optional[Dict[str, Any]]) -> Iterator[None]:
    """Adopt a propagated context (thread hop / RPC extract) for the
    duration of the block; no-op when ctx is None."""
    if ctx is None:
        yield
        return
    token = _current.set(ctx)
    try:
        yield
    finally:
        _current.reset(token)


def start_span(name: str, *, parent: Optional[Dict[str, Any]] = None,
               attrs: Optional[Dict[str, Any]] = None, lane: str = "") -> Span:
    """Module-level convenience over tracer().start_span (does NOT make
    the span current — use `span()` for that)."""
    return tracer().start_span(name, parent=parent, attrs=attrs, lane=lane)


@contextlib.contextmanager
def span(name: str, *, parent: Optional[Dict[str, Any]] = None,
         lane: str = "", **attrs: Any) -> Iterator[Span]:
    """Open a span, make it the context-local current span, end it on
    exit (status=ERROR with the exception repr on the error path)."""
    sp = tracer().start_span(name, parent=parent, attrs=attrs, lane=lane)
    token = _current.set(sp.context)
    try:
        yield sp
    except BaseException as exc:
        sp.end(status="ERROR", error=repr(exc))
        raise
    finally:
        _current.reset(token)
        sp.end()


# --------------------------------------------------------------- wire format

# RPC methods that never carry trace context: chunk windows fire dozens
# of times per transfer (the enclosing transfer.* span already times the
# whole thing) and heartbeats/polls are pure noise.
_RPC_SKIP = frozenset({
    "pull_chunk", "push_chunk", "heartbeat", "ping", "poll_task_done",
})


def inject_context(kwargs: Dict[str, Any], method: str = "") -> Dict[str, Any]:
    """Client half of the RPC boundary: attach the current span context
    as a `_trace_ctx` kwarg (only when a sampled span is active — idle
    control traffic stays zero-overhead)."""
    ctx = _current.get()
    if ctx is None or not ctx.get("sampled", True) or method in _RPC_SKIP:
        return kwargs
    out = dict(kwargs)
    out["_trace_ctx"] = ctx
    return out


def extract_context(kwargs: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Server half: pop the propagated context out of the call kwargs
    (mutates kwargs so handlers never see the private field)."""
    ctx = kwargs.pop("_trace_ctx", None)
    return ctx if isinstance(ctx, dict) and "trace_id" in ctx else None


# ------------------------------------------------------------------- export


def export_chrome_trace(spans: List[Dict[str, Any]],
                        path: Optional[str] = None,
                        extra_events: Optional[List[Dict[str, Any]]] = None,
                        ) -> str:
    """Chrome trace-event / Perfetto JSON for a span set. Spans nest by
    time on their lane: pid = the span's lane (node/actor/engine slot,
    falling back to the trace id), tid = the span name's subsystem. Load
    in https://ui.perfetto.dev or chrome://tracing.

    `extra_events` are pre-built trace events appended verbatim — the
    hook `state.trace_dump(profile_id=...)` uses to merge a captured
    device trace's per-device tracks (util/profiling
    load_device_trace_events, already wall-clock aligned) into the same
    file, so one timeline shows what the runtime asked for AND what the
    chip did.

    Parent→child links that CROSS a lane (a remote task's execute span
    parenting back to the driver's submit span, a router hop landing on
    a replica) additionally emit chrome flow events (ph "s"/"f") so the
    cross-node causality renders as arrows between tracks, not just
    vertically stacked slices."""
    events: List[Dict[str, Any]] = list(extra_events or [])
    by_id = {s["span_id"]: s for s in spans}

    def _pid(s: Dict[str, Any]) -> str:
        return s.get("lane") or s["trace_id"][:8]

    def _tid(s: Dict[str, Any]) -> str:
        return s["name"].split(".", 1)[0]

    for s in spans:
        parent = by_id.get(s["parent_id"]) if s.get("parent_id") else None
        if parent is None or _pid(parent) == _pid(s):
            continue
        # flow id from the child span id: unique per edge, stable across
        # re-exports of the same span set
        flow_id = int(s["span_id"][:12], 16)
        events.append({
            "name": "span-link", "cat": "flow", "ph": "s", "id": flow_id,
            "ts": parent["start_ts"] * 1e6,
            "pid": _pid(parent), "tid": _tid(parent),
            "args": {"trace_id": s["trace_id"], "child": s["name"]},
        })
        events.append({
            "name": "span-link", "cat": "flow", "ph": "f", "bp": "e",
            "id": flow_id,
            "ts": max(s["start_ts"], parent["start_ts"]) * 1e6,
            "pid": _pid(s), "tid": _tid(s),
            "args": {"trace_id": s["trace_id"], "parent": parent["name"]},
        })
    for s in spans:
        end = s["end_ts"] or s["start_ts"]
        pid = s.get("lane") or s["trace_id"][:8]
        events.append({
            "name": s["name"],
            "cat": s["name"].split(".", 1)[0],
            "ph": "X",
            "ts": s["start_ts"] * 1e6,
            "dur": max(0.0, end - s["start_ts"]) * 1e6,
            "pid": pid,
            "tid": s["name"].split(".", 1)[0],
            "args": {
                "trace_id": s["trace_id"],
                "span_id": s["span_id"],
                "parent_id": s["parent_id"],
                "status": s["status"],
                **{k: v for k, v in s.get("attrs", {}).items()
                   if isinstance(v, (str, int, float, bool, type(None)))},
            },
        })
    payload = json.dumps({"traceEvents": events})
    if path:
        with open(path, "w") as f:
            f.write(payload)
    return payload


# ------------------------------------------------- device-trace bridge


def device_annotate(name: str):
    """Label a host region in the XLA device trace (util/profiling
    .annotate) so runtime spans line up with HLO activity — returns a
    null context when jax isn't importable (tracing must never require
    the accelerator stack)."""
    try:
        from .profiling import annotate

        return annotate(name)
    except Exception:  # noqa: BLE001 - tracing works without jax
        return contextlib.nullcontext()
