"""ray_tpu.util — observability (metrics, state API, flight recorder,
goodput accounting, task timeline)."""

from .metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    cluster_prometheus_text,
    get_or_create_counter,
    get_or_create_gauge,
    get_or_create_histogram,
    merge_cluster_expositions,
    register_runtime_gauges,
    registry,
    start_metrics_server,
)
from .state import (  # noqa: F401
    chrome_tracing_dump,
    cluster_metrics,
    get_profile,
    get_trace,
    head_summary,
    list_actors,
    list_nodes,
    list_objects,
    list_profiles,
    list_tasks,
    list_traces,
    node_stats,
    profile,
    profile_artifact,
    status_report,
    summary,
    trace_dump,
)
from . import goodput, postmortem, tracing, watchdog  # noqa: F401
from .events import (  # noqa: F401
    EVENT_KINDS,
    EventLog,
    event_kinds,
    register_event_kind,
)
from .goodput import GoodputAccountant, serve_slo_report  # noqa: F401
from .postmortem import build_bundle, load_bundle  # noqa: F401
from .actor_pool import ActorPool  # noqa: F401
from .profiling import (  # noqa: F401
    ProfilingError,
    StepCost,
    annotate,
    capture_local_profile,
    device_peaks,
    device_trace,
    profiler_server_port,
    roofline,
    start_device_trace,
    start_profiler_server,
    step_annotation,
    step_cost,
    stop_device_trace,
)
from .queue import Empty, Full, Queue  # noqa: F401
from . import multiprocessing  # noqa: F401
