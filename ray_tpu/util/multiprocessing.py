"""multiprocessing.Pool shim over the task runtime.

Reference parity: ray.util.multiprocessing (/root/reference/python/ray/
util/multiprocessing/pool.py) — a drop-in Pool whose workers are cluster
tasks. Here map/starmap/apply fan out as PROCESS-executor tasks (real
GIL-free parallelism for CPU functions) with bounded in-flight chunks.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional

from .. import api


class AsyncResult:
    def __init__(self, refs: List[Any], single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: Optional[float] = None) -> Any:
        values = api.get(self._refs, timeout=timeout)
        return values[0] if self._single else values

    def wait(self, timeout: Optional[float] = None) -> None:
        api.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        done, _ = api.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)


class Pool:
    """Drop-in-ish multiprocessing.Pool: map/starmap/imap/apply_async.

    processes bounds CONCURRENT in-flight tasks (the worker pool itself
    is shared and flag-sized)."""

    def __init__(self, processes: Optional[int] = None):
        api.init(ignore_reinit_error=True)
        self._processes = processes or 4
        self._closed = False

    def _check(self):
        if self._closed:
            raise ValueError("Pool is closed")

    @staticmethod
    def _wrap(func: Callable):
        """The ONE place that decides how Pool work becomes tasks."""
        return api.remote(executor="process", num_cpus=1)(func)

    def apply(self, func: Callable, args: tuple = (), kwds: Optional[dict] = None) -> Any:
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func: Callable, args: tuple = (),
                    kwds: Optional[dict] = None) -> AsyncResult:
        self._check()
        return AsyncResult(
            [self._wrap(func).remote(*args, **(kwds or {}))], single=True
        )

    def map(self, func: Callable, iterable: Iterable[Any]) -> List[Any]:
        return list(self.imap(func, iterable))

    def starmap(self, func: Callable, iterable: Iterable[tuple]) -> List[Any]:
        self._check()
        return list(self._imap_args(func, iterable))

    def imap(self, func: Callable, iterable: Iterable[Any]):
        """Ordered streaming map with a bounded in-flight window."""
        self._check()
        return self._imap_args(func, ((x,) for x in iterable))

    def _imap_args(self, func: Callable, arg_tuples: Iterable[tuple]):
        remote_fn = self._wrap(func)
        pending: List[Any] = []
        for args in arg_tuples:
            pending.append(remote_fn.remote(*args))
            if len(pending) >= self._processes:
                yield api.get(pending.pop(0))
        for ref in pending:
            yield api.get(ref)

    def map_async(self, func: Callable, iterable: Iterable[Any]) -> AsyncResult:
        self._check()
        remote_fn = self._wrap(func)
        return AsyncResult([remote_fn.remote(x) for x in iterable])

    def close(self) -> None:
        self._closed = True

    def join(self) -> None:
        pass  # tasks are tracked by their refs; nothing to join

    def terminate(self) -> None:
        self._closed = True

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
