"""Cluster flight recorder: typed, durable, queryable runtime events.

Reference parity: the events framework under src/ray/util/ (event.h —
severity-labeled structured events exported for the dashboard and
post-mortem debugging) backed by the GCS as the durable source of truth
that makes cluster episodes debuggable after the fact. TPU inversion:
every process keeps an in-memory ring PLUS an optional bounded on-disk
JSONL segment log; the cluster heartbeat federates each node's tail
into the GCS ``_events`` table (core/cluster.py) so the head answers
``state.events()`` / ``ray_tpu events`` for the whole cluster, and
``ray_tpu postmortem`` snapshots the lot into one bundle.

Events are TYPED: every emit names a ``kind`` registered in
``EVENT_KINDS`` (node lifecycle, PG FSM transitions, preemption
announce/drain, checkpoint save/restore/quarantine, gang restarts,
serve scale/drain, chaos injections, watchdog firings, ...). The
raylint ``event-kinds`` rule holds call sites to the registry, so the
postmortem reconstructor and the goodput accountant can rely on kinds
instead of parsing messages.

Each event records BOTH clocks: ``ts`` (wall, for cross-node timeline
placement) and ``mono`` (monotonic, for intra-process interval math
that must not jump with NTP).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")

# Common spellings normalized into the fixed set; anything else is an
# unknown level and degrades to INFO (events must never raise).
_SEVERITY_ALIASES = {
    "WARN": "WARNING",
    "ERR": "ERROR",
    "FATAL": "ERROR",
    "CRITICAL": "ERROR",
    "TRACE": "DEBUG",
}


def normalize_severity(severity: Any) -> str:
    s = str(severity).strip().upper()
    s = _SEVERITY_ALIASES.get(s, s)
    return s if s in SEVERITIES else "INFO"


# ------------------------------------------------------------ kind registry
#
# kind -> one-line doc. The catalog is seeded from every emitting
# subsystem; components may register additional kinds at import time
# with register_event_kind (raylint's event-kinds rule reads both this
# literal and register_event_kind("...") call sites).

EVENT_KINDS: Dict[str, str] = {
    # node lifecycle
    "node.discovered": "a cluster node joined or rejoined the view",
    "node.dead": "a node aged out of heartbeats or was removed",
    "node.preempt_expired": "a preempted node's warning window closed",
    # preemption announce/drain
    "preempt.announced": "a node announced its upcoming preemption",
    "preempt.drain": "a PREEMPTING node stopped taking new placements",
    "preempt.notice": "a train controller received a preemption notice",
    # placement-group FSM
    "pg.transition": "a placement group moved between FSM states",
    "pg.reschedule_failed": "one placement-group reschedule attempt failed",
    # tasks / actors
    "actor.restart": "an actor restarted onto a (re-reserved) bundle/node",
    "task.parked": "an agent parked an undeliverable task completion",
    # checkpoints
    "ckpt.saved": "a training checkpoint committed (incl. emergency saves)",
    "ckpt.quarantine": "a corrupt/torn checkpoint was quarantined",
    "ckpt.fallback": "a restore fell back past a quarantined checkpoint",
    "ckpt.gc": "an uncommitted/torn checkpoint dir was garbage-collected",
    # train run lifecycle
    "train.gang_started": "a training gang (re)started and is running",
    "train.finished": "a training run finished cleanly",
    "train.errored": "a training run errored out",
    "train.restart": "a training gang restarted after a failure",
    "train.preempt_restart": "a gang restarted after an announced preemption",
    "train.coordinator": "a multihost gang elected its coordinator",
    # serve lifecycle
    "serve.deploy": "a serve deployment was (re)deployed",
    "serve.scaled": "a deployment scaled its replica count",
    "serve.drain": "a serve replica began draining",
    "serve.autoscale": "the serve autoscaler changed a replica target",
    "serve.shed": "admission control shed a request (quota/backlog)",
    "serve.degraded": "the serve controller froze/resumed over a head outage",
    "serve.lane_preempted": "a low-priority decode lane was parked for pages",
    "serve.lane_resumed": "a parked decode lane re-admitted after pressure",
    # streaming data plane
    "data.stage_start": "a streaming dataset stage began submitting tasks",
    "data.stage_finish": "a streaming dataset stage drained its last block",
    "data.backpressure": "the data executor stalled on its byte budget",
    "data.spill": "a data-plane run pushed blocks through the spill path",
    "data.reexec": "a lost block was re-executed via lineage mid-ingest",
    # chaos
    "chaos.injected": "a chaos injection fired (delay/failure/kill/preempt)",
    # watchdogs
    "watchdog.stall": "the training stall watchdog flagged a stall",
    "watchdog.recovered": "a stalled run recovered",
    "watchdog.slo_burn": "a serve SLO window exceeded its objective",
    # control plane
    "gcs.restored": "the GCS restored its tables from a snapshot",
    "gcs.subscriber_error": "a pubsub subscriber raised (first failure)",
    # head fault tolerance
    "head.unreachable": "the GCS head stopped answering; degraded mode began",
    "head.reconnected": "the GCS head answered again after an outage",
    "head.stale_epoch": "a write was fenced for carrying a pre-restart epoch",
    "head.reconciled": "a restored head finished reconciling restored state",
    "node.purged": "a restored node never re-announced and was purged",
    "health.dead": "the health-check manager declared a target dead",
    "health.oom": "the OOM policy killed a worker",
    "metrics.sampler_error": "a gauge callback raised (first failure)",
    "autoscaler.scaled": "the autoscaler launched or released a node",
    # capacity plane (core/capacity.py)
    "autoscaler.scale_up": "the capacity plane launched node(s) for pending demand",
    "autoscaler.scale_down": "the capacity plane retired a node through the drain path",
    "autoscaler.replace": "replacement capacity pre-provisioned for a preempting node",
    "autoscaler.blocked": "pending demand cannot be provisioned (limits/budget)",
    "autoscaler.error": "the autoscaler loop raised (first per exception type)",
}


def register_event_kind(kind: str, doc: str = "") -> None:
    """Register an additional typed event kind (idempotent)."""
    EVENT_KINDS.setdefault(kind, doc)


def event_kinds() -> Dict[str, str]:
    """The registered kind catalog (copy)."""
    return dict(EVENT_KINDS)


def _default_node() -> Optional[str]:
    """Attribute events to this process's node (util/logs sets it at
    runtime init) unless the emitter names a more specific one."""
    from . import logs

    return logs._node_hex


class EventLog:
    """Per-process event recorder: ring buffer + optional JSONL sink +
    optional bounded durable segment directory."""

    def __init__(self, capacity: int = 10_000,
                 sink_path: Optional[str] = None):
        self._buf: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink_path = sink_path
        self._sink_file = None  # cached handle: no per-event open()
        self._seq = 0
        # durable bounded segments (flight-recorder disk arm)
        self._seg_dir: Optional[str] = None
        self._seg_file = None
        self._seg_bytes = 0
        self._seg_max_bytes = 1 << 20
        self._seg_keep = 8
        self._seg_counter = 0

    def _sink_handle(self):
        """Caller holds the lock. Lazily (re)open the cached JSONL
        handle — event-heavy failover drills must not pay an open() per
        event; set_sink swaps it."""
        if self._sink_file is None and self._sink_path:
            self._sink_file = open(self._sink_path, "a")
        return self._sink_file

    def emit(self, severity: str, source: str, message: str,
             kind: str = "", node: Optional[str] = None,
             **extra: Any) -> Dict[str, Any]:
        """Record one typed event. `source` is the emitting subsystem
        ("cluster", "train", "health", ...); `kind` is a registered
        EVENT_KINDS name (the raylint event-kinds rule enforces this
        statically — at runtime unknown kinds are still recorded);
        `node` attributes the event to a node id hex (defaults to this
        process's node)."""
        severity = normalize_severity(severity)
        if node is None:
            node = _default_node()
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "mono": time.monotonic(),
                "severity": severity,
                "kind": kind or "",
                "source": source,
                "node": node,
                "message": message,
                **({"extra": extra} if extra else {}),
            }
            self._buf.append(event)
            # write under the lock: concurrent emitters on one handle
            # would otherwise interleave partial JSONL lines
            line = None
            try:
                f = self._sink_handle()
                if f is not None:
                    line = json.dumps(event, default=str)
                    f.write(line + "\n")
                    f.flush()
            except (OSError, ValueError, TypeError):
                # a full disk must not take the runtime down; drop the
                # handle so a later emit can retry a fresh open
                self._close_sink_locked()
            try:
                self._segment_write_locked(
                    line if line is not None
                    else json.dumps(event, default=str)
                )
            except (OSError, ValueError, TypeError):
                self._close_segment_locked()
        return event

    def _close_sink_locked(self) -> None:
        if self._sink_file is not None:
            try:
                self._sink_file.close()
            except OSError:
                pass
            self._sink_file = None

    # ------------------------------------------------------ durable segments

    def configure_segments(self, directory: Optional[str],
                           max_bytes: Optional[int] = None,
                           keep: Optional[int] = None) -> None:
        """Enable (or disable, with None) the bounded on-disk segment
        log: events append to `<dir>/events.jsonl`; once it exceeds
        `max_bytes` it rotates — an atomic os.replace into a numbered
        segment file — and only the newest `keep` rotated segments
        survive. Readers tolerate a torn tail line (a crash mid-append
        loses at most the event being written)."""
        from ..core.config import cfg

        with self._lock:
            self._close_segment_locked()
            self._seg_dir = directory or None
            self._seg_max_bytes = (
                cfg.events_segment_bytes if max_bytes is None else max_bytes
            )
            self._seg_keep = cfg.events_segments_keep if keep is None else keep
            if self._seg_dir:
                os.makedirs(self._seg_dir, exist_ok=True)
                # resume the rotation counter past existing segments
                self._seg_counter = max(
                    [_segment_index(n) for n in os.listdir(self._seg_dir)
                     if _segment_index(n) is not None] or [0]
                )

    def _segment_write_locked(self, line: str) -> None:
        if not self._seg_dir:
            return
        if self._seg_file is None:
            path = os.path.join(self._seg_dir, "events.jsonl")
            self._seg_file = open(path, "a")
            self._seg_bytes = self._seg_file.tell()
        self._seg_file.write(line + "\n")
        self._seg_file.flush()
        self._seg_bytes += len(line) + 1
        if self._seg_bytes >= self._seg_max_bytes:
            self._rotate_segment_locked()

    def _rotate_segment_locked(self) -> None:
        self._seg_file.close()
        self._seg_file = None
        self._seg_bytes = 0
        self._seg_counter += 1
        current = os.path.join(self._seg_dir, "events.jsonl")
        rotated = os.path.join(
            self._seg_dir, f"events-{self._seg_counter:06d}.jsonl"
        )
        os.replace(current, rotated)  # atomic: no torn half-renamed state
        # prune beyond the retention bound, oldest first
        segments = sorted(
            n for n in os.listdir(self._seg_dir)
            if _segment_index(n) is not None
        )
        for name in segments[: max(0, len(segments) - self._seg_keep)]:
            try:
                os.remove(os.path.join(self._seg_dir, name))
            except OSError:
                pass

    def _close_segment_locked(self) -> None:
        if self._seg_file is not None:
            try:
                self._seg_file.close()
            except OSError:
                pass
            self._seg_file = None
            self._seg_bytes = 0

    # --------------------------------------------------------------- queries

    def list(self, *, since_seq: int = 0, severity: Optional[str] = None,
             source: Optional[str] = None, kind: Optional[str] = None,
             node: Optional[str] = None, since_ts: float = 0.0,
             limit: int = 1000) -> List[Dict[str, Any]]:
        """Filtered event tail (oldest first). `severity` matching is
        case-insensitive; `node` matches on hex prefix."""
        sev = normalize_severity(severity) if severity is not None else None
        with self._lock:
            out = [
                e for e in self._buf
                if e["seq"] > since_seq
                and e["ts"] >= since_ts
                and (sev is None or e["severity"] == sev)
                and (source is None or e["source"] == source)
                and (kind is None or e.get("kind") == kind)
                and (node is None or str(e.get("node") or "").startswith(node))
            ]
        return out[-limit:]

    def since(self, seq: int, max_n: int = 1000) -> List[Dict[str, Any]]:
        """The OLDEST max_n events with seq greater than `seq` — the
        federation cursor walk (never skips events the way a tail-limit
        would; a slow shipper just takes more periods to catch up)."""
        with self._lock:
            return [e for e in self._buf if e["seq"] > seq][:max_n]

    def stats(self) -> Dict[str, Any]:
        """Flight-recorder health for the node stats snapshot
        (core/stats.py): total events emitted, ring occupancy, and
        whether the durable segment arm is on."""
        with self._lock:
            return {
                "seq": self._seq,
                "buffered": len(self._buf),
                "segments_dir": self._seg_dir,
            }

    def set_sink(self, path: Optional[str]) -> None:
        with self._lock:
            self._close_sink_locked()
            self._sink_path = path
            if path:
                try:
                    self._sink_file = open(path, "a")
                except OSError:
                    self._sink_file = None  # emit retries lazily

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


def _segment_index(name: str) -> Optional[int]:
    """events-000042.jsonl -> 42; anything else -> None."""
    if not (name.startswith("events-") and name.endswith(".jsonl")):
        return None
    stem = name[len("events-"):-len(".jsonl")]
    return int(stem) if stem.isdigit() else None


def read_segments(directory: str) -> List[Dict[str, Any]]:
    """Replay a segment directory oldest-first: rotated segments in
    order, then the live file. Undecodable lines (torn tail after a
    crash) are skipped, not raised."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(
            n for n in os.listdir(directory) if _segment_index(n) is not None
        )
    except OSError:
        return out
    names.append("events.jsonl")
    for name in names:
        try:
            with open(os.path.join(directory, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        out.append(json.loads(line))
                    except ValueError:
                        continue  # torn tail line
        except OSError:
            continue
    return out


_log: Optional[EventLog] = None
_log_lock = threading.Lock()


def events() -> EventLog:
    global _log
    with _log_lock:
        if _log is None:
            _log = EventLog()
        return _log


def emit(severity: str, source: str, message: str, kind: str = "",
         node: Optional[str] = None, **extra: Any) -> None:
    """Module-level convenience used by runtime components."""
    events().emit(severity, source, message, kind=kind, node=node, **extra)
