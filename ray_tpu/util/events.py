"""Structured cluster events: what happened, when, where — queryable.

Reference parity: the events framework under src/ray/util/ (event.h —
severity-labeled structured events exported for the dashboard and
post-mortem debugging) and the dashboard's event module. TPU inversion:
an in-process ring buffer with an optional JSONL sink — the runtime's
interesting transitions (node join/death, actor restart, failover,
OOM kills, PG lifecycle, head restore) are emitted here by the
components themselves, the state API/dashboard read it back, and the
CLI can dump it. One process = one log; cluster-wide views aggregate
over the node-log RPC like logs do.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


class EventLog:
    def __init__(self, capacity: int = 10_000,
                 sink_path: Optional[str] = None):
        self._buf: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink_path = sink_path
        self._seq = 0

    def emit(self, severity: str, source: str, message: str,
             **extra: Any) -> Dict[str, Any]:
        """Record one event. source is the emitting subsystem
        ("cluster", "actors", "health", "autoscaler", "jobs", ...)."""
        if severity not in SEVERITIES:
            severity = "INFO"
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "severity": severity,
                "source": source,
                "message": message,
                **({"extra": extra} if extra else {}),
            }
            self._buf.append(event)
            sink = self._sink_path
        if sink:
            try:
                with open(sink, "a") as f:
                    f.write(json.dumps(event, default=str) + "\n")
            except OSError:
                pass  # a full disk must not take the runtime down
        return event

    def list(self, *, since_seq: int = 0, severity: Optional[str] = None,
             source: Optional[str] = None, limit: int = 1000) -> List[Dict[str, Any]]:
        with self._lock:
            out = [
                e for e in self._buf
                if e["seq"] > since_seq
                and (severity is None or e["severity"] == severity)
                and (source is None or e["source"] == source)
            ]
        return out[-limit:]

    def set_sink(self, path: Optional[str]) -> None:
        with self._lock:
            self._sink_path = path

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


_log: Optional[EventLog] = None
_log_lock = threading.Lock()


def events() -> EventLog:
    global _log
    with _log_lock:
        if _log is None:
            _log = EventLog()
        return _log


def emit(severity: str, source: str, message: str, **extra: Any) -> None:
    """Module-level convenience used by runtime components."""
    events().emit(severity, source, message, **extra)
