"""Structured cluster events: what happened, when, where — queryable.

Reference parity: the events framework under src/ray/util/ (event.h —
severity-labeled structured events exported for the dashboard and
post-mortem debugging) and the dashboard's event module. TPU inversion:
an in-process ring buffer with an optional JSONL sink — the runtime's
interesting transitions (node join/death, actor restart, failover,
OOM kills, PG lifecycle, head restore) are emitted here by the
components themselves, the state API/dashboard read it back, and the
CLI can dump it. One process = one log; cluster-wide views aggregate
over the node-log RPC like logs do.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

SEVERITIES = ("DEBUG", "INFO", "WARNING", "ERROR")


class EventLog:
    def __init__(self, capacity: int = 10_000,
                 sink_path: Optional[str] = None):
        self._buf: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._sink_path = sink_path
        self._sink_file = None  # cached handle: no per-event open()
        self._seq = 0

    def _sink_handle(self):
        """Caller holds the lock. Lazily (re)open the cached JSONL
        handle — event-heavy failover drills must not pay an open() per
        event; set_sink swaps it."""
        if self._sink_file is None and self._sink_path:
            self._sink_file = open(self._sink_path, "a")
        return self._sink_file

    def emit(self, severity: str, source: str, message: str,
             **extra: Any) -> Dict[str, Any]:
        """Record one event. source is the emitting subsystem
        ("cluster", "actors", "health", "autoscaler", "jobs", ...)."""
        if severity not in SEVERITIES:
            severity = "INFO"
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "ts": time.time(),
                "severity": severity,
                "source": source,
                "message": message,
                **({"extra": extra} if extra else {}),
            }
            self._buf.append(event)
            # write under the lock: concurrent emitters on one handle
            # would otherwise interleave partial JSONL lines
            try:
                f = self._sink_handle()
                if f is not None:
                    f.write(json.dumps(event, default=str) + "\n")
                    f.flush()
            except (OSError, ValueError):
                # a full disk must not take the runtime down; drop the
                # handle so a later emit can retry a fresh open
                self._close_sink_locked()
        return event

    def _close_sink_locked(self) -> None:
        if self._sink_file is not None:
            try:
                self._sink_file.close()
            except OSError:
                pass
            self._sink_file = None

    def list(self, *, since_seq: int = 0, severity: Optional[str] = None,
             source: Optional[str] = None, limit: int = 1000) -> List[Dict[str, Any]]:
        with self._lock:
            out = [
                e for e in self._buf
                if e["seq"] > since_seq
                and (severity is None or e["severity"] == severity)
                and (source is None or e["source"] == source)
            ]
        return out[-limit:]

    def set_sink(self, path: Optional[str]) -> None:
        with self._lock:
            self._close_sink_locked()
            self._sink_path = path
            if path:
                try:
                    self._sink_file = open(path, "a")
                except OSError:
                    self._sink_file = None  # emit retries lazily

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


_log: Optional[EventLog] = None
_log_lock = threading.Lock()


def events() -> EventLog:
    global _log
    with _log_lock:
        if _log is None:
            _log = EventLog()
        return _log


def emit(severity: str, source: str, message: str, **extra: Any) -> None:
    """Module-level convenience used by runtime components."""
    events().emit(severity, source, message, **extra)
