"""ray_tpu.rllib — reinforcement learning on the actor runtime.

Reference parity: rllib (/root/reference/rllib/ — Algorithm :202,
EnvRunner groups, algorithms/ppo + algorithms/dqn). Scoped to the
load-bearing core: vectorized envs, actor rollout workers, PPO (the
on-policy family) and double-DQN with replay (the off-policy family),
each as one fused XLA update program.
"""

from .dqn import DQN, DQNConfig, DQNRolloutWorker, ReplayBuffer  # noqa: F401
from .env import CartPoleVectorEnv, VectorEnv, make_env, register_env  # noqa: F401
from .ppo import PPO, PPOConfig, RolloutWorker, init_policy, policy_forward  # noqa: F401
