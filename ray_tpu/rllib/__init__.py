"""ray_tpu.rllib — reinforcement learning on the actor runtime.

Reference parity: rllib (/root/reference/rllib/ — Algorithm :202,
EnvRunner groups, PPO). Scoped to the load-bearing core: vectorized
envs, actor rollout workers, and PPO as one fused XLA update.
"""

from .env import CartPoleVectorEnv, VectorEnv, make_env, register_env  # noqa: F401
from .ppo import PPO, PPOConfig, RolloutWorker, init_policy, policy_forward  # noqa: F401
