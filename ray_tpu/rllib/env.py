"""Vectorized environments for the RL library.

Reference parity: rllib's EnvRunner/vector-env substrate
(/root/reference/rllib/env/). Zero-egress image ⇒ no gym dependency: the
classic CartPole dynamics are implemented directly in numpy (same
physics constants as gym's CartPole-v1), vectorized over N lanes with
auto-reset — the standard benchmark env for "does the algorithm learn".
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np


class VectorEnv:
    """N independent env lanes stepped in lockstep, auto-resetting."""

    observation_dim: int
    num_actions: int

    def reset(self, seed: int = 0) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """-> (obs (N, D), rewards (N,), dones (N,)); done lanes restart."""
        raise NotImplementedError


class CartPoleVectorEnv(VectorEnv):
    """CartPole-v1 physics (pole balancing; +1 reward per step, episode
    ends past ±12° / ±2.4 units / 500 steps)."""

    observation_dim = 4
    num_actions = 2
    max_steps = 500

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE = 10.0
    DT = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4

    def __init__(self, num_envs: int = 8):
        self.num_envs = num_envs
        self._state = np.zeros((num_envs, 4), np.float64)
        self._steps = np.zeros(num_envs, np.int64)
        self._rng = np.random.default_rng(0)

    def reset(self, seed: int = 0) -> np.ndarray:
        self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=(self.num_envs, 4))
        self._steps[:] = 0
        return self._state.astype(np.float32)

    def _reset_lanes(self, mask: np.ndarray) -> None:
        n = int(mask.sum())
        if n:
            self._state[mask] = self._rng.uniform(-0.05, 0.05, size=(n, 4))
            self._steps[mask] = 0

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE, -self.FORCE)
        cos, sin = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot**2 * sin) / total_mass
        theta_acc = (self.GRAVITY * sin - cos * temp) / (
            self.POLE_HALF_LEN * (4.0 / 3.0 - self.POLE_MASS * cos**2 / total_mass)
        )
        x_acc = temp - pole_ml * theta_acc * cos / total_mass
        x = x + self.DT * x_dot
        x_dot = x_dot + self.DT * x_acc
        theta = theta + self.DT * theta_dot
        theta_dot = theta_dot + self.DT * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1
        dones = (
            (np.abs(x) > self.X_LIMIT)
            | (np.abs(theta) > self.THETA_LIMIT)
            | (self._steps >= self.max_steps)
        )
        rewards = np.ones(self.num_envs, np.float32)
        self._reset_lanes(dones)
        return self._state.astype(np.float32), rewards, dones


_ENV_REGISTRY: Dict[str, Callable[[int], VectorEnv]] = {
    "cartpole": lambda n: CartPoleVectorEnv(n),
    "CartPole-v1": lambda n: CartPoleVectorEnv(n),
}


def register_env(name: str, factory: Callable[[int], VectorEnv]) -> None:
    _ENV_REGISTRY[name] = factory


def make_env(name: str, num_envs: int) -> VectorEnv:
    if name not in _ENV_REGISTRY:
        raise ValueError(f"unknown env {name!r}; register_env() it first")
    return _ENV_REGISTRY[name](num_envs)
