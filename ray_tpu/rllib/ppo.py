"""PPO: the flagship RL algorithm, TPU-native.

Reference parity: rllib's PPO (/root/reference/rllib/algorithms/ppo/ —
Algorithm.train() :202 driving EnvRunner actors + a Learner). TPU
inversion: rollout workers are ray_tpu actors stepping numpy vector envs
with a jitted policy; learning is ONE fused jitted update (GAE targets →
minibatched clipped-surrogate epochs via lax.scan) so the whole
optimization step is a single XLA program — no per-minibatch Python.

    algo = PPOConfig(env="cartpole", num_workers=2).build()
    for _ in range(20):
        result = algo.train()     # {"episode_reward_mean": ...}
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import api
from .env import make_env

Params = Dict[str, Any]


# ------------------------------------------------------------------- policy


def init_policy(key: jax.Array, obs_dim: int, num_actions: int,
                hidden: Tuple[int, ...] = (64, 64)) -> Params:
    """MLP actor-critic: shared trunk, policy + value heads."""
    params: Params = {}
    sizes = (obs_dim,) + hidden
    for i in range(len(hidden)):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * (
            1.0 / np.sqrt(sizes[i])
        )
        params[f"b{i}"] = jnp.zeros(sizes[i + 1])
    key, k1, k2 = jax.random.split(key, 3)
    params["w_pi"] = jax.random.normal(k1, (hidden[-1], num_actions)) * 0.01
    params["b_pi"] = jnp.zeros(num_actions)
    params["w_v"] = jax.random.normal(k2, (hidden[-1], 1)) * 1.0 / np.sqrt(hidden[-1])
    params["b_v"] = jnp.zeros(1)
    return params


def policy_forward(params: Params, obs: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """obs (..., D) -> (logits (..., A), value (...,))."""
    x = obs
    i = 0
    while f"w{i}" in params:
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[..., 0]
    return logits, value


# ------------------------------------------------------------------ rollout


class RolloutWorker:
    """Actor: steps a vector env with the latest policy, returns batches.
    (reference EnvRunner, rllib/env/env_runner.py)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int, seed: int):
        self.env = make_env(env_name, num_envs)
        self.rollout_len = rollout_len
        self.obs = self.env.reset(seed=seed)
        self.seed = seed
        self._key = jax.random.PRNGKey(seed)
        self._episode_returns = np.zeros(num_envs, np.float32)
        self._finished_returns: List[float] = []
        self._sample = jax.jit(
            lambda p, o, k: _sample_action(p, o, k)
        )

    def set_weights(self, params: Params) -> None:
        self.params = params

    def rollout(self) -> Dict[str, np.ndarray]:
        T, N = self.rollout_len, self.env.num_envs
        obs_buf = np.zeros((T, N, self.env.observation_dim), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        self._finished_returns = []
        for t in range(T):
            self._key, sub = jax.random.split(self._key)
            action, logp, value = self._sample(self.params, self.obs, sub)
            action = np.asarray(action)
            obs_buf[t] = self.obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            self.obs, rewards, dones = self.env.step(action)
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._episode_returns += rewards
            for i in np.nonzero(dones)[0]:
                self._finished_returns.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
        _, last_value = policy_forward(self.params, jnp.asarray(self.obs))
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "last_value": np.asarray(last_value),
            "episode_returns": np.asarray(self._finished_returns, np.float32),
        }


def _sample_action(params, obs, key):
    logits, value = policy_forward(params, obs)
    action = jax.random.categorical(key, logits)
    logp = jax.nn.log_softmax(logits)[jnp.arange(obs.shape[0]), action]
    return action, logp, value


# ---------------------------------------------------------------- algorithm


@dataclasses.dataclass
class PPOConfig:
    env: str = "cartpole"
    num_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_len: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    entropy_coeff: float = 0.01
    value_coeff: float = 0.5
    num_epochs: int = 4
    num_minibatches: int = 4
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    """Algorithm.train() parity (reference rllib/algorithms/algorithm.py:202)."""

    def __init__(self, config: PPOConfig):
        self.config = config
        env = make_env(config.env, 1)
        self.obs_dim = env.observation_dim
        self.num_actions = env.num_actions
        key = jax.random.PRNGKey(config.seed)
        self.params = init_policy(key, self.obs_dim, self.num_actions, config.hidden)
        self.opt = optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        self._key = jax.random.PRNGKey(config.seed + 1)
        self.iteration = 0

        worker_cls = api.remote(RolloutWorker)
        self.workers = [
            worker_cls.options(name=f"ppo-worker-{i}", num_cpus=1).remote(
                config.env, config.num_envs_per_worker, config.rollout_len,
                seed=config.seed * 1000 + i,
            )
            for i in range(config.num_workers)
        ]
        self._update = jax.jit(self._make_update())

    def _make_update(self):
        c = self.config

        def compute_gae(rewards, values, dones, last_value):
            # rewards/values/dones: (T, N); backward scan for advantages
            def step(carry, xs):
                gae = carry
                reward, value, done, next_value = xs
                nonterminal = 1.0 - done
                delta = reward + c.gamma * next_value * nonterminal - value
                gae = delta + c.gamma * c.gae_lambda * nonterminal * gae
                return gae, gae

            next_values = jnp.concatenate([values[1:], last_value[None]], axis=0)
            _, advantages = jax.lax.scan(
                step,
                jnp.zeros_like(last_value),
                (rewards, values, dones.astype(jnp.float32), next_values),
                reverse=True,
            )
            return advantages

        def loss_fn(params, batch):
            logits, values = policy_forward(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp"])
            adv = batch["advantages"]
            adv = (adv - adv.mean()) / (adv.std() + 1e-8)
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - c.clip_eps, 1 + c.clip_eps) * adv
            policy_loss = -jnp.minimum(unclipped, clipped).mean()
            value_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).mean()
            total = (
                policy_loss
                + c.value_coeff * value_loss
                - c.entropy_coeff * entropy
            )
            return total, (policy_loss, value_loss, entropy)

        def update(params, opt_state, key, rollouts):
            # rollouts: stacked (W, T, N, ...) host arrays
            obs = rollouts["obs"]
            W, T, N = obs.shape[0], obs.shape[1], obs.shape[2]
            adv = jax.vmap(compute_gae)(
                rollouts["rewards"], rollouts["values"], rollouts["dones"],
                rollouts["last_value"],
            )  # (W, T, N)
            returns = adv + rollouts["values"]
            flat = {
                "obs": obs.reshape(W * T * N, -1),
                "actions": rollouts["actions"].reshape(-1),
                "logp": rollouts["logp"].reshape(-1),
                "advantages": adv.reshape(-1),
                "returns": returns.reshape(-1),
            }
            B = W * T * N
            mb = B // c.num_minibatches

            def epoch(carry, key_e):
                params, opt_state = carry
                perm = jax.random.permutation(key_e, B)

                def minibatch(carry, idx):
                    params, opt_state = carry
                    take = jax.lax.dynamic_slice_in_dim(perm, idx * mb, mb)
                    batch = {k: v[take] for k, v in flat.items()}
                    (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                        params, batch
                    )
                    updates, opt_state = self.opt.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), aux

                (params, opt_state), aux = jax.lax.scan(
                    minibatch, (params, opt_state), jnp.arange(c.num_minibatches)
                )
                return (params, opt_state), aux

            keys = jax.random.split(key, c.num_epochs)
            (params, opt_state), aux = jax.lax.scan(
                epoch, (params, opt_state), keys
            )
            policy_loss, value_loss, entropy = jax.tree.map(
                lambda x: x[-1, -1], aux
            )
            return params, opt_state, {
                "policy_loss": policy_loss,
                "value_loss": value_loss,
                "entropy": entropy,
            }

        return update

    def train(self) -> Dict[str, Any]:
        """One iteration: sync weights → parallel rollouts → fused update."""
        t0 = time.perf_counter()
        api.get([w.set_weights.remote(self.params) for w in self.workers])
        rollouts = api.get([w.rollout.remote() for w in self.workers])
        stacked = {
            k: np.stack([r[k] for r in rollouts])
            for k in ("obs", "actions", "logp", "values", "rewards", "dones",
                      "last_value")
        }
        episode_returns = np.concatenate(
            [r["episode_returns"] for r in rollouts]
        )
        self._key, sub = jax.random.split(self._key)
        self.params, self.opt_state, losses = self._update(
            self.params, self.opt_state, sub, stacked
        )
        self.iteration += 1
        c = self.config
        steps = c.num_workers * c.num_envs_per_worker * c.rollout_len
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (
                float(episode_returns.mean()) if episode_returns.size else float("nan")
            ),
            "episodes_this_iter": int(episode_returns.size),
            "timesteps_this_iter": steps,
            "time_this_iter_s": time.perf_counter() - t0,
            **{k: float(v) for k, v in losses.items()},
        }

    def stop(self) -> None:
        for w in self.workers:
            try:
                api.kill(w)
            except Exception:
                pass
