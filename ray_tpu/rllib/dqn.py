"""DQN with double-Q targets: the off-policy value-learning family.

Reference parity: rllib's DQN (/root/reference/rllib/algorithms/dqn/ —
EnvRunner actors feeding a replay buffer, a Learner applying TD updates,
periodic target-network sync). TPU inversion: rollout workers are
ray_tpu actors stepping numpy vector envs with a jitted epsilon-greedy
policy; the replay buffer is a flat numpy ring on the driver; each
train() runs K double-DQN minibatch updates fused into ONE jitted
lax.scan program (no per-minibatch Python), and the target params sync
by tree copy every `target_update_freq` updates.

    algo = DQNConfig(env="cartpole", num_workers=2).build()
    for _ in range(40):
        result = algo.train()
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import api
from .env import make_env

Params = Dict[str, Any]


def init_q_network(key: jax.Array, obs_dim: int, num_actions: int,
                   hidden: Tuple[int, ...] = (64, 64)) -> Params:
    params: Params = {}
    sizes = (obs_dim,) + hidden
    for i in range(len(hidden)):
        key, sub = jax.random.split(key)
        params[f"w{i}"] = jax.random.normal(sub, (sizes[i], sizes[i + 1])) * (
            1.0 / np.sqrt(sizes[i])
        )
        params[f"b{i}"] = jnp.zeros(sizes[i + 1])
    key, sub = jax.random.split(key)
    params["w_q"] = jax.random.normal(sub, (hidden[-1], num_actions)) * 0.01
    params["b_q"] = jnp.zeros(num_actions)
    return params


def q_forward(params: Params, obs: jax.Array) -> jax.Array:
    """obs (..., D) -> Q-values (..., A)."""
    x = obs
    i = 0
    while f"w{i}" in params:
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    return x @ params["w_q"] + params["b_q"]


class DQNRolloutWorker:
    """Actor: epsilon-greedy steps of a vector env, returning flat
    transitions for the replay buffer (reference EnvRunner in the
    off-policy stack)."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int, seed: int):
        self.env = make_env(env_name, num_envs)
        self.rollout_len = rollout_len
        self.obs = self.env.reset(seed=seed)
        self._key = jax.random.PRNGKey(seed)
        self._episode_returns = np.zeros(num_envs, np.float32)
        self._finished: List[float] = []
        self._greedy = jax.jit(lambda p, o: jnp.argmax(q_forward(p, o), axis=-1))

    def set_weights(self, params: Params) -> None:
        self.params = params

    def rollout(self, epsilon: float) -> Dict[str, np.ndarray]:
        T, N, D = self.rollout_len, self.env.num_envs, self.env.observation_dim
        obs_buf = np.zeros((T, N, D), np.float32)
        next_buf = np.zeros((T, N, D), np.float32)
        act_buf = np.zeros((T, N), np.int32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        self._finished = []
        rng = np.random.default_rng(int(jax.random.randint(
            self._key, (), 0, 2**31 - 1
        )))
        self._key = jax.random.fold_in(self._key, 1)
        greedy = None
        for t in range(T):
            greedy = np.asarray(self._greedy(self.params, self.obs))
            explore = rng.random(N) < epsilon
            action = np.where(
                explore, rng.integers(0, self.env.num_actions, size=N), greedy
            ).astype(np.int32)
            obs_buf[t] = self.obs
            act_buf[t] = action
            self.obs, rewards, dones = self.env.step(action)
            # NOTE: auto-reset envs return the NEW episode's obs on done;
            # the TD target masks next-state value by (1 - done), so the
            # reset obs never leaks into a target
            next_buf[t] = self.obs
            rew_buf[t] = rewards
            done_buf[t] = dones
            self._episode_returns += rewards
            for i in np.nonzero(dones)[0]:
                self._finished.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
        flat = T * N
        return {
            "obs": obs_buf.reshape(flat, D),
            "actions": act_buf.reshape(flat),
            "rewards": rew_buf.reshape(flat),
            "next_obs": next_buf.reshape(flat, D),
            "dones": done_buf.reshape(flat),
            "episode_returns": np.asarray(self._finished, np.float32),
        }


class ReplayBuffer:
    """Flat numpy ring (reference: replay_buffers/ in rllib utils)."""

    def __init__(self, capacity: int, obs_dim: int):
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.bool_)
        self.size = 0
        self._pos = 0

    def add(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(batch["actions"])
        idx = (self._pos + np.arange(n)) % self.capacity
        self.obs[idx] = batch["obs"]
        self.next_obs[idx] = batch["next_obs"]
        self.actions[idx] = batch["actions"]
        self.rewards[idx] = batch["rewards"]
        self.dones[idx] = batch["dones"]
        self._pos = int((self._pos + n) % self.capacity)
        self.size = min(self.size + n, self.capacity)

    def sample(self, rng: np.random.Generator, n: int) -> Dict[str, np.ndarray]:
        idx = rng.integers(0, self.size, size=n)
        return {
            "obs": self.obs[idx],
            "next_obs": self.next_obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx].astype(np.float32),
        }


@dataclasses.dataclass
class DQNConfig:
    env: str = "cartpole"
    num_workers: int = 2
    num_envs_per_worker: int = 8
    rollout_len: int = 64
    buffer_size: int = 100_000
    batch_size: int = 256
    updates_per_iter: int = 32
    lr: float = 1e-3
    gamma: float = 0.99
    target_update_freq: int = 200  # in updates
    eps_start: float = 1.0
    eps_end: float = 0.05
    eps_decay_iters: int = 30
    learning_starts: int = 1000  # transitions before updates begin
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0

    def build(self) -> "DQN":
        return DQN(self)


class DQN:
    """Algorithm.train() parity for the off-policy family."""

    def __init__(self, config: DQNConfig):
        self.config = config
        env = make_env(config.env, 1)
        self.obs_dim = env.observation_dim
        self.num_actions = env.num_actions
        key = jax.random.PRNGKey(config.seed)
        self.params = init_q_network(key, self.obs_dim, self.num_actions,
                                     config.hidden)
        self.target_params = jax.tree.map(jnp.copy, self.params)
        self.opt = optax.adam(config.lr)
        self.opt_state = self.opt.init(self.params)
        self.buffer = ReplayBuffer(config.buffer_size, self.obs_dim)
        self._rng = np.random.default_rng(config.seed)
        self.iteration = 0
        self.num_updates = 0

        worker_cls = api.remote(DQNRolloutWorker)
        self.workers = [
            worker_cls.options(name=f"dqn-worker-{i}", num_cpus=1).remote(
                config.env, config.num_envs_per_worker, config.rollout_len,
                seed=config.seed * 1000 + i,
            )
            for i in range(config.num_workers)
        ]
        self._update_k = jax.jit(self._make_update())

    def _make_update(self):
        c = self.config

        def td_loss(params, target_params, batch):
            q = q_forward(params, batch["obs"])
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1
            )[:, 0]
            # double DQN: online net picks the action, target net scores it
            next_q_online = q_forward(params, batch["next_obs"])
            next_act = jnp.argmax(next_q_online, axis=-1)
            next_q_target = jnp.take_along_axis(
                q_forward(target_params, batch["next_obs"]),
                next_act[:, None], axis=-1,
            )[:, 0]
            target = batch["rewards"] + c.gamma * (1.0 - batch["dones"]) * (
                jax.lax.stop_gradient(next_q_target)
            )
            td = q_taken - target
            return jnp.mean(td * td), jnp.mean(jnp.abs(td))

        def update_k(params, target_params, opt_state, batches):
            # batches: dict of (K, B, ...) arrays; one scan = K updates
            def body(carry, batch):
                params, opt_state = carry
                (loss, td_abs), grads = jax.value_and_grad(
                    td_loss, has_aux=True
                )(params, target_params, batch)
                updates, opt_state = self.opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, td_abs)

            (params, opt_state), (losses, td_abs) = jax.lax.scan(
                body, (params, opt_state), batches
            )
            return params, opt_state, losses[-1], jnp.mean(td_abs)

        return update_k

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self.iteration / max(1, c.eps_decay_iters))
        return float(c.eps_start + frac * (c.eps_end - c.eps_start))

    def train(self) -> Dict[str, Any]:
        """One iteration: sync → epsilon-greedy rollouts → replay-sampled
        fused double-DQN updates → periodic target sync."""
        c = self.config
        t0 = time.perf_counter()
        eps = self._epsilon()
        api.get([w.set_weights.remote(self.params) for w in self.workers])
        rollouts = api.get([w.rollout.remote(eps) for w in self.workers])
        for r in rollouts:
            self.buffer.add(r)
        episode_returns = np.concatenate(
            [r["episode_returns"] for r in rollouts]
        )
        loss = td_abs = float("nan")
        if self.buffer.size >= max(c.learning_starts, c.batch_size):
            ks = [
                self.buffer.sample(self._rng, c.batch_size)
                for _ in range(c.updates_per_iter)
            ]
            batches = {
                k: jnp.asarray(np.stack([b[k] for b in ks])) for k in ks[0]
            }
            self.params, self.opt_state, loss_j, td_j = self._update_k(
                self.params, self.target_params, self.opt_state, batches
            )
            loss, td_abs = float(loss_j), float(td_j)
            prev = self.num_updates
            self.num_updates += c.updates_per_iter
            if self.num_updates // c.target_update_freq != prev // c.target_update_freq:
                self.target_params = jax.tree.map(jnp.copy, self.params)
        self.iteration += 1
        steps = c.num_workers * c.num_envs_per_worker * c.rollout_len
        return {
            "training_iteration": self.iteration,
            "episode_reward_mean": (
                float(episode_returns.mean())
                if episode_returns.size else float("nan")
            ),
            "episodes_this_iter": int(episode_returns.size),
            "timesteps_this_iter": steps,
            "buffer_size": self.buffer.size,
            "epsilon": eps,
            "td_loss": loss,
            "td_abs": td_abs,
            "num_updates": self.num_updates,
            "time_this_iter_s": time.perf_counter() - t0,
        }

    def stop(self) -> None:
        for w in self.workers:
            try:
                api.kill(w)
            except Exception:
                pass
