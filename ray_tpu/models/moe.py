"""Mixture-of-Experts transformer (Mixtral family) with expert parallelism.

The reference only reaches MoE through vLLM engine internals (SURVEY.md
§2.4: expert parallel "absent as a framework feature"). Here experts are a
first-class mesh axis: expert-stacked weights carry the "expert" logical
axis → `ep` on the mesh, and the GShard-style dense dispatch/combine
einsums give XLA the contraction structure it needs to insert the
all-to-alls over ICI on its own. Routing is top-k with capacity: dropped
tokens (over capacity) fall through on the residual path, the standard
Switch/GShard behavior; a load-balancing aux loss keeps experts busy.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import rope_frequencies, swiglu
from .transformer import (
    Params,
    TransformerConfig,
    _norm,
    attention_sublayer,
    init_params as _dense_init,
    logical_axes as _dense_axes,
)


@dataclasses.dataclass(frozen=True)
class MoEConfig(TransformerConfig):
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 2.0
    router_aux_coeff: float = 0.01


def mixtral_8x7b() -> MoEConfig:
    """Mixtral 8x7B — BASELINE config 3 (expert parallelism)."""
    return MoEConfig(
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq=8192,
        pos_emb="rope",
        norm="rmsnorm",
        act="swiglu",
        use_bias=False,
        tie_embeddings=False,
        rope_theta=1e6,
        remat=True,
        n_experts=8,
        top_k=2,
    )


def moe_tiny() -> MoEConfig:
    """4-layer 4-expert toy for CI (divisible by ep=2/tp=2 test meshes)."""
    return MoEConfig(
        vocab_size=256,
        d_model=64,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq=128,
        pos_emb="rope",
        norm="rmsnorm",
        act="swiglu",
        use_bias=False,
        tie_embeddings=False,
        dtype=jnp.float32,
        n_experts=4,
        top_k=2,
    )


# ----------------------------------------------------------------------- init


def init_params(config: MoEConfig, key: jax.Array) -> Params:
    """Dense skeleton + per-expert MLP stacks (L, E_exp, ...)."""
    base = _dense_init(config, key)
    blocks = base["blocks"]
    for name in ("w_up", "w_down", "w_gate", "b_up", "b_down"):
        blocks.pop(name, None)
    c = config
    pd = c.param_dtype
    std = 0.02
    res_std = std / math.sqrt(2 * c.n_layers)
    keys = jax.random.split(jax.random.fold_in(key, 99), 4)
    L, E = c.n_layers, c.n_experts
    blocks["router"] = (std * jax.random.normal(keys[0], (L, c.d_model, E))).astype(pd)
    blocks["we_gate"] = (std * jax.random.normal(keys[1], (L, E, c.d_model, c.d_ff))).astype(pd)
    blocks["we_up"] = (std * jax.random.normal(keys[2], (L, E, c.d_model, c.d_ff))).astype(pd)
    blocks["we_down"] = (res_std * jax.random.normal(keys[3], (L, E, c.d_ff, c.d_model))).astype(pd)
    return base


def logical_axes(config: MoEConfig) -> Params:
    axes = _dense_axes(config)
    blocks = axes["blocks"]
    for name in ("w_up", "w_down", "w_gate", "b_up", "b_down"):
        blocks.pop(name, None)
    blocks["router"] = ("layers", "embed", None)
    blocks["we_gate"] = ("layers", "expert", "embed", "mlp")
    blocks["we_up"] = ("layers", "expert", "embed", "mlp")
    blocks["we_down"] = ("layers", "expert", "mlp", "embed")
    return axes


# -------------------------------------------------------------------- routing


def topk_dispatch(
    probs: jax.Array, top_k: int, capacity: int
) -> Tuple[jax.Array, jax.Array]:
    """GShard dense dispatch. probs (B, S, E) → dispatch (B,S,E,C) {0,1},
    combine (B,S,E,C) gate-weighted; tokens over capacity are dropped."""
    num_experts = probs.shape[-1]
    weights, idx = jax.lax.top_k(probs, top_k)  # (B,S,k)
    weights = weights / (jnp.sum(weights, -1, keepdims=True) + 1e-9)
    onehot = jax.nn.one_hot(idx, num_experts, dtype=probs.dtype)  # (B,S,k,E)
    b, s, k, e = onehot.shape
    # queue position of each (token, choice) within its expert, in (S·k) order
    flat = onehot.transpose(0, 2, 1, 3).reshape(b, k * s, e)
    pos_flat = jnp.cumsum(flat, axis=1) - flat
    pos = pos_flat.reshape(b, k, s, e).transpose(0, 2, 1, 3)  # (B,S,k,E)
    pos = pos.astype(jnp.int32)
    keep = (pos < capacity).astype(probs.dtype) * onehot
    pos_onehot = jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1), capacity, dtype=probs.dtype
    )  # (B,S,k,E,C)
    dispatch = jnp.einsum("bske,bskec->bsec", keep, pos_onehot)
    combine = jnp.einsum("bsk,bske,bskec->bsec", weights, keep, pos_onehot)
    return dispatch, combine


def load_balancing_loss(probs: jax.Array, dispatch: jax.Array) -> jax.Array:
    """Switch aux loss: E · Σ_e (token frac to e · mean router prob of e)."""
    num_experts = probs.shape[-1]
    token_frac = jnp.mean(jnp.sum(dispatch, axis=-1), axis=(0, 1))  # (E,)
    prob_mean = jnp.mean(probs, axis=(0, 1))
    return num_experts * jnp.sum(token_frac * prob_mean)


def moe_mlp_sublayer(
    x: jax.Array, lp: Params, config: MoEConfig
) -> Tuple[jax.Array, jax.Array]:
    """Pre-norm MoE FFN + residual; returns (out, aux_loss)."""
    c = config
    dt = c.dtype
    h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
    b, s, _ = h.shape
    capacity = max(1, int(c.capacity_factor * c.top_k * s / c.n_experts))

    router_logits = jnp.einsum(
        "bsm,me->bse", h.astype(jnp.float32), lp["router"].astype(jnp.float32)
    )
    probs = jax.nn.softmax(router_logits, axis=-1)
    dispatch, combine = topk_dispatch(probs, c.top_k, capacity)
    aux = load_balancing_loss(probs, dispatch)

    # dispatch: (B,S,E,C) × (B,S,M) → (E,B,C,M); XLA turns the e-sharded
    # contraction into the all-to-all over the ep axis
    expert_in = jnp.einsum("bsec,bsm->ebcm", dispatch.astype(dt), h)
    gate = jnp.einsum("ebcm,emf->ebcf", expert_in, lp["we_gate"].astype(dt))
    up = jnp.einsum("ebcm,emf->ebcf", expert_in, lp["we_up"].astype(dt))
    act = swiglu(gate, up)
    expert_out = jnp.einsum("ebcf,efm->ebcm", act, lp["we_down"].astype(dt))
    out = jnp.einsum("ebcm,bsec->bsm", expert_out, combine.astype(dt))
    return x + out, aux


# -------------------------------------------------------------------- forward


def forward(
    params: Params,
    tokens: jax.Array,
    config: MoEConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(B, S) → (logits (B,S,V), total aux loss)."""
    c = config
    dt = c.dtype
    _, s = tokens.shape
    x = params["wte"].astype(dt)[tokens]
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[None, :s]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    def block_fn(carry, lp):
        x = attention_sublayer(carry, lp, c, rope_tables, positions)
        x, aux = moe_mlp_sublayer(x, lp, c)
        return x, aux

    if c.remat:
        block_fn = jax.checkpoint(block_fn)
    x, aux_per_layer = jax.lax.scan(block_fn, x, params["blocks"])

    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head", None)
    if head is None:
        head = params["wte"].T
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(dt))
    return logits, jnp.sum(aux_per_layer)


def moe_loss(
    params: Params, tokens: jax.Array, config: MoEConfig
) -> Tuple[jax.Array, Any]:
    """Next-token CE + router aux (for make_train_step-style factories)."""
    from ..ops import cross_entropy_loss

    logits, aux = forward(params, tokens[:, :-1], config)
    ce, ntok = cross_entropy_loss(logits, tokens[:, 1:])
    return ce + config.router_aux_coeff * aux, (ce, aux, ntok)
