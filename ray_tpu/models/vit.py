"""ViT image encoder + CLIP dual-tower (BASELINE config 4).

Reference parity: multimodal pipelines ride torch models under Ray Data/
Train in the reference; here ViT/CLIP are native. The encoder reuses the
decoder's block stack (transformer.attention_sublayer with causal=False) —
patchify is a reshape + one einsum, so the whole image tower is matmuls on
the MXU; there is no conv primitive to special-case.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import cross_entropy_loss
from .transformer import (
    Params,
    TransformerConfig,
    _block,
    _norm,
    init_params as _dense_init,
    logical_axes as _dense_axes,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    d_ff: int = 3072
    out_dim: int = 1000  # classes (classifier) or projection dim (CLIP)
    pool: str = "cls"  # "cls" | "mean"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def encoder_config(self) -> TransformerConfig:
        return TransformerConfig(
            vocab_size=1,  # unused: the tower has no token embedding
            d_model=self.d_model,
            n_layers=self.n_layers,
            n_heads=self.n_heads,
            d_ff=self.d_ff,
            max_seq=self.num_patches + 1,
            pos_emb="learned",
            norm="layernorm",
            act="gelu",
            use_bias=True,
            causal=False,
            dtype=self.dtype,
            param_dtype=self.param_dtype,
            remat=self.remat,
        )

    def replace(self, **kw) -> "ViTConfig":
        return dataclasses.replace(self, **kw)


def vit_b16() -> ViTConfig:
    return ViTConfig()


def vit_l16() -> ViTConfig:
    return ViTConfig(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)


def vit_tiny() -> ViTConfig:
    return ViTConfig(
        image_size=32,
        patch_size=8,
        d_model=64,
        n_layers=2,
        n_heads=4,
        d_ff=128,
        out_dim=10,
        dtype=jnp.float32,
    )


# ----------------------------------------------------------------------- init


def init_params(config: ViTConfig, key: jax.Array) -> Params:
    c = config
    enc = c.encoder_config
    base = _dense_init(enc, key)
    pd = c.param_dtype
    patch_dim = c.patch_size * c.patch_size * c.channels
    keys = jax.random.split(jax.random.fold_in(key, 7), 4)
    return {
        "patch_proj": (
            (1.0 / math.sqrt(patch_dim)) * jax.random.normal(keys[0], (patch_dim, c.d_model))
        ).astype(pd),
        "patch_bias": jnp.zeros((c.d_model,), pd),
        "cls": (0.02 * jax.random.normal(keys[1], (1, 1, c.d_model))).astype(pd),
        "pos": (0.02 * jax.random.normal(keys[2], (c.num_patches + 1, c.d_model))).astype(pd),
        "blocks": base["blocks"],
        "lnf_scale": base["lnf_scale"],
        "lnf_bias": base["lnf_bias"],
        "head": (0.02 * jax.random.normal(keys[3], (c.d_model, c.out_dim))).astype(pd),
        "head_bias": jnp.zeros((c.out_dim,), pd),
    }


def logical_axes(config: ViTConfig) -> Params:
    base = _dense_axes(config.encoder_config)
    return {
        "patch_proj": (None, "embed"),
        "patch_bias": (None,),
        "cls": (None, None, "embed"),
        "pos": (None, "embed"),
        "blocks": base["blocks"],
        "lnf_scale": (None,),
        "lnf_bias": (None,),
        "head": ("embed", None),
        "head_bias": (None,),
    }


# -------------------------------------------------------------------- forward


def patchify(images: jax.Array, patch: int) -> jax.Array:
    """(B, H, W, C) → (B, N, patch·patch·C), row-major patches."""
    b, h, w, c = images.shape
    gh, gw = h // patch, w // patch
    x = images.reshape(b, gh, patch, gw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * c)


def forward(
    params: Params, images: jax.Array, config: ViTConfig
) -> jax.Array:
    """(B, H, W, C) float images → (B, out_dim)."""
    c = config
    enc = c.encoder_config
    dt = c.dtype
    patches = patchify(images.astype(dt), c.patch_size)
    x = jnp.einsum("bnp,pe->bne", patches, params["patch_proj"].astype(dt))
    x = x + params["patch_bias"].astype(dt)
    cls = jnp.broadcast_to(params["cls"].astype(dt), (x.shape[0], 1, c.d_model))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + params["pos"].astype(dt)[None]

    def block_fn(carry, lp):
        return _block(carry, lp, enc, None, None), None

    if c.remat:
        block_fn = jax.checkpoint(block_fn)
    x, _ = jax.lax.scan(block_fn, x, params["blocks"])
    x = _norm(x, params["lnf_scale"], params["lnf_bias"], "layernorm")
    pooled = x[:, 0] if c.pool == "cls" else jnp.mean(x[:, 1:], axis=1)
    return jnp.einsum("be,eo->bo", pooled, params["head"].astype(dt)) + params[
        "head_bias"
    ].astype(dt)


# ----------------------------------------------------------------------- CLIP


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    vision: ViTConfig = dataclasses.field(default_factory=vit_b16)
    text: TransformerConfig = dataclasses.field(
        default_factory=lambda: TransformerConfig(
            vocab_size=49408,
            d_model=512,
            n_layers=12,
            n_heads=8,
            d_ff=2048,
            max_seq=77,
            pos_emb="learned",
            norm="layernorm",
            act="gelu",
            causal=True,
            tie_embeddings=False,
        )
    )
    proj_dim: int = 512
    init_logit_scale: float = math.log(1 / 0.07)


def clip_tiny() -> CLIPConfig:
    return CLIPConfig(
        vision=vit_tiny().replace(out_dim=32),
        text=TransformerConfig(
            vocab_size=256,
            d_model=64,
            n_layers=2,
            n_heads=4,
            d_ff=128,
            max_seq=16,
            pos_emb="learned",
            norm="layernorm",
            act="gelu",
            causal=True,
            tie_embeddings=False,
            dtype=jnp.float32,
        ),
        proj_dim=32,
    )


def init_clip_params(config: CLIPConfig, key: jax.Array) -> Params:
    kv, kt, kp = jax.random.split(key, 3)
    vision_cfg = config.vision.replace(out_dim=config.proj_dim)
    text_params = _dense_init(config.text, kt)
    text_params.pop("lm_head", None)
    return {
        "vision": init_params(vision_cfg, kv),
        "text": text_params,
        "text_proj": (
            0.02 * jax.random.normal(kp, (config.text.d_model, config.proj_dim))
        ).astype(config.text.param_dtype),
        "logit_scale": jnp.asarray(config.init_logit_scale, jnp.float32),
    }


def _text_features(
    params: Params, tokens: jax.Array, lengths: jax.Array, config: CLIPConfig
) -> jax.Array:
    """Causal text tower pooled at the last valid token."""
    from .transformer import forward as _text_forward  # reuse trunk via logits? no:

    c = config.text
    dt = c.dtype
    _, s = tokens.shape
    x = params["wte"].astype(dt)[tokens]
    x = x + params["wpe"].astype(dt)[None, :s]

    def block_fn(carry, lp):
        return _block(carry, lp, c, None, None), None

    x, _ = jax.lax.scan(block_fn, x, params["blocks"])
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    return jnp.take_along_axis(x, (lengths - 1)[:, None, None], axis=1)[:, 0]


def clip_forward(
    params: Params,
    images: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    config: CLIPConfig,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """→ (image_emb (B,P), text_emb (B,P), logit_scale) — L2-normalized."""
    vision_cfg = config.vision.replace(out_dim=config.proj_dim)
    img = forward(params["vision"], images, vision_cfg).astype(jnp.float32)
    txt = _text_features(params["text"], tokens, lengths, config).astype(jnp.float32)
    txt = txt @ params["text_proj"].astype(jnp.float32)
    img = img / (jnp.linalg.norm(img, axis=-1, keepdims=True) + 1e-8)
    txt = txt / (jnp.linalg.norm(txt, axis=-1, keepdims=True) + 1e-8)
    scale = jnp.exp(jnp.clip(params["logit_scale"], -10.0, math.log(100.0)))
    return img, txt, scale


def clip_loss(
    params: Params,
    images: jax.Array,
    tokens: jax.Array,
    lengths: jax.Array,
    config: CLIPConfig,
) -> jax.Array:
    """Symmetric InfoNCE over the batch."""
    img, txt, scale = clip_forward(params, images, tokens, lengths, config)
    logits = scale * img @ txt.T  # (B, B)
    labels = jnp.arange(logits.shape[0])
    li, _ = cross_entropy_loss(logits, labels)
    lt, _ = cross_entropy_loss(logits.T, labels)
    return 0.5 * (li + lt)
