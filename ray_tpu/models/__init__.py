"""ray_tpu.models — flagship model families, TPU-shaped.

Decoder-only LMs (GPT-2, Llama) now; MoE (Mixtral) and ViT/CLIP follow the
same pattern: pytree params + logical-axis tree + scan-stacked layers.
"""

from .configs import PRESETS, get_config  # noqa: F401
from .transformer import (  # noqa: F401
    TransformerConfig,
    count_params,
    decode_step,
    forward,
    init_cache,
    init_params,
    logical_axes,
    prefill,
)
