"""ray_tpu.models — flagship model families, TPU-shaped.

Decoder-only LMs (GPT-2, Llama) now; MoE (Mixtral) and ViT/CLIP follow the
same pattern: pytree params + logical-axis tree + scan-stacked layers.
"""

from .configs import PRESETS, get_config  # noqa: F401
from .moe import (  # noqa: F401
    MoEConfig,
    mixtral_8x7b,
    moe_loss,
    moe_tiny,
)
from .transformer import (  # noqa: F401
    TransformerConfig,
    count_params,
    decode_step,
    forward,
    init_cache,
    init_params,
    logical_axes,
    prefill,
)
from .vit import (  # noqa: F401
    CLIPConfig,
    ViTConfig,
    clip_forward,
    clip_loss,
    clip_tiny,
    init_clip_params,
    vit_b16,
    vit_l16,
    vit_tiny,
)
