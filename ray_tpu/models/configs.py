"""Model family presets (BASELINE.md target configs).

Sizes follow the published architectures; `*_tiny` variants are shrunk for
CI on the virtual CPU mesh (head counts divisible by tp=2, dims by fsdp=2).
"""

from __future__ import annotations

import jax.numpy as jnp

from .transformer import TransformerConfig


def gpt2_small() -> TransformerConfig:
    """GPT-2 124M — BASELINE config 1 (single chip)."""
    return TransformerConfig(
        vocab_size=50257,
        d_model=768,
        n_layers=12,
        n_heads=12,
        d_ff=3072,
        max_seq=1024,
        pos_emb="learned",
        norm="layernorm",
        act="gelu",
        use_bias=True,
        tie_embeddings=True,
    )


def gpt2_medium() -> TransformerConfig:
    return gpt2_small().replace(d_model=1024, n_layers=24, n_heads=16, d_ff=4096)


def gpt2_xl() -> TransformerConfig:
    return gpt2_small().replace(d_model=1600, n_layers=48, n_heads=25, d_ff=6400)


def llama3_8b() -> TransformerConfig:
    """Llama-3-8B — BASELINE config 2 (FSDP on a slice)."""
    return TransformerConfig(
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq=8192,
        pos_emb="rope",
        norm="rmsnorm",
        act="swiglu",
        use_bias=False,
        tie_embeddings=False,
        rope_theta=500000.0,
        remat=True,
    )


def llama3_70b() -> TransformerConfig:
    return llama3_8b().replace(
        d_model=8192, n_layers=80, n_heads=64, n_kv_heads=8, d_ff=28672
    )


def gpt2_tiny() -> TransformerConfig:
    """4-layer GPT-2 for tests (runs on the 8-device CPU mesh)."""
    return TransformerConfig(
        vocab_size=256,
        d_model=64,
        n_layers=4,
        n_heads=4,
        d_ff=128,
        max_seq=128,
        pos_emb="learned",
        norm="layernorm",
        act="gelu",
        use_bias=True,
        tie_embeddings=True,
        dtype=jnp.float32,
    )


def llama_tiny() -> TransformerConfig:
    """4-layer Llama-style (rope/rmsnorm/swiglu/GQA) for tests."""
    return TransformerConfig(
        vocab_size=256,
        d_model=64,
        n_layers=4,
        n_heads=4,
        n_kv_heads=2,
        d_ff=128,
        max_seq=128,
        pos_emb="rope",
        norm="rmsnorm",
        act="swiglu",
        use_bias=False,
        tie_embeddings=False,
        dtype=jnp.float32,
    )


PRESETS = {
    "gpt2-small": gpt2_small,
    "gpt2-medium": gpt2_medium,
    "gpt2-xl": gpt2_xl,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "gpt2-tiny": gpt2_tiny,
    "llama-tiny": llama_tiny,
}


def get_config(name: str) -> TransformerConfig:
    try:
        return PRESETS[name]()
    except KeyError:
        raise ValueError(f"unknown preset {name!r}; have {sorted(PRESETS)}") from None
