"""Decoder-only transformer covering the GPT-2 and Llama families.

The reference serves these architectures through vLLM/torch model zoos
(/root/reference/python/ray/llm/_internal/serve/deployments/llm/vllm/
vllm_engine.py:254); training rides user torch code under Ray Train
(/root/reference/python/ray/train/torch/config.py:153). Here the models are
first-class and TPU-shaped:

- parameters are a plain pytree with a parallel tree of *logical axis names*
  (ray_tpu.parallel.sharding) — DP/FSDP/TP/SP/EP is a rule-table change,
  never a model change;
- layers are stacked on a leading axis and executed with `lax.scan`, so
  compile time is O(1) in depth and remat is one `jax.checkpoint`;
- attention dispatches to the Pallas flash kernel on TPU (ray_tpu.ops);
- one config struct spans GPT-2 (learned pos, layernorm, gelu, tied head)
  and Llama (rope, rmsnorm, swiglu, GQA, untied) — family presets live in
  ray_tpu.models.configs.

Shapes: tokens (B, S) int32 → logits (B, S, V). Decode path carries a dense
KV cache (L, B, Hkv, max_seq, Dh) with per-example write positions, the
substrate for continuous batching in ray_tpu.serve.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import (
    apply_rope,
    flash_attention,
    gelu,
    layernorm,
    rmsnorm,
    rope_frequencies,
    swiglu,
)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    n_kv_heads: Optional[int] = None  # None → n_heads (MHA); < n_heads → GQA
    d_ff: int = 3072
    max_seq: int = 1024
    pos_emb: str = "learned"  # "learned" (GPT-2) | "rope" (Llama)
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    act: str = "gelu"  # "gelu" | "swiglu"
    use_bias: bool = True
    tie_embeddings: bool = True
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = False
    attn_impl: Optional[str] = None  # None → pallas on TPU, xla elsewhere
    causal: bool = True  # False → bidirectional encoder (ViT, CLIP text off)
    fused_qkv: bool = False  # single [E, (Hq+2Hkv)·Dh] projection matmul
    scan_unroll: int = 1  # lax.scan unroll for the layer stack

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads or self.n_heads

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def replace(self, **kw) -> "TransformerConfig":
        return dataclasses.replace(self, **kw)


# ---------------------------------------------------------------------- init


def init_params(config: TransformerConfig, key: jax.Array) -> Params:
    """GPT-2-style init: N(0, 0.02), residual-out projections scaled by
    1/sqrt(2L). Block params are stacked on a leading layer axis for scan."""
    c = config
    pd = c.param_dtype
    dh = c.head_dim
    keys = jax.random.split(key, 16)
    std = 0.02
    res_std = std / math.sqrt(2 * c.n_layers)

    def normal(k, shape, s=std):
        return (s * jax.random.normal(k, shape)).astype(pd)

    L = c.n_layers
    blocks: Params = {
        "ln1_scale": jnp.ones((L, c.d_model), pd),
        "wq": normal(keys[0], (L, c.d_model, c.n_heads, dh)),
        "wk": normal(keys[1], (L, c.d_model, c.kv_heads, dh)),
        "wv": normal(keys[2], (L, c.d_model, c.kv_heads, dh)),
        "wo": normal(keys[3], (L, c.n_heads, dh, c.d_model), res_std),
        "ln2_scale": jnp.ones((L, c.d_model), pd),
        "w_up": normal(keys[4], (L, c.d_model, c.d_ff)),
        "w_down": normal(keys[5], (L, c.d_ff, c.d_model), res_std),
    }
    if c.act == "swiglu":
        blocks["w_gate"] = normal(keys[6], (L, c.d_model, c.d_ff))
    if c.norm == "layernorm":
        blocks["ln1_bias"] = jnp.zeros((L, c.d_model), pd)
        blocks["ln2_bias"] = jnp.zeros((L, c.d_model), pd)
    if c.use_bias:
        blocks["bq"] = jnp.zeros((L, c.n_heads, dh), pd)
        blocks["bk"] = jnp.zeros((L, c.kv_heads, dh), pd)
        blocks["bv"] = jnp.zeros((L, c.kv_heads, dh), pd)
        blocks["bo"] = jnp.zeros((L, c.d_model), pd)
        blocks["b_up"] = jnp.zeros((L, c.d_ff), pd)
        blocks["b_down"] = jnp.zeros((L, c.d_model), pd)

    params: Params = {
        "wte": normal(keys[7], (c.vocab_size, c.d_model)),
        "blocks": blocks,
        "lnf_scale": jnp.ones((c.d_model,), pd),
    }
    if c.pos_emb == "learned":
        params["wpe"] = normal(keys[8], (c.max_seq, c.d_model), 0.01)
    if c.norm == "layernorm":
        params["lnf_bias"] = jnp.zeros((c.d_model,), pd)
    if not c.tie_embeddings:
        params["lm_head"] = normal(keys[9], (c.d_model, c.vocab_size))
    return params


def logical_axes(config: TransformerConfig) -> Params:
    """Logical-axis tree mirroring init_params output (sharding rule input)."""
    c = config
    blocks: Params = {
        "ln1_scale": ("layers", None),
        "wq": ("layers", "embed", "heads", "head_dim"),
        "wk": ("layers", "embed", "kv_heads", "head_dim"),
        "wv": ("layers", "embed", "kv_heads", "head_dim"),
        "wo": ("layers", "heads", "head_dim", "embed"),
        "ln2_scale": ("layers", None),
        "w_up": ("layers", "embed", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
    }
    if c.act == "swiglu":
        blocks["w_gate"] = ("layers", "embed", "mlp")
    if c.norm == "layernorm":
        blocks["ln1_bias"] = ("layers", None)
        blocks["ln2_bias"] = ("layers", None)
    if c.use_bias:
        blocks["bq"] = ("layers", "heads", "head_dim")
        blocks["bk"] = ("layers", "kv_heads", "head_dim")
        blocks["bv"] = ("layers", "kv_heads", "head_dim")
        blocks["bo"] = ("layers", None)
        blocks["b_up"] = ("layers", "mlp")
        blocks["b_down"] = ("layers", None)
    axes: Params = {
        "wte": ("vocab", "embed"),
        "blocks": blocks,
        "lnf_scale": (None,),
    }
    if c.pos_emb == "learned":
        axes["wpe"] = (None, "embed")
    if c.norm == "layernorm":
        axes["lnf_bias"] = (None,)
    if not c.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def count_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))


# -------------------------------------------------------------------- forward


def _norm(x, scale, bias, kind):
    if kind == "rmsnorm":
        return rmsnorm(x, scale)
    return layernorm(x, scale, bias)


def attention_sublayer(
    x: jax.Array,
    lp: Params,
    config: TransformerConfig,
    rope_tables: Optional[Tuple[jax.Array, jax.Array]],
    positions: Optional[jax.Array],
) -> jax.Array:
    """Pre-norm causal self-attention + residual on (B, S, E)."""
    c = config
    dt = c.dtype
    h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
    if c.fused_qkv:
        # one wide matmul beats three narrow ones on the MXU; the concat of
        # the (static) weights folds into the kernel at compile time
        wqkv = jnp.concatenate(
            [
                lp["wq"].reshape(c.d_model, -1),
                lp["wk"].reshape(c.d_model, -1),
                lp["wv"].reshape(c.d_model, -1),
            ],
            axis=-1,
        ).astype(dt)
        qkv = jnp.einsum("bse,ef->bsf", h, wqkv)
        nq = c.n_heads * c.head_dim
        nkv = c.kv_heads * c.head_dim
        b_, s_, _ = qkv.shape
        q = qkv[..., :nq].reshape(b_, s_, c.n_heads, c.head_dim).transpose(0, 2, 1, 3)
        k = qkv[..., nq : nq + nkv].reshape(b_, s_, c.kv_heads, c.head_dim).transpose(0, 2, 1, 3)
        v = qkv[..., nq + nkv :].reshape(b_, s_, c.kv_heads, c.head_dim).transpose(0, 2, 1, 3)
    else:
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
    if c.use_bias:
        q = q + lp["bq"].astype(dt)[None, :, None, :]
        k = k + lp["bk"].astype(dt)[None, :, None, :]
        v = v + lp["bv"].astype(dt)[None, :, None, :]
    if rope_tables is not None:
        cos, sin = rope_tables
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)
    attn = flash_attention(q, k, v, causal=c.causal, implementation=c.attn_impl)
    out = jnp.einsum("bhsd,hde->bse", attn, lp["wo"].astype(dt))
    if c.use_bias:
        out = out + lp["bo"].astype(dt)
    return x + out


def mlp_sublayer(x: jax.Array, lp: Params, config: TransformerConfig) -> jax.Array:
    """Pre-norm dense MLP + residual on (B, S, E)."""
    c = config
    dt = c.dtype
    h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
    up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
    if c.use_bias:
        up = up + lp["b_up"].astype(dt)
    if c.act == "swiglu":
        gate = jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt))
        act = swiglu(gate, up)
    else:
        act = gelu(up)
    down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
    if c.use_bias:
        down = down + lp["b_down"].astype(dt)
    return x + down


def _block(
    x: jax.Array,
    lp: Params,
    config: TransformerConfig,
    rope_tables: Optional[Tuple[jax.Array, jax.Array]],
    positions: Optional[jax.Array],
) -> jax.Array:
    """One transformer block on (B, S, E) activations (training/prefill)."""
    x = attention_sublayer(x, lp, config, rope_tables, positions)
    return mlp_sublayer(x, lp, config)


def forward_hidden(
    params: Params,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Forward up to (but excluding) the LM head: (B, S) → (B, S, E).
    The chunked fused-loss path (ops/losses.py
    fused_linear_cross_entropy) consumes this so the full logits tensor
    never materializes."""
    c = config
    dt = c.dtype
    _, s = tokens.shape
    x = params["wte"].astype(dt)[tokens]
    if c.pos_emb == "learned":
        if positions is None:
            x = x + params["wpe"].astype(dt)[None, :s]
        else:
            x = x + params["wpe"].astype(dt)[positions]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    def block_fn(carry, lp):
        return _block(carry, lp, c, rope_tables, positions), None

    if c.remat:
        block_fn = jax.checkpoint(block_fn)
    x, _ = jax.lax.scan(block_fn, x, params["blocks"], unroll=c.scan_unroll)

    return _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)


def lm_head_weights(params: Params, config: TransformerConfig) -> jax.Array:
    """(E, V) output projection — tied to wte unless a separate lm_head
    exists."""
    head = params.get("lm_head", None)
    if head is None:
        head = params["wte"].T
    return head.astype(config.dtype)


def forward(
    params: Params,
    tokens: jax.Array,
    config: TransformerConfig,
    *,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """Full-sequence forward (training / prefill): (B, S) → (B, S, V)."""
    x = forward_hidden(params, tokens, config, positions=positions)
    return jnp.einsum("bse,ev->bsv", x, lm_head_weights(params, config))


# --------------------------------------------------------------------- decode


def init_cache(
    config: TransformerConfig, batch: int, max_seq: Optional[int] = None
) -> Params:
    """Dense KV cache: k/v of shape (L, B, Hkv, S, Dh) in the compute dtype."""
    c = config
    s = max_seq or c.max_seq
    shape = (c.n_layers, batch, c.kv_heads, s, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _decode_attention(q, k_cache, v_cache, lengths):
    """Single-step attention against the cache. q (B, H, 1, Dh); cache
    (B, Hkv, S, Dh); lengths (B,) = #valid cache slots per example."""
    b, hq, _, dh = q.shape
    hkv = k_cache.shape[1]
    if hq != hkv:
        k_cache = jnp.repeat(k_cache, hq // hkv, axis=1)
        v_cache = jnp.repeat(v_cache, hq // hkv, axis=1)
    logits = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k_cache, preferred_element_type=jnp.float32
    ) / math.sqrt(dh)
    mask = jnp.arange(k_cache.shape[2])[None, None, None, :] < lengths[:, None, None, None]
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v_cache.dtype), v_cache)


def decode_step(
    params: Params,
    cache: Params,
    tokens: jax.Array,
    positions: jax.Array,
    config: TransformerConfig,
) -> Tuple[jax.Array, Params]:
    """One autoregressive step for continuous batching.

    tokens (B,) int32; positions (B,) int32 — per-example write slot (also
    the rope position). Returns (logits (B, V), updated cache). Examples at
    different sequence positions coexist in one batch: each writes its own
    cache row at its own position.
    """
    c = config
    dt = c.dtype
    b = tokens.shape[0]
    x = params["wte"].astype(dt)[tokens][:, None, :]  # (B, 1, E)
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[positions][:, None, :]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    lengths = positions + 1

    def write_at(cache_bhsd, new_bh1d):
        # scatter each example's new row at its own position
        def one(cache_hsd, new_h1d, pos):
            return jax.lax.dynamic_update_slice(cache_hsd, new_h1d, (0, pos, 0))

        return jax.vmap(one)(cache_bhsd, new_bh1d, positions)

    def block_fn(x, scanned):
        lp, k_cache, v_cache = scanned
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            pos2d = positions[:, None]
            q = apply_rope(q, cos, sin, pos2d)
            k = apply_rope(k, cos, sin, pos2d)
        k_cache = write_at(k_cache, k.astype(c.dtype))
        v_cache = write_at(v_cache, v.astype(c.dtype))
        attn = _decode_attention(q, k_cache, v_cache, lengths)
        out = jnp.einsum("bhsd,hde->bse", attn.astype(dt), lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            act = swiglu(jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt)), up)
        else:
            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        return x + down, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(block_fn, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head", None)
    if head is None:
        head = params["wte"].T
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(dt))[:, 0]
    return logits, {"k": new_k, "v": new_v}


def prefill(
    params: Params,
    tokens: jax.Array,
    lengths: jax.Array,
    cache: Params,
    config: TransformerConfig,
) -> Tuple[jax.Array, Params]:
    """Prompt ingestion: run the full-sequence path once, stash K/V into the
    cache, return last-valid-token logits. tokens (B, S) right-padded;
    lengths (B,) true prompt lengths."""
    c = config
    dt = c.dtype
    b, s = tokens.shape
    x = params["wte"].astype(dt)[tokens]
    if c.pos_emb == "learned":
        x = x + params["wpe"].astype(dt)[None, :s]
        rope_tables = None
    else:
        rope_tables = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    def block_fn(x, scanned):
        lp, k_cache, v_cache = scanned
        h = _norm(x, lp["ln1_scale"], lp.get("ln1_bias"), c.norm)
        q = jnp.einsum("bse,ehd->bhsd", h, lp["wq"].astype(dt))
        k = jnp.einsum("bse,ehd->bhsd", h, lp["wk"].astype(dt))
        v = jnp.einsum("bse,ehd->bhsd", h, lp["wv"].astype(dt))
        if c.use_bias:
            q = q + lp["bq"].astype(dt)[None, :, None, :]
            k = k + lp["bk"].astype(dt)[None, :, None, :]
            v = v + lp["bv"].astype(dt)[None, :, None, :]
        if rope_tables is not None:
            cos, sin = rope_tables
            q = apply_rope(q, cos, sin, None)
            k = apply_rope(k, cos, sin, None)
        # write the first S slots of the cache; padded tail is masked by
        # `lengths` at decode time
        k_cache = jax.lax.dynamic_update_slice(
            k_cache, k.astype(c.dtype), (0, 0, 0, 0)
        )
        v_cache = jax.lax.dynamic_update_slice(
            v_cache, v.astype(c.dtype), (0, 0, 0, 0)
        )
        attn = flash_attention(q, k, v, causal=True, implementation=c.attn_impl)
        out = jnp.einsum("bhsd,hde->bse", attn, lp["wo"].astype(dt))
        if c.use_bias:
            out = out + lp["bo"].astype(dt)
        x = x + out
        h = _norm(x, lp["ln2_scale"], lp.get("ln2_bias"), c.norm)
        up = jnp.einsum("bse,ef->bsf", h, lp["w_up"].astype(dt))
        if c.use_bias:
            up = up + lp["b_up"].astype(dt)
        if c.act == "swiglu":
            act = swiglu(jnp.einsum("bse,ef->bsf", h, lp["w_gate"].astype(dt)), up)
        else:
            act = gelu(up)
        down = jnp.einsum("bsf,fe->bse", act, lp["w_down"].astype(dt))
        if c.use_bias:
            down = down + lp["b_down"].astype(dt)
        return x + down, (k_cache, v_cache)

    x, (new_k, new_v) = jax.lax.scan(
        block_fn, x, (params["blocks"], cache["k"], cache["v"])
    )
    x = _norm(x, params["lnf_scale"], params.get("lnf_bias"), c.norm)
    head = params.get("lm_head", None)
    if head is None:
        head = params["wte"].T
    logits = jnp.einsum("bse,ev->bsv", x, head.astype(dt))
    last = jnp.take_along_axis(logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
    return last, {"k": new_k, "v": new_v}
