"""Multi-process cluster harness for tests and local experiments.

Reference parity: `ray.cluster_utils.Cluster`
(/root/reference/python/ray/cluster_utils.py:135), which starts a head
plus N worker raylets as real processes on one machine so multi-node
behavior is testable without a cluster. Here the head lives in the
calling process (`init(head=True)`) and each `add_node` spawns a real
`python -m ray_tpu start --address=...` OS process that joins it.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time
from typing import Any, Dict, List, Optional

from .core.rpc import RpcClient, RpcError


class NodeHandle:
    """One spawned worker-agent process."""

    def __init__(self, proc: subprocess.Popen, num_cpus: int, log_path: str):
        self.proc = proc
        self.num_cpus = num_cpus
        self.log_path = log_path

    @property
    def pid(self) -> int:
        return self.proc.pid

    def alive(self) -> bool:
        return self.proc.poll() is None

    def logs(self) -> str:
        try:
            with open(self.log_path, "r") as f:
                return f.read()
        except OSError:
            return ""


class Cluster:
    """Head in-process + worker agents as subprocesses.

    Usage::

        cluster = Cluster(head_node_args={"num_cpus": 2})
        cluster.add_node(num_cpus=2)
        cluster.wait_for_nodes(2)
        ... use ray_tpu normally; tasks spill onto the worker agents ...
        cluster.shutdown()
    """

    def __init__(self, head_node_args: Optional[Dict[str, Any]] = None,
                 token: Optional[str] = None):
        import ray_tpu

        args = dict(head_node_args or {})
        args.setdefault("num_cpus", 2)
        args.setdefault("detect_accelerators", False)
        self.token = token
        self.runtime = ray_tpu.init(head=True, cluster_token=token, **args)
        self.address: str = self.runtime.cluster.gcs_address
        self._nodes: List[NodeHandle] = []

    def add_node(self, num_cpus: int = 1, env: Optional[Dict[str, str]] = None,
                 system_config: Optional[Dict[str, Any]] = None,
                 resources: Optional[Dict[str, float]] = None) -> NodeHandle:
        """Spawn a worker agent that joins this cluster."""
        import json as _json

        cmd = [
            sys.executable, "-m", "ray_tpu", "--no-tpu", "start",
            "--address", self.address, "--num-cpus", str(num_cpus),
        ]
        if resources:
            cmd += ["--resources", _json.dumps(resources)]
        if self.token:
            cmd += ["--token", self.token]
        child_env = dict(os.environ)
        # agents in tests must not grab accelerators or another platform
        child_env.setdefault("JAX_PLATFORMS", "cpu")
        for key, value in (system_config or {}).items():
            child_env[f"RAY_TPU_{key.upper()}"] = str(value)
        child_env.update(env or {})
        # Log to a FILE, not a pipe: nothing drains a pipe while the agent
        # runs, so a chatty worker would block on a full pipe buffer, stop
        # heartbeating, and be declared dead.
        fd, log_path = tempfile.mkstemp(prefix="ray_tpu_agent_", suffix=".log")
        log_file = os.fdopen(fd, "w")
        try:
            proc = subprocess.Popen(
                cmd, env=child_env,
                stdout=log_file, stderr=subprocess.STDOUT, text=True,
            )
        finally:
            log_file.close()  # the child holds its own descriptor
        handle = NodeHandle(proc, num_cpus, log_path)
        self._nodes.append(handle)
        return handle

    def wait_for_nodes(self, count: int, timeout: float = 60.0) -> None:
        """Block until the scheduler's view holds `count` nodes total
        (head included)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.runtime.scheduler.nodes()) >= count:
                return
            for handle in self._nodes:
                if not handle.alive():
                    raise RuntimeError(
                        f"worker agent pid={handle.pid} exited "
                        f"rc={handle.proc.returncode}:\n{handle.logs()}"
                    )
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {count} nodes in {timeout}s "
            f"(have {len(self.runtime.scheduler.nodes())})"
        )

    def remove_node(self, handle: NodeHandle, allow_graceful: bool = True) -> None:
        """Take a worker down. Graceful asks the agent to stop (clean
        deregistration); otherwise SIGKILL simulates node failure."""
        if allow_graceful and handle.alive():
            try:
                info = self._agent_info(handle)
                if info is not None:
                    RpcClient(info, timeout=5.0, retries=0, token=self.token).call(
                        "shutdown_node"
                    )
            except (RpcError, OSError):
                pass
            try:
                handle.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                handle.proc.kill()
        else:
            handle.proc.kill()
        handle.proc.wait()
        if handle in self._nodes:
            self._nodes.remove(handle)
        try:
            os.unlink(handle.log_path)
        except OSError:
            pass

    def _agent_info(self, handle: NodeHandle) -> Optional[str]:
        """Find the agent address of a spawned node via the GCS table."""
        ctx = self.runtime.cluster
        for info in ctx.nodes():
            if info.get("pid") == handle.pid:
                return info["address"]
        return None

    def shutdown(self) -> None:
        import ray_tpu

        for handle in list(self._nodes):
            handle.proc.kill()
            handle.proc.wait()
            try:
                os.unlink(handle.log_path)
            except OSError:
                pass
        self._nodes.clear()
        ray_tpu.shutdown()
