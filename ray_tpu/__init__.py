"""ray_tpu — a TPU-native distributed AI framework.

Capabilities of Ray (actors/tasks/object store/Train/Data/Serve/Tune),
re-designed TPU-first: the compute plane is JAX/XLA/pjit/Pallas over ICI
device meshes; the control plane is a resource-aware actor/task runtime.

Public surface (parity with /root/reference/python/ray/__init__.py):
    init, shutdown, remote, get, put, wait, kill, cancel, get_actor,
    placement_group, cluster_resources, available_resources, nodes, ...
Subpackages:
    ray_tpu.parallel — device meshes, sharding rules, collectives
    ray_tpu.models   — flagship model families (GPT-2, Llama, MoE, ViT)
    ray_tpu.ops      — Pallas TPU kernels (flash/ring/paged attention)
    ray_tpu.train    — multi-host training controller (Train-equivalent)
    ray_tpu.data     — streaming datasets (Data-equivalent)
    ray_tpu.serve    — continuous-batching inference (Serve-equivalent)
    ray_tpu.tune     — experiment sweeps (Tune-equivalent)
"""

from ._version import __version__  # noqa: F401
from .api import (  # noqa: F401
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    list_actors,
    nodes,
    placement_group,
    put,
    remote,
    remove_placement_group,
    shutdown,
    wait,
)
from .core.exceptions import (  # noqa: F401
    ActorDiedError,
    ActorError,
    ActorUnavailableError,
    BackPressureError,
    DeploymentUnavailableError,
    GetTimeoutError,
    HeadUnavailableError,
    ObjectLostError,
    ObjectStoreFullError,
    OutOfResourcesError,
    PlacementGroupUnschedulableError,
    ProfilingError,
    RayTpuError,
    ReplicaDrainingError,
    RequestTimeoutError,
    RuntimeNotInitializedError,
    StaleEpochError,
    TaskCancelledError,
    TaskError,
)
from .core.runtime import ActorHandle, ObjectRef  # noqa: F401
from .core.streaming import ObjectRefGenerator  # noqa: F401
from .core.scheduler import (  # noqa: F401
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)
