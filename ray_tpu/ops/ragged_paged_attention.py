"""Ragged paged attention: ONE kernel launch for mixed prefill + decode.

The serve engine's former dispatch was split — `batched_chunk_prefill_step`
for prompt chunks, the Pallas paged-attention kernel (decode, q_len == 1)
for everything else — so a tick with both kinds of work paid two compiled
programs and two rounds of HBM traffic over the page pool. This module is
the ragged-paged-attention recipe from PAPERS.md (arxiv 2604.15464): the
batch is described RAGGED — per-sequence q lengths, kv lengths and
scalar-prefetched block tables — and one grid covers prefill chunks
(q_len up to chunk_tokens) and decode lanes (q_len == 1) together.

Layout:

- q is TOKEN-MAJOR with heads leading: (Hq, T, D). T is the concatenation
  of per-sequence q REGIONS, each a whole number of `block_q` rows
  (`q_starts`/`q_block_counts`, in block units). A sequence's real rows are
  the first `q_lens[s]` of its region; the rest are padding the kernel
  masks off and writes back as zeros.
- K/V come straight from the paged pool, (Hkv, P, ps, D); `block_tables`
  (S, maxP) holds absolute page ids (callers fold per-layer offsets in).
  Unused table entries must point at the scratch page 0.
- The query at region row r of sequence s sits at token position
  kv_lens[s] - q_lens[s] + r; causal masking and the kv-length bound both
  derive from that, so a prefill chunk at offset o (q_len = chunk tokens,
  kv_len = o + chunk tokens) and a decode lane (q_len = 1, kv_len =
  position + 1) are the same descriptor.

Numerics contract: the kernel uses plain exp (NOT the exp2 trick the dense
flash kernel uses) and the caller pre-scales q, so the XLA fallback
`ragged_reference_attention` — a gather over block tables that replays the
kernel's block schedule op for op — is bit-exact vs the kernel at f32.
Off-TPU the engine runs the reference; the interpret driver exists so CI
can replay the exact kernel schedule without hardware.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports can fail on exotic non-TPU builds; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30  # finite "minus infinity": exp() lands at exactly 0.0


# ------------------------------------------------------------------ kernel


def _ragged_kernel(
    # scalar-prefetched descriptor (available before the body runs — they
    # drive the q/kv BlockSpec index maps)
    starts_ref,   # (S,)  region start, in block_q units
    counts_ref,   # (S,)  region size, in block_q units (>= 1)
    q_lens_ref,   # (S,)  real q rows in the region
    kv_lens_ref,  # (S,)  total kv length (includes this step's tokens)
    tables_ref,   # (S, maxP) absolute page ids (0 = scratch)
    # tensor refs
    q_ref,        # (1, block_q, D)
    k_ref,        # (1, 1, ps, D)
    v_ref,        # (1, 1, ps, D)
    o_ref,        # (1, block_q, D)
    m_scr,        # (block_q, 128) f32 running max
    l_scr,        # (block_q, 128) f32 running sum
    acc_scr,      # (block_q, D)  f32 running numerator
    *,
    block_q: int,
    page_size: int,
    num_kv_blocks: int,
):
    s = pl.program_id(0)
    qb = pl.program_id(2)
    kb = pl.program_id(3)
    q_len = q_lens_ref[s]
    kv_len = kv_lens_ref[s]

    @pl.when(kb == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # A (qb, kb) tile contributes iff the q block holds a real row AND the
    # kv block starts at or before the block's last reachable position.
    # pos_hi is the causal frontier of the block's last REAL row.
    pos_hi = kv_len - q_len + jnp.minimum((qb + 1) * block_q, q_len) - 1
    work = (qb * block_q < q_len) & (kb * page_size <= pos_hi)

    @pl.when(work)
    def _compute():
        q = q_ref[0].astype(jnp.float32)      # (block_q, D) — pre-scaled
        k = k_ref[0, 0].astype(jnp.float32)   # (ps, D)
        v = v_ref[0, 0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (block_q, ps)
        row = qb * block_q + jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        col = kb * page_size + jax.lax.broadcasted_iota(
            jnp.int32, logits.shape, 1
        )
        pos = kv_len - q_len + row
        mask = (row < q_len) & (col <= pos) & (col < kv_len)
        logits = jnp.where(mask, logits, _NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    # Write every block the sequence OWNS (padding blocks flush zeros, so
    # no region row is ever left as undefined memory); overflow grid steps
    # past the region (qb >= counts) alias the region's last block in the
    # index map and must not touch o_ref — the buffer re-flushes its
    # already-correct content.
    @pl.when((kb == num_kv_blocks - 1) & (qb < counts_ref[s]))
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


def _clamped_q_block(s, qb, starts_ref, counts_ref):
    # Overflow steps (qb beyond this sequence's region) pin to the region's
    # last block: the index never crosses into a neighbour's rows.
    return starts_ref[s] + jnp.minimum(qb, counts_ref[s] - 1)


def _ragged_pallas(
    q, k_pages, v_pages, starts, counts, q_lens, kv_lens, tables,
    *, block_q: int, max_q_blocks: int, interpret: bool,
):
    hq, t, d = q.shape
    hkv = k_pages.shape[0]
    ps = k_pages.shape[2]
    s_count, max_pages = tables.shape
    groups = hq // hkv
    grid = (s_count, hq, max_q_blocks, max_pages)

    def q_map(s, h, qb, kb, starts_ref, counts_ref, ql_ref, kl_ref, t_ref):
        return (h, _clamped_q_block(s, qb, starts_ref, counts_ref), 0)

    def kv_map(s, h, qb, kb, starts_ref, counts_ref, ql_ref, kl_ref, t_ref):
        return (h // groups, t_ref[s, kb], 0, 0)

    kernel = functools.partial(
        _ragged_kernel,
        block_q=block_q,
        page_size=ps,
        num_kv_blocks=max_pages,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, 1, ps, d), kv_map),
            pl.BlockSpec((1, 1, ps, d), kv_map),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_map),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((hq, t, d), q.dtype),
        interpret=interpret,
    )(starts, counts, q_lens, kv_lens, tables, q, k_pages, v_pages)


# --------------------------------------------------------------- reference


def ragged_reference_attention(
    q, k_pages, v_pages, starts, counts, q_lens, kv_lens, tables,
    *, block_q: int, max_q_blocks: int,
):
    """Gather-based XLA fallback that REPLAYS the kernel's block schedule.

    Pages are gathered through the block tables exactly as the kernel's
    index maps fetch them, and the online-softmax update runs per kv block
    in the kernel's op order (same dot shapes, same mask constant, same
    plain exp), vectorized over (S, Hq, q-block). That makes it bit-exact
    vs the Pallas kernel at f32 — the parity drill asserts it — instead of
    merely allclose, so off-TPU runs pin the kernel's numerics.
    """
    hq, t, d = q.shape
    hkv = k_pages.shape[0]
    ps = k_pages.shape[2]
    s_count, max_pages = tables.shape
    groups = hq // hkv

    # (S, MAXQB) region-clamped block indices -> q blocks (Hq, S, MAXQB, bq, D)
    qb_idx = jnp.arange(max_q_blocks)[None, :]
    blk = starts[:, None] + jnp.minimum(qb_idx, counts[:, None] - 1)
    q_blocks = q.reshape(hq, t // block_q, block_q, d)[:, blk]
    # gathered pages: (Hkv, S, maxP, ps, D)
    k_seq = k_pages[:, tables]
    v_seq = v_pages[:, tables]
    if groups > 1:
        k_seq = jnp.repeat(k_seq, groups, axis=0)
        v_seq = jnp.repeat(v_seq, groups, axis=0)

    row = (
        qb_idx[:, :, None] * block_q
        + jnp.arange(block_q)[None, None, :]
    )  # (1, MAXQB, bq) -> broadcast over S
    pos = kv_lens[:, None, None] - q_lens[:, None, None] + row  # (S, MAXQB, bq)
    row_valid = row < q_lens[:, None, None]
    pos_hi = (
        kv_lens[:, None] - q_lens[:, None]
        + jnp.minimum((qb_idx + 1) * block_q, q_lens[:, None]) - 1
    )  # (S, MAXQB)

    def step(carry, kb):
        m_prev, l_prev, acc = carry
        k = k_seq[:, :, kb].astype(jnp.float32)  # (Hq, S, ps, D)
        v = v_seq[:, :, kb].astype(jnp.float32)
        # same contraction as the kernel's 2D dot, batched over (Hq, S, MAXQB)
        logits = jnp.einsum(
            "hsbqd,hskd->hsbqk",
            q_blocks.astype(jnp.float32),
            k,
            preferred_element_type=jnp.float32,
        )  # (Hq, S, MAXQB, bq, ps)
        col = kb * ps + jnp.arange(ps)
        mask = (
            row_valid[None, :, :, :, None]
            & (col[None, None, None, None, :] <= pos[None, :, :, :, None])
            & (col[None, None, None, None, :] < kv_lens[None, :, None, None, None])
        )
        logits = jnp.where(mask, logits, _NEG_INF)
        m_cur = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "hsbqk,hskd->hsbqd", p, v, preferred_element_type=jnp.float32
        )
        # the kernel's pl.when(work) guard, replayed per (S, qb) block
        work = (
            (qb_idx * block_q < q_lens[:, None]) & (kb * ps <= pos_hi)
        )[None, :, :, None, None]
        m_new = jnp.where(work, m_new, m_prev)
        l_new = jnp.where(work, l_new, l_prev)
        acc_new = jnp.where(work, acc_new, acc)
        return (m_new, l_new, acc_new), None

    stat = (hq, s_count, max_q_blocks, block_q, 1)
    init = (
        jnp.full(stat, _NEG_INF, jnp.float32),
        jnp.zeros(stat, jnp.float32),
        jnp.zeros((hq, s_count, max_q_blocks, block_q, d), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(step, init, jnp.arange(max_pages))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out_blocks = (acc / safe_l).astype(q.dtype)  # (Hq, S, MAXQB, bq, D)

    # scatter region blocks back to token-major rows; padding blocks beyond
    # a region (qb >= counts) must NOT clobber the aliased last block
    flat_blk = blk.reshape(-1)  # (S*MAXQB,)
    valid = (qb_idx < counts[:, None]).reshape(-1)
    out = jnp.zeros((hq, t // block_q, block_q, d), q.dtype)
    out = out.at[:, jnp.where(valid, flat_blk, t // block_q)].set(
        out_blocks.reshape(hq, -1, block_q, d), mode="drop"
    )
    return out.reshape(hq, t, d)


# ----------------------------------------------------------------- dispatch


def ragged_paged_attention(
    q: jax.Array,           # (Hq, T, D) token-major, per-seq block regions
    k_pages: jax.Array,     # (Hkv, P, ps, D)
    v_pages: jax.Array,
    starts: jax.Array,      # (S,) int32 region starts, block_q units
    counts: jax.Array,      # (S,) int32 region sizes, block_q units (>= 1)
    q_lens: jax.Array,      # (S,) int32 real q rows (0 = inactive lane)
    kv_lens: jax.Array,     # (S,) int32 total kv length per sequence
    tables: jax.Array,      # (S, maxP) int32 absolute page ids
    *,
    block_q: int = 8,
    sm_scale: Optional[float] = None,
    max_q_blocks: Optional[int] = None,
    use_kernel: Optional[bool] = None,
    interpret: bool = False,
    mesh=None,
    tp_axis: str = "tp",
) -> jax.Array:
    """Causal ragged paged attention over a page pool; returns (Hq, T, D).

    Region semantics: query row r of a region sits at absolute position
    kv_len - q_len + r, so the SAME descriptor covers every region shape
    the engine dispatches — prefill chunks (q_len = chunk fill), plain
    decode lanes (q_len = 1), and speculative VERIFY regions (q_len = K:
    the pending token plus K-1 drafts scored causally in one launch, each
    draft row attending to the drafts before it plus the lane's whole
    paged history). Nothing kernel-side distinguishes a verify region
    from a short prefill chunk — speculation rides the existing grid.

    Dispatch: Pallas kernel on TPU when the Mosaic tiling rules hold
    (D % 128 == 0, page_size % 8 == 0, block_q % 8 == 0); the
    schedule-replaying gather reference otherwise. `interpret=True` forces
    the kernel through the Pallas interpreter (CI parity drills).
    Under a tensor-parallel mesh the kernel path is wrapped in `shard_map`
    over the head axes — GSPMD cannot partition a pallas_call, but both
    Hq and Hkv divide by tp, so each shard runs the kernel on its local
    head group with the descriptor replicated.
    """
    hq, t, d = q.shape
    ps = k_pages.shape[2]
    if t % block_q:
        raise ValueError(
            f"token rows ({t}) must divide by block_q ({block_q}): regions "
            "are dispatched in block_q-row units"
        )
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if max_q_blocks is None:
        # static upper bound on region size: T is exactly the sum of the
        # regions, so T // block_q bounds any single one; callers with a
        # tighter bound (the engine: chunk blocks) pass it to shrink the grid
        max_q_blocks = t // block_q
    q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    if use_kernel is None:
        use_kernel = (
            _HAS_PLTPU
            and jax.default_backend() == "tpu"
            and d % 128 == 0
            and ps % 8 == 0
            and block_q % 8 == 0
        )
    if interpret and _HAS_PLTPU:
        use_kernel = True
    args = (starts, counts, q_lens, kv_lens, tables)
    if use_kernel:
        # nb: keep this local's name distinct from any method name in the
        # repo — raylint's name-level reachability treats shard_map args
        # as hot roots project-wide
        ragged_kernel_fn = functools.partial(
            _ragged_pallas,
            block_q=block_q,
            max_q_blocks=max_q_blocks,
            interpret=interpret or jax.default_backend() != "tpu",
        )
        if mesh is not None and mesh.shape.get(tp_axis, 1) > 1:
            from .._jax_compat import shard_map
            from jax.sharding import PartitionSpec as P

            ragged_kernel_fn = shard_map(
                ragged_kernel_fn,
                mesh=mesh,
                in_specs=(
                    P(tp_axis, None, None),        # q: shard heads
                    P(tp_axis, None, None, None),  # k pages: shard kv heads
                    P(tp_axis, None, None, None),  # v pages
                    P(), P(), P(), P(), P(),       # descriptor: replicated
                ),
                out_specs=P(tp_axis, None, None),
                check_rep=False,
            )
        return ragged_kernel_fn(q, k_pages, v_pages, *args)
    return ragged_reference_attention(
        q, k_pages, v_pages, *args,
        block_q=block_q, max_q_blocks=max_q_blocks,
    )
