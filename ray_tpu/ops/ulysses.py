"""Ulysses context parallelism: all-to-all head-scattered attention.

NEW capability relative to the reference — czxxing/ray has no sequence/
context parallelism (SURVEY.md §2.4). This is the DeepSpeed-Ulysses
recipe mapped to TPU: inputs arrive SEQUENCE-sharded on the `sp` mesh
axis; one `all_to_all` over ICI re-shards them HEAD-wise so every device
holds the full sequence for H/n heads, runs ordinary (flash) attention
locally — the Pallas kernel, fully fused, no ring bookkeeping — and a
second all_to_all restores sequence sharding.

Compared to ring attention: 2 collectives total instead of n ppermute
hops, and the local compute is the plain fused kernel; the tradeoff is
that heads must divide the axis size (rings have no such constraint)
and each device momentarily holds S × H/n activations. Use Ulysses when
H ≥ n; fall back to the ring for very long sequences on large axes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .._jax_compat import shard_map

from .attention import flash_attention

P = PartitionSpec


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
    implementation: Optional[str],
):
    """Per-shard body (under shard_map). q/k/v: (B, H, S_local, D)."""
    # scatter heads, gather sequence: (B, H, S/n, D) -> (B, H/n, S, D)
    q = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    out = flash_attention(
        q, k, v, causal=causal, sm_scale=sm_scale,
        implementation=implementation,
    )
    # scatter sequence, gather heads: back to (B, H, S/n, D)
    return lax.all_to_all(out, axis_name, split_axis=2, concat_axis=1, tiled=True)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    sm_scale: Optional[float] = None,
    implementation: Optional[str] = None,
) -> jax.Array:
    """Sequence-parallel exact attention via head scattering.

    q (B,Hq,S,D), k/v (B,Hkv,S,D); S and Hq must divide by
    mesh.shape[axis]. Returns (B,Hq,S,D) sharded like q. Differentiable
    (all_to_all transposes to itself; the local kernel has its own vjp).
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        groups = hq // hkv
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"seq {q.shape[2]} not divisible by {axis}={n}")
    if hq % n:
        raise ValueError(
            f"Ulysses needs heads ({hq}) divisible by the {axis} axis ({n}); "
            "use ring_attention for head counts below the axis size"
        )
    spec = P(None, None, axis, None)
    body = functools.partial(
        _ulysses_local, axis_name=axis, causal=causal, sm_scale=sm_scale,
        implementation=implementation,
    )
    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ulysses_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """Convenience: device_put inputs seq-sharded, run, leave output sharded."""
    spec = NamedSharding(mesh, P(None, None, axis, None))
    q = jax.device_put(q, spec)
    k = jax.device_put(k, spec)
    v = jax.device_put(v, spec)
    return ulysses_attention(q, k, v, mesh=mesh, axis=axis, causal=causal)
