"""ray_tpu.ops — TPU compute kernels (Pallas) with XLA reference paths.

The reference framework delegates attention/normalization kernels to vLLM /
torch CUDA kernels (e.g. /root/reference/python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py:254). Here the hot ops are implemented
TPU-first: Pallas kernels tiled for the MXU/VPU, with pure-XLA reference
implementations used for correctness testing and as the CPU fallback.

Dispatch convention: every op takes `implementation=` ("pallas" | "xla" |
None). None auto-selects pallas on TPU backends, xla elsewhere.
"""

from .attention import flash_attention, mha_reference  # noqa: F401
from .ragged_paged_attention import (  # noqa: F401
    ragged_paged_attention,
    ragged_reference_attention,
)
from .ring_attention import ring_attention, ring_attention_sharded  # noqa: F401
from .ulysses import ulysses_attention, ulysses_attention_sharded  # noqa: F401
from .layers import (  # noqa: F401
    apply_rope,
    gelu,
    layernorm,
    rmsnorm,
    rope_frequencies,
    swiglu,
)
from .losses import cross_entropy_loss, z_loss  # noqa: F401
