"""Flash attention for TPU: Pallas forward/backward kernels + XLA reference.

The reference framework has no attention kernel of its own — it rides on
vLLM/torch CUDA kernels (/root/reference/python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py:254). This module is the TPU-native
replacement: a blockwise online-softmax kernel (Dao et al.) tiled so the
score/accumulate matmuls land on the MXU and the running max/sum stay in
VMEM scratch across the kv-block grid dimension.

Layout convention: q (B, Hq, Sq, D); k, v (B, Hkv, Skv, D) with
Hq % Hkv == 0 (grouped-query attention — kv blocks are index-mapped onto
query-head groups, no materialized repeat on the forward path).

All shapes are static; padding to block multiples happens in the wrapper and
is masked inside the kernel, so XLA never sees dynamic shapes.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on non-TPU builds only for exotic setups; guard anyway
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_NEG_INF = -1e30  # finite "minus infinity": keeps exp() at exactly 0.0 without NaNs
_LOG2E = 1.4426950408889634  # kernels fold log2(e) into sm_scale and use
# exp2/log2 internally: one VPU transcendental per element instead of
# exp's extra multiply (the standard TPU flash trick); the stored lse
# stays in NATURAL log so the backward contract is unchanged


# ------------------------------------------------------------------ reference


def mha_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    kv_len: Optional[int] = None,
) -> jax.Array:
    """Pure-XLA multi-head attention. Ground truth for the Pallas kernels and
    the CPU-backend fallback. Supports GQA and right-padding via `kv_len`."""
    _, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if hq != hkv:
        groups = hq // hkv
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * sm_scale
    mask = None
    if kv_len is not None:
        mask = jnp.arange(skv)[None, :] < kv_len
    if causal:
        causal_mask = jnp.arange(skv)[None, :] <= jnp.arange(sq)[:, None] + (skv - sq)
        mask = causal_mask if mask is None else (mask & causal_mask)
    if mask is not None:
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)
    return out.astype(q.dtype)


# -------------------------------------------------------------- pallas forward


def _fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    lse_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    sm_scale: float,
    causal: bool,
    block_q: int,
    block_kv: int,
    kv_len: int,
    num_kv_blocks: int,
):
    i = pl.program_id(2)
    j = pl.program_id(3)

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # Causal: kv blocks strictly above the diagonal band contribute nothing.
    needed = True
    if causal:
        needed = j * block_kv <= i * block_q + (block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]  # (block_q, d)
        k = k_ref[0, 0]  # (block_kv, d)
        v = v_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * (sm_scale * _LOG2E)  # base-2 log domain

        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == num_kv_blocks - 1)
    def _finalize():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # logsumexp residual for the backward pass; fully-masked rows get -inf.
        # Stored as (..., S, 1) — a (block_q, 1) block satisfies the Mosaic
        # last-two-dims tiling rule, a bare (block_q,) block does not.
        lse_ref[0, 0] = jnp.where(
            l == 0.0, _NEG_INF,
            (m_scr[:, :1] + jnp.log2(safe_l)) * (1.0 / _LOG2E),
        )


def _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    groups = hq // hkv
    nq = sq // block_q
    nk = skv // block_kv

    kernel = functools.partial(
        _fwd_kernel,
        sm_scale=sm_scale,
        causal=causal,
        block_q=block_q,
        block_kv=block_kv,
        kv_len=kv_len,
        num_kv_blocks=nk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec(
                (1, 1, block_kv, d), lambda b_, h, i, j, g=groups: (b_, h // g, j, 0)
            ),
            pl.BlockSpec(
                (1, 1, block_kv, d), lambda b_, h, i, j, g=groups: (b_, h // g, j, 0)
            ),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i, j: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i, j: (b_, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse


# ------------------------------------------------------------- pallas backward
#
# Standard flash backward (Dao et al. alg. 2), two kernels:
#   dkv kernel: grid kv-outer / q-inner, accumulates dK_j, dV_j across q blocks
#   dq  kernel: grid q-outer / kv-inner, accumulates dQ_i across kv blocks
# P is recomputed from (q, k, lse); delta = rowsum(dO * O) is cheap in XLA.
# GQA is handled in the wrapper (repeat kv, then segment-sum dk/dv) — the
# kernels always see Hq == Hkv.


def _dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dk_ref, dv_ref, dk_scr, dv_scr,
    *, sm_scale, causal, block_q, block_kv, kv_len, num_q_blocks,
):
    j = pl.program_id(2)  # kv block (outer)
    i = pl.program_id(3)  # q block (inner)

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    needed = True
    if causal:
        needed = j * block_kv <= i * block_q + (block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (block_q, 1)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (sm_scale * _LOG2E)
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp2(s - lse * _LOG2E)  # (block_q, block_kv)

        # dV_j += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        # dP = dO V^T ; dS = P * (dP - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        # dK_j += dS^T Q
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref, dq_scr,
    *, sm_scale, causal, block_q, block_kv, kv_len, num_kv_blocks,
):
    i = pl.program_id(2)  # q block (outer)
    j = pl.program_id(3)  # kv block (inner)

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    needed = True
    if causal:
        needed = j * block_kv <= i * block_q + (block_q - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0]  # (block_q, 1)
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * (sm_scale * _LOG2E)
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (col <= row)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp2(s - lse * _LOG2E)

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_kv_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_pallas(q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nq = sq // block_q
    nk = skv // block_kv

    # (b, h, sq, 1): the trailing singleton keeps row blocks 2D for Mosaic
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    q_spec = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, j, i: (b_, h_, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, j, i: (b_, h_, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, j, i: (b_, h_, i, 0))

    dkv_kernel = functools.partial(
        _dkv_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_len=kv_len, num_q_blocks=nq,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk, nq),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    q_spec2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i, j: (b_, h_, i, 0))
    kv_spec2 = pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, i, j: (b_, h_, j, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i, j: (b_, h_, i, 0))

    dq_kernel = functools.partial(
        _dq_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_len=kv_len, num_kv_blocks=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq, nk),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ----------------------------------------------------------- custom_vjp plumbing


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    out, _ = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret)
    return out


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    out, lse = _fwd_pallas(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_kv, kv_len, interpret, res, do):
    q, k, v, out, lse = res
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        groups = hq // hkv
        k_full = jnp.repeat(k, groups, axis=1)
        v_full = jnp.repeat(v, groups, axis=1)
    else:
        groups = 1
        k_full, v_full = k, v
    dq, dk, dv = _bwd_pallas(
        q, k_full, v_full, out, lse, do, causal, sm_scale, block_q, block_kv,
        kv_len, interpret,
    )
    if groups > 1:
        b, _, skv, d = dk.shape
        dk = dk.reshape(b, hkv, groups, skv, d).sum(axis=2)
        dv = dv.reshape(b, hkv, groups, skv, d).sum(axis=2)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------- pipelined kernels
#
# The classic kernels above run, per (q, kv) tile: QK^T (MXU) -> online
# softmax (VPU) -> PV (MXU) — a serial dependency chain that parks the MXU
# through the whole softmax (PERF_NOTES.md: 5-6x off roofline at D=64).
# The pipelined variants break the chain with a one-step software skew over
# the kv-tile loop: inner step t issues tile t's QK^T while the online
# softmax/rescale for tile t-1 runs, so the two stages have no data
# dependency inside one step and Mosaic can overlap the MXU and VPU chains.
#
# On TPU the kv tiles stream HBM->VMEM through pltpu.emit_pipeline (explicit
# double buffering; q and the accumulators stay VMEM-resident across the
# whole row instead of being re-fetched per (i, j) grid step like the
# classic 4D grid does). Off-TPU an interpret-mode driver executes the SAME
# stage functions and slot arithmetic inside a fori_loop — the numerics of
# both drivers are identical by construction, and bit-identical to the
# classic kernel: tile math and accumulation order are unchanged, only the
# schedule moves. tests/test_ops.py pins that equality at f32.


def _fwd_stages(sm_scale, causal, block_q, block_kv, kv_len):
    """Per-tile forward stages. `scores` is the MXU stage (QK^T + mask),
    `online_update` the VPU-heavy stage (online softmax + PV rescale).
    Expressions mirror _fwd_kernel exactly — bit-compatibility depends on
    it."""

    def scores(q, k, i, t):
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * (sm_scale * _LOG2E)
        col = t * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < kv_len
        if causal:
            row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            mask = mask & (col <= row)
        return jnp.where(mask, s, _NEG_INF)

    def online_update(s, v, m_scr, l_scr, acc_scr):
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    return scores, online_update


def _bwd_stages(sm_scale, causal, block_q, block_kv, kv_len):
    """Per-tile backward stages; expressions mirror _dkv_kernel/_dq_kernel."""
    scores, _ = _fwd_stages(sm_scale, causal, block_q, block_kv, kv_len)

    def dkv_update(s, q, do, v, lse, delta, dk_scr, dv_scr):
        p = jnp.exp2(s - lse * _LOG2E)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def dq_update(s, k, v, do, lse, delta, dq_scr):
        p = jnp.exp2(s - lse * _LOG2E)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    return scores, dkv_update, dq_update


def _num_kv_tiles(i, causal, block_q, block_kv, nk):
    """kv tiles query block i touches (causal block skipping, same set the
    classic kernel's `needed` predicate admits)."""
    if not causal:
        return nk
    last = (i * block_q + block_q - 1) // block_kv
    return jnp.minimum(last + 1, nk)


def _fwd_finalize(o_ref, lse_ref, m_scr, l_scr, acc_scr):
    l = l_scr[:, :1]
    safe_l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0, 0] = (acc_scr[...] / safe_l).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(
        l == 0.0, _NEG_INF,
        (m_scr[:, :1] + jnp.log2(safe_l)) * (1.0 / _LOG2E),
    )


def _fwd_kernel_pipe_interp(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, s_scr,
    *, sm_scale, causal, block_q, block_kv, kv_len, num_kv_blocks,
):
    """Interpret-mode driver: the emit_pipeline schedule (skewed stages,
    double-buffered score slots) replayed in a fori_loop with whole-row k/v
    resident."""
    i = pl.program_id(2)
    scores, online_update = _fwd_stages(sm_scale, causal, block_q, block_kv, kv_len)
    m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
    l_scr[...] = jnp.zeros_like(l_scr)
    acc_scr[...] = jnp.zeros_like(acc_scr)
    q = q_ref[0, 0]
    tiles = _num_kv_tiles(i, causal, block_q, block_kv, num_kv_blocks)

    def body(t, carry):
        @pl.when(t < tiles)
        def _stage_a():  # QK^T for tile t
            kt = k_ref[0, 0, pl.ds(t * block_kv, block_kv), :]
            s_scr[t % 2] = scores(q, kt, i, t)

        @pl.when(t > 0)
        def _stage_b():  # online softmax + PV for tile t-1
            vt = v_ref[0, 0, pl.ds((t - 1) * block_kv, block_kv), :]
            online_update(s_scr[(t - 1) % 2], vt, m_scr, l_scr, acc_scr)

        return carry

    jax.lax.fori_loop(0, tiles + 1, body, 0)
    _fwd_finalize(o_ref, lse_ref, m_scr, l_scr, acc_scr)


def _fwd_pipe_interp(q, k, v, causal, sm_scale, block_q, block_kv, kv_len):
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    groups = hq // hkv
    nq = sq // block_q
    nk = skv // block_kv
    kernel = functools.partial(
        _fwd_kernel_pipe_interp, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_kv=block_kv, kv_len=kv_len, num_kv_blocks=nk,
    )
    return pl.pallas_call(
        kernel,
        grid=(b, hq, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda b_, h, i, g=groups: (b_, h // g, 0, 0)),
            pl.BlockSpec((1, 1, skv, d), lambda b_, h, i, g=groups: (b_, h // g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i: (b_, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
        interpret=True,
    )(q, k, v)


def _fwd_pipe_tpu(q, k, v, causal, sm_scale, block_q, block_kv, kv_len):
    """emit_pipeline driver: q/accumulators VMEM-resident per (b, h, i) row;
    kv tiles stream HBM->VMEM double-buffered, v delivered one step behind k
    so stage B always has the tile stage A scored on the previous step."""
    b, hq, sq, d = q.shape
    _, hkv, skv, _ = k.shape
    groups = hq // hkv
    nq = sq // block_q
    nk = skv // block_kv

    def outer(q_ref, k_hbm, v_hbm, o_ref, lse_ref, m_scr, l_scr, acc_scr, s_scr):
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        i = pl.program_id(2)
        hk = hi // groups
        scores, online_update = _fwd_stages(
            sm_scale, causal, block_q, block_kv, kv_len
        )
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        q_blk = q_ref[0, 0]
        tiles = _num_kv_tiles(i, causal, block_q, block_kv, nk)

        def inner(k_ref, v_ref):
            t = pl.program_id(0)

            @pl.when(t < tiles)
            def _stage_a():
                s_scr[t % 2] = scores(q_blk, k_ref[0, 0], i, t)

            @pl.when(t > 0)
            def _stage_b():
                online_update(s_scr[(t - 1) % 2], v_ref[0, 0], m_scr, l_scr, acc_scr)

        pipeline = pltpu.emit_pipeline(
            inner,
            grid=(tiles + 1,),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_kv, d),
                    lambda t: (bi, hk, jnp.minimum(t, nk - 1), 0),
                ),
                pl.BlockSpec(
                    (1, 1, block_kv, d),
                    lambda t: (bi, hk, jnp.maximum(t - 1, 0), 0),
                ),
            ],
            out_specs=[],
        )
        pipeline(k_hbm, v_hbm)
        _fwd_finalize(o_ref, lse_ref, m_scr, l_scr, acc_scr)

    return pl.pallas_call(
        outer,
        grid=(b, hq, nq),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda b_, h, i: (b_, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b_, h, i: (b_, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
    )(q, k, v)


def _fwd_pipe(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    if interpret:
        return _fwd_pipe_interp(q, k, v, causal, sm_scale, block_q, block_kv, kv_len)
    return _fwd_pipe_tpu(q, k, v, causal, sm_scale, block_q, block_kv, kv_len)


def _dkv_kernel_pipe_interp(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr, s_scr,
    *, sm_scale, causal, block_q, block_kv, kv_len, num_q_blocks,
):
    j = pl.program_id(2)
    scores, dkv_update, _ = _bwd_stages(sm_scale, causal, block_q, block_kv, kv_len)
    dk_scr[...] = jnp.zeros_like(dk_scr)
    dv_scr[...] = jnp.zeros_like(dv_scr)
    k_blk = k_ref[0, 0]
    v_blk = v_ref[0, 0]
    # causal: q blocks strictly above the diagonal band contribute nothing
    t_start = (j * block_kv) // block_q if causal else 0
    n_tiles = num_q_blocks - t_start

    def body(u, carry):
        t = t_start + u

        @pl.when(u < n_tiles)
        def _stage_a():
            qt = q_ref[0, 0, pl.ds(t * block_q, block_q), :]
            s_scr[u % 2] = scores(qt, k_blk, t, j)

        @pl.when(u > 0)
        def _stage_b():
            tp = t - 1
            sl = pl.ds(tp * block_q, block_q)
            dkv_update(
                s_scr[(u - 1) % 2], q_ref[0, 0, sl, :], do_ref[0, 0, sl, :],
                v_blk, lse_ref[0, 0, sl, :], delta_ref[0, 0, sl, :],
                dk_scr, dv_scr,
            )

        return carry

    jax.lax.fori_loop(0, n_tiles + 1, body, 0)
    dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
    dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel_pipe_interp(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, s_scr,
    *, sm_scale, causal, block_q, block_kv, kv_len, num_kv_blocks,
):
    i = pl.program_id(2)
    scores, _, dq_update = _bwd_stages(sm_scale, causal, block_q, block_kv, kv_len)
    dq_scr[...] = jnp.zeros_like(dq_scr)
    q_blk = q_ref[0, 0]
    do_blk = do_ref[0, 0]
    lse_blk = lse_ref[0, 0]
    delta_blk = delta_ref[0, 0]
    tiles = _num_kv_tiles(i, causal, block_q, block_kv, num_kv_blocks)

    def body(t, carry):
        @pl.when(t < tiles)
        def _stage_a():
            kt = k_ref[0, 0, pl.ds(t * block_kv, block_kv), :]
            s_scr[t % 2] = scores(q_blk, kt, i, t)

        @pl.when(t > 0)
        def _stage_b():
            sl = pl.ds((t - 1) * block_kv, block_kv)
            dq_update(
                s_scr[(t - 1) % 2], k_ref[0, 0, sl, :], v_ref[0, 0, sl, :],
                do_blk, lse_blk, delta_blk, dq_scr,
            )

        return carry

    jax.lax.fori_loop(0, tiles + 1, body, 0)
    dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_pipe_interp(q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nq = sq // block_q
    nk = skv // block_kv
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    full_q = pl.BlockSpec((1, 1, sq, d), lambda b_, h_, g: (b_, h_, 0, 0))
    full_row = pl.BlockSpec((1, 1, sq, 1), lambda b_, h_, g: (b_, h_, 0, 0))
    kv_blk = pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, j: (b_, h_, j, 0))

    dkv_kernel = functools.partial(
        _dkv_kernel_pipe_interp, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_kv=block_kv, kv_len=kv_len, num_q_blocks=nq,
    )
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, nk),
        in_specs=[full_q, kv_blk, kv_blk, full_q, full_row, full_row],
        out_specs=[kv_blk, kv_blk],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
        interpret=True,
    )(q, k, v, do, lse, delta)

    q_blk = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    row_blk = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0))
    full_kv = pl.BlockSpec((1, 1, skv, d), lambda b_, h_, i: (b_, h_, 0, 0))

    dq_kernel = functools.partial(
        _dq_kernel_pipe_interp, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_kv=block_kv, kv_len=kv_len, num_kv_blocks=nk,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, nq),
        in_specs=[q_blk, full_kv, full_kv, q_blk, row_blk, row_blk],
        out_specs=q_blk,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
        interpret=True,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _bwd_pipe_tpu(q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len):
    b, h, sq, d = q.shape
    skv = k.shape[2]
    nq = sq // block_q
    nk = skv // block_kv
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1, keepdims=True
    )

    def dkv_outer(q_hbm, k_ref, v_ref, do_hbm, lse_hbm, delta_hbm,
                  dk_ref, dv_ref, dk_scr, dv_scr, s_scr):
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        j = pl.program_id(2)
        scores, dkv_update, _ = _bwd_stages(sm_scale, causal, block_q, block_kv, kv_len)
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        k_blk = k_ref[0, 0]
        v_blk = v_ref[0, 0]
        t_start = (j * block_kv) // block_q if causal else 0
        n_tiles = nq - t_start

        def inner(qa_ref, qb_ref, do_ref, lse_ref, delta_ref):
            u = pl.program_id(0)
            t = t_start + u

            @pl.when(u < n_tiles)
            def _stage_a():
                s_scr[u % 2] = scores(qa_ref[0, 0], k_blk, t, j)

            @pl.when(u > 0)
            def _stage_b():
                dkv_update(
                    s_scr[(u - 1) % 2], qb_ref[0, 0], do_ref[0, 0], v_blk,
                    lse_ref[0, 0], delta_ref[0, 0], dk_scr, dv_scr,
                )

        # q streams twice at different offsets: once for the t-tile QK^T,
        # once (a step behind) for the t-1 dk accumulation
        idx_a = lambda u: (bi, hi, jnp.minimum(t_start + u, nq - 1), 0)
        idx_b = lambda u: (bi, hi, jnp.minimum(t_start + jnp.maximum(u - 1, 0), nq - 1), 0)
        pipeline = pltpu.emit_pipeline(
            inner,
            grid=(n_tiles + 1,),
            in_specs=[
                pl.BlockSpec((1, 1, block_q, d), idx_a),
                pl.BlockSpec((1, 1, block_q, d), idx_b),
                pl.BlockSpec((1, 1, block_q, d), idx_b),
                pl.BlockSpec((1, 1, block_q, 1), idx_b),
                pl.BlockSpec((1, 1, block_q, 1), idx_b),
            ],
            out_specs=[],
        )
        pipeline(q_hbm, q_hbm, do_hbm, lse_hbm, delta_hbm)
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)

    kv_blk = pl.BlockSpec((1, 1, block_kv, d), lambda b_, h_, j: (b_, h_, j, 0))
    dk, dv = pl.pallas_call(
        dkv_outer,
        grid=(b, h, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY), kv_blk, kv_blk,
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=[kv_blk, kv_blk],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
    )(q, k, v, do, lse, delta)

    def dq_outer(q_ref, k_hbm, v_hbm, do_ref, lse_ref, delta_ref,
                 dq_ref, dq_scr, s_scr):
        bi = pl.program_id(0)
        hi = pl.program_id(1)
        i = pl.program_id(2)
        scores, _, dq_update = _bwd_stages(sm_scale, causal, block_q, block_kv, kv_len)
        dq_scr[...] = jnp.zeros_like(dq_scr)
        q_blk = q_ref[0, 0]
        do_blk = do_ref[0, 0]
        lse_blk = lse_ref[0, 0]
        delta_blk = delta_ref[0, 0]
        tiles = _num_kv_tiles(i, causal, block_q, block_kv, nk)

        def inner(ka_ref, kb_ref, vb_ref):
            t = pl.program_id(0)

            @pl.when(t < tiles)
            def _stage_a():
                s_scr[t % 2] = scores(q_blk, ka_ref[0, 0], i, t)

            @pl.when(t > 0)
            def _stage_b():
                dq_update(
                    s_scr[(t - 1) % 2], kb_ref[0, 0], vb_ref[0, 0],
                    do_blk, lse_blk, delta_blk, dq_scr,
                )

        idx_a = lambda t: (bi, hi, jnp.minimum(t, nk - 1), 0)
        idx_b = lambda t: (bi, hi, jnp.maximum(t - 1, 0), 0)
        pipeline = pltpu.emit_pipeline(
            inner,
            grid=(tiles + 1,),
            in_specs=[
                pl.BlockSpec((1, 1, block_kv, d), idx_a),
                pl.BlockSpec((1, 1, block_kv, d), idx_b),
                pl.BlockSpec((1, 1, block_kv, d), idx_b),
            ],
            out_specs=[],
        )
        pipeline(k_hbm, k_hbm, v_hbm)
        dq_ref[0, 0] = dq_scr[...].astype(dq_ref.dtype)

    q_blk2 = pl.BlockSpec((1, 1, block_q, d), lambda b_, h_, i: (b_, h_, i, 0))
    row_blk2 = pl.BlockSpec((1, 1, block_q, 1), lambda b_, h_, i: (b_, h_, i, 0))
    dq = pl.pallas_call(
        dq_outer,
        grid=(b, h, nq),
        in_specs=[
            q_blk2,
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            q_blk2, row_blk2, row_blk2,
        ],
        out_specs=q_blk2,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((2, block_q, block_kv), jnp.float32),
        ],
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _bwd_pipe(q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    if interpret:
        return _bwd_pipe_interp(
            q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len
        )
    return _bwd_pipe_tpu(
        q, k, v, out, lse, do, causal, sm_scale, block_q, block_kv, kv_len
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash_pipelined(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    out, _ = _fwd_pipe(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret)
    return out


def _flash_pipelined_fwd(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret):
    out, lse = _fwd_pipe(q, k, v, causal, sm_scale, block_q, block_kv, kv_len, interpret)
    return out, (q, k, v, out, lse)


def _flash_pipelined_bwd(causal, sm_scale, block_q, block_kv, kv_len, interpret, res, do):
    q, k, v, out, lse = res
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        groups = hq // hkv
        k_full = jnp.repeat(k, groups, axis=1)
        v_full = jnp.repeat(v, groups, axis=1)
    else:
        groups = 1
        k_full, v_full = k, v
    dq, dk, dv = _bwd_pipe(
        q, k_full, v_full, out, lse, do, causal, sm_scale, block_q, block_kv,
        kv_len, interpret,
    )
    if groups > 1:
        b, _, skv, d = dk.shape
        dk = dk.reshape(b, hkv, groups, skv, d).sum(axis=2)
        dv = dv.reshape(b, hkv, groups, skv, d).sum(axis=2)
    return dq, dk, dv


_flash_pipelined.defvjp(_flash_pipelined_fwd, _flash_pipelined_bwd)


# ------------------------------------------------------------------ public API


_PIPE_BLOCK_KV = 256  # stream tile: >=2 tiles in flight is what buys overlap


def _pipeline_enabled() -> bool:
    from ..core.config import cfg

    return bool(cfg.attn_pipeline)


def _resolve_impl(implementation: Optional[str]) -> str:
    if implementation is not None:
        return implementation
    if jax.default_backend() != "tpu":
        return "xla"
    return "pallas_pipelined" if _pipeline_enabled() else "pallas"


def _pipe_blocks(sq: int, skv: int, block_q: Optional[int], block_kv: Optional[int]):
    """Pipelined defaults: whole-row q tiles (q stays VMEM-resident), small
    streaming kv tiles. Returns None if the shape leaves <2 kv tiles —
    nothing to overlap, the classic single-block kernel is the right tool."""
    bq = min(block_q or 1024, max(sq, 1))
    bkv = min(block_kv or _PIPE_BLOCK_KV, max(skv, 1))
    padded_skv = skv + ((-skv) % bkv)
    if padded_skv // bkv < 2:
        return None
    return bq, bkv


def _pad_seq(x: jax.Array, axis: int, multiple: int) -> jax.Array:
    length = x.shape[axis]
    pad = (-length) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    implementation: Optional[str] = None,
) -> jax.Array:
    """Blockwise flash attention. q (B,Hq,Sq,D); k,v (B,Hkv,Skv,D).

    implementation: "pallas_pipelined" (double-buffered emit_pipeline
    kernel; skewed-schedule interpret driver off-TPU), "pallas" (classic
    kernel; interpreted off-TPU), "xla" (reference), or None = auto: on TPU
    backends the pipelined kernel when `cfg.attn_pipeline` is set and the
    shape gives >=2 kv tiles, else the classic kernel; xla otherwise.

    Block defaults: classic kernel 1024x1024 (clamped to the sequence) —
    at head_dim 64-128 it is grid-overhead-bound and big tiles measured
    3.1x faster than 128x128 on v5e while the f32 score tile (4 MB) still
    fits VMEM. Pipelined kernel 1024x256: q stays VMEM-resident so small
    kv tiles cost no revisit overhead, and >=4 tiles in flight is what
    lets the next tile's QK^T overlap the current tile's softmax.
    """
    implementation = _resolve_impl(implementation)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if implementation == "xla":
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)
    if implementation not in ("pallas", "pallas_pipelined"):
        raise ValueError(f"unknown attention implementation: {implementation!r}")
    if not _HAS_PLTPU:  # pragma: no cover
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale)

    sq, skv = q.shape[2], k.shape[2]
    if causal and sq != skv:
        raise NotImplementedError("causal flash kernel requires Sq == Skv")
    interpret = jax.default_backend() != "tpu"

    if implementation == "pallas_pipelined":
        blocks = _pipe_blocks(sq, skv, block_q, block_kv)
        if blocks is not None:
            bq, bkv = blocks
            qp = _pad_seq(q, 2, bq)
            kp = _pad_seq(k, 2, bkv)
            vp = _pad_seq(v, 2, bkv)
            out = _flash_pipelined(
                qp, kp, vp, causal, sm_scale, bq, bkv, skv, interpret
            )
            if out.shape[2] != sq:
                out = out[:, :, :sq]
            return out
        # single kv tile: fall through to the classic kernel

    block_q = min(block_q or 1024, max(sq, 1))
    block_kv = min(block_kv or 1024, max(skv, 1))
    qp = _pad_seq(q, 2, block_q)
    kp = _pad_seq(k, 2, block_kv)
    vp = _pad_seq(v, 2, block_kv)
    out = _flash(qp, kp, vp, causal, sm_scale, block_q, block_kv, skv, interpret)
    if out.shape[2] != sq:
        out = out[:, :, :sq]
    return out


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    implementation: Optional[str] = None,
) -> "tuple[jax.Array, jax.Array]":
    """Like flash_attention but also returns the per-row logsumexp of the
    scaled scores, shape (B, Hq, Sq, 1) float32 — the carry blockwise
    consumers (ring attention) need to merge partial attentions exactly.

    FORWARD ONLY: no VJP is registered through the lse output; callers
    that need gradients wrap their own (ring_attention's custom_vjp
    recomputes through the einsum reference)."""
    implementation = _resolve_impl(implementation)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if implementation == "xla" or not _HAS_PLTPU:
        _, hq, sq, _ = q.shape
        _, hkv, skv, _ = k.shape
        if hq != hkv:
            groups = hq // hkv
            k = jnp.repeat(k, groups, axis=1)
            v = jnp.repeat(v, groups, axis=1)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * sm_scale
        if causal:
            if sq != skv:
                raise NotImplementedError("causal requires Sq == Skv")
            row = jnp.arange(sq)[:, None]
            col = jnp.arange(skv)[None, :]
            s = jnp.where(col <= row, s, _NEG_INF)
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        out = jnp.einsum("bhqk,bhkd->bhqd", p / l, v.astype(p.dtype))
        return out.astype(q.dtype), m + jnp.log(l)
    sq, skv = q.shape[2], k.shape[2]
    if causal and sq != skv:
        raise NotImplementedError("causal flash kernel requires Sq == Skv")
    interpret = jax.default_backend() != "tpu"
    if implementation == "pallas_pipelined":
        blocks = _pipe_blocks(sq, skv, block_q, block_kv)
        if blocks is not None:
            bq, bkv = blocks
            qp = _pad_seq(q, 2, bq)
            kp = _pad_seq(k, 2, bkv)
            vp = _pad_seq(v, 2, bkv)
            out, lse = _fwd_pipe(
                qp, kp, vp, causal, sm_scale, bq, bkv, skv, interpret
            )
            if out.shape[2] != sq:
                out = out[:, :, :sq]
                lse = lse[:, :, :sq]
            return out, lse
        # single kv tile: fall through to the classic kernel
    block_q = min(block_q or 1024, max(sq, 1))
    block_kv = min(block_kv or 1024, max(skv, 1))
    qp = _pad_seq(q, 2, block_q)
    kp = _pad_seq(k, 2, block_kv)
    vp = _pad_seq(v, 2, block_kv)
    out, lse = _fwd_pallas(
        qp, kp, vp, causal, sm_scale, block_q, block_kv, skv, interpret
    )
    if out.shape[2] != sq:
        out = out[:, :, :sq]
        lse = lse[:, :, :sq]
    return out, lse
