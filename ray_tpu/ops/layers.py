"""Transformer layer primitives: norms, rotary embeddings, gated MLP acts.

These are deliberately plain jnp: XLA fuses elementwise chains into the
surrounding matmuls on TPU, so hand-written Pallas buys nothing here (the
Pallas budget goes to attention and serving kernels instead). Computation is
done in float32 and cast back, the standard mixed-precision discipline for
bf16 training.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def rmsnorm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMSNorm (Llama-family). scale has shape (d,)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32)).astype(dtype)


def layernorm(
    x: jax.Array, scale: jax.Array, bias: jax.Array, eps: float = 1e-5
) -> jax.Array:
    """LayerNorm (GPT-2-family)."""
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    normed = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (normed * scale.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)


def gelu(x: jax.Array) -> jax.Array:
    """tanh-approximated GELU (GPT-2 uses the approximate form)."""
    return jax.nn.gelu(x, approximate=True)


def swiglu(gate: jax.Array, up: jax.Array) -> jax.Array:
    """SwiGLU gate: silu(gate) * up (Llama/Mixtral MLP)."""
    return jax.nn.silu(gate) * up


def rope_frequencies(
    head_dim: int, max_seq: int, theta: float = 10000.0, dtype=jnp.float32
) -> Tuple[jax.Array, jax.Array]:
    """Precompute (cos, sin) tables of shape (max_seq, head_dim // 2)."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs).astype(dtype), jnp.sin(freqs).astype(dtype)


def apply_rope(
    x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array | None = None
) -> jax.Array:
    """Rotary position embedding over the last dim of x (B, H, S, D).

    `positions` (B, S) selects rows of the (max_seq, D/2) tables; defaults to
    arange(S). Uses the split-half convention (matches HF Llama).
    """
    b, _, s, d = x.shape
    if positions is None:
        cos_sel = cos[:s][None, None]  # (1, 1, S, D/2)
        sin_sel = sin[:s][None, None]
    else:
        cos_sel = cos[positions][:, None]  # (B, 1, S, D/2)
        sin_sel = sin[positions][:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos_sel = cos_sel.astype(jnp.float32)
    sin_sel = sin_sel.astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos_sel - x2 * sin_sel, x2 * cos_sel + x1 * sin_sel], axis=-1
    )
    return out.astype(x.dtype)
