"""Training losses: cross entropy with optional z-loss, computed in float32.

The einsum-free formulation (take_along_axis on log-softmax) avoids
materializing one-hot targets — at 50k-128k vocab the one-hot would dominate
HBM traffic in the loss.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def z_loss(logits: jax.Array) -> jax.Array:
    """Auxiliary z-loss (mean logsumexp^2) — stabilizes logit scale at scale."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(jnp.square(lse))


def cross_entropy_loss(
    logits: jax.Array,
    targets: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    z_loss_coeff: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Token-level CE. logits (..., V), targets (...) int. Returns
    (mean_loss, num_tokens). mask=0 drops a position (padding)."""
    logits32 = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits32, axis=-1, keepdims=True)
    # gather the target logit FIRST, then subtract: logz - logits[target]
    # never materializes the (B, S, V) f32 logprobs tensor (the full
    # subtract showed up as an 11 ms/step HBM-bound fusion on v5e)
    tgt = jnp.take_along_axis(logits32, targets[..., None], axis=-1)
    nll = (logz - tgt)[..., 0]
    if mask is not None:
        mask_f = mask.astype(jnp.float32)
        num = jnp.maximum(jnp.sum(mask_f), 1.0)
        loss = jnp.sum(nll * mask_f) / num
    else:
        num = jnp.asarray(nll.size, jnp.float32)
        loss = jnp.mean(nll)
    if z_loss_coeff:
        lse2 = jnp.square(logz[..., 0])
        if mask is not None:
            zl = jnp.sum(lse2 * mask.astype(jnp.float32)) / num
        else:
            zl = jnp.mean(lse2)
        loss = loss + z_loss_coeff * zl
    return loss, num


# bytes the dense loss path keeps live per logit element: the bf16 logits
# from the head matmul, their f32 upcast, and the f32 probs tensor the
# backward softmax materializes (PERF_NOTES.md: the b24->b32 regression)
_DENSE_LOSS_BYTES_PER_LOGIT = 2 + 4 + 4
_AUTO_CHUNK_HBM_FRACTION = 0.8  # leave headroom for params/opt/activations
_CHUNK_CANDIDATES = (512, 256, 128)


def auto_loss_chunk(
    batch_per_device: int,
    seq: int,
    vocab: int,
    hbm_bytes: Optional[int] = None,
) -> int:
    """Pick the fused-linear-CE chunk size (0 = dense) from the logits HBM
    working-set estimate vs the device limit.

    The dense path is ~8% faster when it fits (PERF_NOTES.md: its extra
    recomputed head matmul + scan overhead), so dense wins until the
    (B_local, S, V) logits working set crowds the HBM — measured on v5e
    16G: batch 24 dense 118.5k tok/s, batch 32 REGRESSES to 111k while
    fused holds 110.3k flat. Crossover: estimate > 80% of HBM -> chunk.

    hbm_bytes None = probe the local device (memory_stats().bytes_limit);
    unknown (CPU backends) means no HBM cliff to dodge -> dense."""
    if hbm_bytes is None:
        hbm_bytes = _device_hbm_bytes()
    if not hbm_bytes:
        return 0
    est = batch_per_device * seq * vocab * _DENSE_LOSS_BYTES_PER_LOGIT
    if est <= _AUTO_CHUNK_HBM_FRACTION * hbm_bytes:
        return 0
    for chunk in _CHUNK_CANDIDATES:
        if seq % chunk == 0:
            return chunk
    return 0


def _device_hbm_bytes() -> int:
    try:
        device = jax.local_devices()[0]
        if getattr(device, "platform", "cpu") == "cpu":
            return 0
        stats = device.memory_stats() or {}
        return int(stats.get("bytes_limit", 0))
    except Exception:  # noqa: BLE001 - heuristic must never fail a trace
        return 0


def fused_linear_cross_entropy(
    x: jax.Array,
    head: jax.Array,
    targets: jax.Array,
    *,
    chunk: int = 256,
    mask: Optional[jax.Array] = None,
    z_loss_coeff: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """lm_head matmul + CE fused over sequence chunks: the full
    (B, S, V) logits tensor — the peak-HBM hog of LM training (f32
    copies of it dominate the working set at 50k vocab; measured on
    v5e: batch 24→32 REGRESSES 118.5k→111k tok/s without this) — is
    never materialized. Each chunk's logits live only inside a
    rematerialized scan body (forward AND backward), trading one extra
    head matmul per chunk in the backward (~+10% head flops) for
    O(S/chunk) less loss memory.

    x: (B, S, E) pre-head hidden states; head: (E, V); targets: (B, S).
    Same return contract as cross_entropy_loss. S % chunk must be 0
    (pick chunk from {128, 256, 512}; S here is a static shape).
    """
    b, s, _ = x.shape
    if s % chunk:
        raise ValueError(f"seq len {s} not divisible by loss chunk {chunk}")
    nc = s // chunk
    xs = x.reshape(b, nc, chunk, x.shape[-1]).swapaxes(0, 1)
    ts = targets.reshape(b, nc, chunk).swapaxes(0, 1)
    if mask is not None:
        ms = mask.reshape(b, nc, chunk).swapaxes(0, 1).astype(jnp.float32)
    else:
        ms = jnp.ones((nc, b, chunk), jnp.float32)

    def chunk_loss(xc, tc, mc):
        logits32 = jnp.einsum("bce,ev->bcv", xc, head).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits32, axis=-1, keepdims=True)
        nll = -jnp.take_along_axis(logits32 - logz, tc[..., None], axis=-1)[..., 0]
        return (
            jnp.sum(nll * mc),
            jnp.sum(jnp.square(logz[..., 0]) * mc),
            jnp.sum(mc),
        )

    chunk_loss = jax.checkpoint(chunk_loss)

    def body(carry, xtm):
        xc, tc, mc = xtm
        nll, zl, n = chunk_loss(xc, tc, mc)
        return (carry[0] + nll, carry[1] + zl, carry[2] + n), None

    (total_nll, total_zl, num), _ = jax.lax.scan(
        body, (jnp.zeros(()), jnp.zeros(()), jnp.zeros(())), (xs, ts, ms)
    )
    num = jnp.maximum(num, 1.0)
    loss = total_nll / num
    if z_loss_coeff:
        loss = loss + z_loss_coeff * (total_zl / num)
    return loss, num
