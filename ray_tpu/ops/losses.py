"""Training losses: cross entropy with optional z-loss, computed in float32.

The einsum-free formulation (take_along_axis on log-softmax) avoids
materializing one-hot targets — at 50k-128k vocab the one-hot would dominate
HBM traffic in the loss.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def z_loss(logits: jax.Array) -> jax.Array:
    """Auxiliary z-loss (mean logsumexp^2) — stabilizes logit scale at scale."""
    lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
    return jnp.mean(jnp.square(lse))


def cross_entropy_loss(
    logits: jax.Array,
    targets: jax.Array,
    *,
    mask: Optional[jax.Array] = None,
    z_loss_coeff: float = 0.0,
) -> Tuple[jax.Array, jax.Array]:
    """Token-level CE. logits (..., V), targets (...) int. Returns
    (mean_loss, num_tokens). mask=0 drops a position (padding)."""
    logits32 = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits32, axis=-1, keepdims=True)
    logprobs = logits32 - logz
    nll = -jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    if mask is not None:
        mask_f = mask.astype(jnp.float32)
        num = jnp.maximum(jnp.sum(mask_f), 1.0)
        loss = jnp.sum(nll * mask_f) / num
    else:
        num = jnp.asarray(nll.size, jnp.float32)
        loss = jnp.mean(nll)
    if z_loss_coeff:
        lse2 = jnp.square(logz[..., 0])
        if mask is not None:
            zl = jnp.sum(lse2 * mask.astype(jnp.float32)) / num
        else:
            zl = jnp.mean(lse2)
        loss = loss + z_loss_coeff * zl
    return loss, num
