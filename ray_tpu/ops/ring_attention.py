"""Ring attention: exact attention over sequence shards on a mesh axis.

NEW capability relative to the reference — czxxing/ray has no sequence/
context parallelism at all (SURVEY.md §2.4: grep for ring_attention/
ulysses/context_parallel is empty). This is the TPU-native design: shard
the sequence over the `sp` mesh axis, keep Q local, and rotate K/V shards
around the ring with `ppermute` (ICI neighbor hops) while accumulating
blockwise online softmax (Liu et al., Ring Attention; the flash-attention
recurrence across devices instead of across VMEM tiles).

Per ring step each device computes one (Q_local × KV_visiting) block —
compute overlaps the next KV transfer in XLA's schedule. Memory per device
is O(S/n · S/n) per block, never O(S²); sequence length scales linearly
with the ring size.

Differentiable: the step loop is a `lax.scan` and `ppermute` transposes to
the reverse rotation, so jax.grad gives the ring-parallel backward
automatically (each device re-sees every KV shard in reverse order).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .._jax_compat import shard_map

P = PartitionSpec

_NEG_INF = -1e30


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
):
    """Per-shard body (call under shard_map). q/k/v: (B, H, S_local, D)."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape

    q32 = q.astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]  # send kv to the next host

    def _block(m_prev, l_prev, acc, k_cur, v_cur, kv_idx, masked: bool):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q32, k_cur.astype(jnp.float32)
        ) * sm_scale
        if masked:
            q_pos = my_idx * s_local + lax.broadcasted_iota(
                jnp.int32, (1, 1, s_local, s_local), 2
            )
            kv_pos = kv_idx * s_local + lax.broadcasted_iota(
                jnp.int32, (1, 1, s_local, s_local), 3
            )
            s = jnp.where(kv_pos <= q_pos, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32)
        )
        return m_new, l_new, acc

    def step(carry, step_idx):
        m_prev, l_prev, acc, k_cur, v_cur = carry
        # whose kv shard do we hold after `step_idx` rotations?
        kv_idx = (my_idx - step_idx) % n

        if causal:
            # Causal block skipping (Liu et al.): a KV shard entirely in
            # this device's future contributes nothing — branch to a
            # no-op instead of computing a fully-masked block, so the
            # ring does ~n/2 block matmuls instead of n. The diagonal
            # block is the only one that needs the intra-block mask.
            branch = jnp.where(
                kv_idx > my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2)
            )
            m_new, l_new, acc = lax.switch(
                branch,
                [
                    lambda *a: (m_prev, l_prev, acc),  # future: skip
                    lambda *a: _block(*a, masked=True),  # diagonal
                    lambda *a: _block(*a, masked=False),  # past: full
                ],
                m_prev, l_prev, acc, k_cur, v_cur, kv_idx,
            )
        else:
            m_new, l_new, acc = _block(
                m_prev, l_prev, acc, k_cur, v_cur, kv_idx, masked=False
            )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc, k_next, v_next), None

    m0 = jnp.full((b, h, s_local, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    (m, l, acc, _, _), _ = lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n)
    )
    safe_l = jnp.where(l == 0.0, 1.0, l)
    return (acc / safe_l).astype(q.dtype)


def _ring_fused_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float,
    block_impl: Optional[str] = None,
):
    """Fused per-shard body: each ring block runs through the Pallas flash
    kernel (ops/attention.py — online softmax INSIDE the block stays in
    VMEM, no (S_local × S_local) f32 logits in HBM) and blocks merge
    across ring steps by logsumexp reweighting, which is algebraically
    the same online-softmax recurrence the einsum body carries as
    (m, l, acc). The diagonal block is the causal kernel; past blocks the
    full kernel; future blocks skip (Liu et al. causal skipping).

    The kernel choice rides flash_attention_with_lse's auto-resolution:
    with cfg.attn_pipeline set (default) each ring block runs the
    double-buffered emit_pipeline kernel on TPU, so `ring_fused_speedup`
    inherits the pipelined inner block without a separate code path."""
    from .attention import flash_attention_with_lse

    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, s_local, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def merge(o_acc, lse_acc, o_new, lse_new):
        lse = jnp.logaddexp(lse_acc, lse_new)
        w_acc = jnp.exp(lse_acc - lse)
        w_new = jnp.exp(lse_new - lse)
        return o_acc * w_acc + o_new.astype(jnp.float32) * w_new, lse

    def diag(o_acc, lse_acc, k_cur, v_cur):
        o, lse = flash_attention_with_lse(
            q, k_cur, v_cur, causal=True, sm_scale=sm_scale,
            implementation=block_impl,
        )
        return merge(o_acc, lse_acc, o, lse)

    def full(o_acc, lse_acc, k_cur, v_cur):
        o, lse = flash_attention_with_lse(
            q, k_cur, v_cur, causal=False, sm_scale=sm_scale,
            implementation=block_impl,
        )
        return merge(o_acc, lse_acc, o, lse)

    def step(carry, step_idx):
        o_acc, lse_acc, k_cur, v_cur = carry
        kv_idx = (my_idx - step_idx) % n
        if causal:
            branch = jnp.where(
                kv_idx > my_idx, 0, jnp.where(kv_idx == my_idx, 1, 2)
            )
            o_acc, lse_acc = lax.switch(
                branch,
                [lambda o, l, *_: (o, l), diag, full],
                o_acc, lse_acc, k_cur, v_cur,
            )
        else:
            o_acc, lse_acc = full(o_acc, lse_acc, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (o_acc, lse_acc, k_next, v_next), None

    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    lse0 = jnp.full((b, h, s_local, 1), _NEG_INF, jnp.float32)
    (o_acc, lse_acc, _, _), _ = lax.scan(
        step, (o0, lse0, k, v), jnp.arange(n)
    )
    return o_acc.astype(q.dtype)


def _make_fused_body(axis_name: str, causal: bool, sm_scale: float,
                     block_impl: Optional[str] = None):
    """Fused forward + einsum-reference backward. The flash kernel's VJP
    does not thread through the cross-step lse merge, so the backward
    recomputes the whole ring via the differentiable einsum body — same
    collective pattern, transposed ppermutes, mathematically identical."""

    @jax.custom_vjp
    def body(q, k, v):
        return _ring_fused_local(
            q, k, v, axis_name=axis_name, causal=causal, sm_scale=sm_scale,
            block_impl=block_impl,
        )

    def fwd(q, k, v):
        return body(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, pullback = jax.vjp(
            lambda q_, k_, v_: _ring_attention_local(
                q_, k_, v_, axis_name=axis_name, causal=causal,
                sm_scale=sm_scale,
            ),
            q, k, v,
        )
        return pullback(g)

    body.defvjp(fwd, bwd)
    return body


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    sm_scale: Optional[float] = None,
    impl: str = "fused",
    block_impl: Optional[str] = None,
) -> jax.Array:
    """Sequence-parallel exact attention. q (B,Hq,S,D), k/v (B,Hkv,S,D);
    S must divide by mesh.shape[axis]. Returns (B,Hq,S,D) sharded like q.

    impl: "fused" (default — per-block Pallas flash kernel on TPU, fused
    XLA reference elsewhere) or "einsum" (the original blockwise einsum
    body; also the backward path of "fused"). block_impl picks the flash
    kernel inside each fused ring block (None = flash_attention's auto
    resolution, i.e. the pipelined kernel when cfg.attn_pipeline is on)."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    hq, hkv = q.shape[1], k.shape[1]
    if hq != hkv:
        groups = hq // hkv
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    n = mesh.shape[axis]
    if q.shape[2] % n:
        raise ValueError(f"seq {q.shape[2]} not divisible by {axis}={n}")

    spec = P(None, None, axis, None)
    if impl == "fused":
        body = _make_fused_body(axis, causal, sm_scale, block_impl)
    elif impl == "einsum":
        body = functools.partial(
            _ring_attention_local, axis_name=axis, causal=causal,
            sm_scale=sm_scale,
        )
    else:
        raise ValueError(f"unknown ring impl {impl!r}")
    fn = shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)


def ring_attention_sharded(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """Convenience: device_put inputs seq-sharded, run, leave output sharded."""
    spec = NamedSharding(mesh, P(None, None, axis, None))
    q = jax.device_put(q, spec)
    k = jax.device_put(k, spec)
    v = jax.device_put(v, spec)
    return ring_attention(q, k, v, mesh=mesh, axis=axis, causal=causal)
