"""Search spaces + variant generation (reference parity: tune/search/ —
sample.py domains, basic_variant.py BasicVariantGenerator)."""

from __future__ import annotations

import dataclasses
import itertools
import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class Domain:
    def sample(self, rng: np.random.Generator) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return float(rng.uniform(self.low, self.high))


@dataclasses.dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return float(math.exp(rng.uniform(math.log(self.low), math.log(self.high))))


@dataclasses.dataclass
class RandInt(Domain):
    low: int
    high: int  # exclusive

    def sample(self, rng):
        return int(rng.integers(self.low, self.high))


@dataclasses.dataclass
class Choice(Domain):
    options: Sequence[Any]

    def sample(self, rng):
        return self.options[int(rng.integers(0, len(self.options)))]


@dataclasses.dataclass
class GridSearch:
    """Marker: expanded as a cross-product, not sampled."""

    values: Sequence[Any]


# public constructors (ray.tune.uniform etc.)
def uniform(low: float, high: float) -> Uniform:
    return Uniform(low, high)


def loguniform(low: float, high: float) -> LogUniform:
    return LogUniform(low, high)


def randint(low: int, high: int) -> RandInt:
    return RandInt(low, high)


def choice(options: Sequence[Any]) -> Choice:
    return Choice(list(options))


def grid_search(values: Sequence[Any]) -> GridSearch:
    return GridSearch(list(values))


def generate_variants(
    param_space: Dict[str, Any], num_samples: int, seed: int = 0
) -> Iterator[Dict[str, Any]]:
    """Cross-product of grid axes × num_samples draws of random domains.
    Plain values pass through. Accepts both the constructor form
    (tune.grid_search([...])) and the reference's literal dict form
    ({"grid_search": [...]})."""
    param_space = {
        k: (
            GridSearch(list(v["grid_search"]))
            if isinstance(v, dict) and set(v) == {"grid_search"}
            else v
        )
        for k, v in param_space.items()
    }
    grid_keys = [k for k, v in param_space.items() if isinstance(v, GridSearch)]
    grid_values = [param_space[k].values for k in grid_keys]
    rng = np.random.default_rng(seed)

    grids = list(itertools.product(*grid_values)) if grid_keys else [()]
    for _ in range(num_samples):
        for combo in grids:
            config: Dict[str, Any] = {}
            for k, v in param_space.items():
                if isinstance(v, GridSearch):
                    config[k] = combo[grid_keys.index(k)]
                elif isinstance(v, Domain):
                    config[k] = v.sample(rng)
                else:
                    config[k] = v
            yield config
