"""Trial schedulers: early stopping + population-based training.

Reference parity: tune/schedulers/async_hyperband.py:19 ASHAScheduler,
median_stopping_rule.py, pbt.py:221 PopulationBasedTraining. Decisions run
on every report: CONTINUE, STOP, or an Exploit directive (PBT) telling the
controller to restart the trial from a donor's checkpoint with a mutated
config.
"""

from __future__ import annotations

import collections
import dataclasses
import random
from typing import Any, Callable, Dict, List, Union

CONTINUE = "CONTINUE"
STOP = "STOP"


@dataclasses.dataclass
class Exploit:
    """PBT verdict: clone `donor_trial`'s checkpoint, adopt `new_config`,
    and continue training (reference pbt.py _exploit)."""

    donor_trial: str
    new_config: Dict[str, Any]


Verdict = Union[str, Exploit]


class TrialScheduler:
    def on_result(self, trial_id: str, result: Dict) -> Verdict:
        return CONTINUE

    def on_trial_config(self, trial_id: str, config: Dict) -> None:
        """Controller tells the scheduler each trial's (current) config."""


class FIFOScheduler(TrialScheduler):
    """No early stopping."""


class ASHAScheduler(TrialScheduler):
    """Async Successive Halving: at each rung (grace_period · rf^k steps of
    `time_attr`), stop a trial whose metric is outside the top 1/rf of
    completed rung peers."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: int = 4,
        max_t: int = 100,
    ):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric per trial
        self._rung_records: Dict[int, Dict[str, float]] = collections.defaultdict(dict)
        self._stopped: set = set()

    def on_result(self, trial_id: str, result: Dict) -> str:
        if trial_id in self._stopped:
            return STOP
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        value = float(value)
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung:
                break
            records = self._rung_records[rung]
            if trial_id not in records:
                records[trial_id] = value
                if not self._in_top_fraction(records, value):
                    decision = STOP
        if decision == STOP:
            self._stopped.add(trial_id)
        return decision

    def _in_top_fraction(self, records: Dict[str, float], value: float) -> bool:
        values = sorted(records.values(), reverse=(self.mode == "max"))
        k = max(1, len(values) // self.rf)
        cutoff = values[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose latest metric is worse than the median of peers'
    running averages at the same step count."""

    def __init__(self, metric: str, mode: str = "max", grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._history[trial_id].append(float(value))
        t = result.get(self.time_attr, len(self._history[trial_id]))
        if t < self.grace or len(self._history) < 3:
            return CONTINUE
        means = {
            tid: sum(vs) / len(vs) for tid, vs in self._history.items() if vs
        }
        peer_means = sorted(means.values())
        median = peer_means[len(peer_means) // 2]
        mine = means[trial_id]
        worse = mine < median if self.mode == "max" else mine > median
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference tune/schedulers/pbt.py:221): every
    `perturbation_interval` steps of `time_attr`, a trial in the bottom
    `quantile_fraction` of the population exploits a top-quantile donor —
    it clones the donor's checkpoint and config — then explores by
    mutating hyperparameters (resample with `resample_probability`, else
    perturb numeric values by 0.8x / 1.2x).
    """

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        perturbation_interval: int = 4,
        hyperparam_mutations: Dict[str, Any] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: int = 0,
    ):
        assert mode in ("max", "min")
        assert hyperparam_mutations, "PBT requires hyperparam_mutations"
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = dict(hyperparam_mutations)
        self.quantile = quantile_fraction
        self.resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._configs: Dict[str, Dict] = {}
        self._scores: Dict[str, float] = {}  # latest metric per trial
        self._last_perturb: Dict[str, int] = collections.defaultdict(int)
        self.num_exploits = 0

    def on_trial_config(self, trial_id: str, config: Dict) -> None:
        self._configs[trial_id] = dict(config)

    def on_result(self, trial_id: str, result: Dict) -> Verdict:
        value = result.get(self.metric)
        t = result.get(self.time_attr)
        if value is None or t is None:
            return CONTINUE
        self._scores[trial_id] = float(value)
        if t - self._last_perturb[trial_id] < self.interval:
            return CONTINUE
        self._last_perturb[trial_id] = t
        ranked = sorted(
            self._scores.items(), key=lambda kv: kv[1],
            reverse=(self.mode == "max"),
        )
        if len(ranked) < 2:
            return CONTINUE
        k = max(1, int(len(ranked) * self.quantile))
        top = [tid for tid, _ in ranked[:k]]
        bottom = {tid for tid, _ in ranked[-k:]}
        if trial_id not in bottom or trial_id in top:
            return CONTINUE
        donor = self._rng.choice(top)
        new_config = self._explore(self._configs.get(donor, {}))
        self._configs[trial_id] = dict(new_config)
        self.num_exploits += 1
        return Exploit(donor_trial=donor, new_config=new_config)

    def _explore(self, donor_config: Dict) -> Dict:
        out = dict(donor_config)
        for name, spec in self.mutations.items():
            resample = self._rng.random() < self.resample_prob
            if callable(spec):
                if resample or name not in out:
                    out[name] = spec()
                else:
                    out[name] = _perturb(out[name], self._rng)
            elif isinstance(spec, (list, tuple)):
                if resample or name not in out:
                    out[name] = self._rng.choice(list(spec))
                else:
                    choices = list(spec)
                    idx = choices.index(out[name]) if out[name] in choices else 0
                    idx = max(0, min(len(choices) - 1, idx + self._rng.choice([-1, 1])))
                    out[name] = choices[idx]
            else:
                raise TypeError(
                    f"mutation spec for {name!r} must be a callable or a "
                    f"list of choices, got {type(spec).__name__}"
                )
        return out


def _perturb(value, rng: "random.Random"):
    if isinstance(value, (int, float)):
        factor = rng.choice([0.8, 1.2])
        new = value * factor
        return int(round(new)) if isinstance(value, int) else new
    return value
