"""Trial schedulers: early stopping on intermediate results.

Reference parity: tune/schedulers/async_hyperband.py:19 ASHAScheduler,
median_stopping_rule.py. Decisions run on every report: CONTINUE or STOP.
"""

from __future__ import annotations

import collections
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial_id: str, result: Dict) -> str:
        return CONTINUE


class FIFOScheduler(TrialScheduler):
    """No early stopping."""


class ASHAScheduler(TrialScheduler):
    """Async Successive Halving: at each rung (grace_period · rf^k steps of
    `time_attr`), stop a trial whose metric is outside the top 1/rf of
    completed rung peers."""

    def __init__(
        self,
        metric: str,
        mode: str = "max",
        time_attr: str = "training_iteration",
        grace_period: int = 1,
        reduction_factor: int = 4,
        max_t: int = 100,
    ):
        assert mode in ("max", "min")
        self.metric = metric
        self.mode = mode
        self.time_attr = time_attr
        self.rf = reduction_factor
        self.rungs: List[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric per trial
        self._rung_records: Dict[int, Dict[str, float]] = collections.defaultdict(dict)
        self._stopped: set = set()

    def on_result(self, trial_id: str, result: Dict) -> str:
        if trial_id in self._stopped:
            return STOP
        t = result.get(self.time_attr)
        value = result.get(self.metric)
        if t is None or value is None:
            return CONTINUE
        value = float(value)
        decision = CONTINUE
        for rung in self.rungs:
            if t < rung:
                break
            records = self._rung_records[rung]
            if trial_id not in records:
                records[trial_id] = value
                if not self._in_top_fraction(records, value):
                    decision = STOP
        if decision == STOP:
            self._stopped.add(trial_id)
        return decision

    def _in_top_fraction(self, records: Dict[str, float], value: float) -> bool:
        values = sorted(records.values(), reverse=(self.mode == "max"))
        k = max(1, len(values) // self.rf)
        cutoff = values[k - 1]
        return value >= cutoff if self.mode == "max" else value <= cutoff


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose latest metric is worse than the median of peers'
    running averages at the same step count."""

    def __init__(self, metric: str, mode: str = "max", grace_period: int = 1,
                 time_attr: str = "training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.time_attr = time_attr
        self._history: Dict[str, List[float]] = collections.defaultdict(list)

    def on_result(self, trial_id: str, result: Dict) -> str:
        value = result.get(self.metric)
        if value is None:
            return CONTINUE
        self._history[trial_id].append(float(value))
        t = result.get(self.time_attr, len(self._history[trial_id]))
        if t < self.grace or len(self._history) < 3:
            return CONTINUE
        means = {
            tid: sum(vs) / len(vs) for tid, vs in self._history.items() if vs
        }
        peer_means = sorted(means.values())
        median = peer_means[len(peer_means) // 2]
        mine = means[trial_id]
        worse = mine < median if self.mode == "max" else mine > median
        return STOP if worse else CONTINUE
