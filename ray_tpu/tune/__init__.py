"""ray_tpu.tune — experiment sweeps (Ray Tune equivalent).

Search spaces (grid/random domains), trial schedulers (ASHA, median
stopping), and a Tuner running concurrent trial actors with early stop.
Report from a trainable with ray_tpu.train.report(...).
"""

from ..train.session import get_checkpoint, report  # noqa: F401  (tune aliases)
from .schedulers import (  # noqa: F401
    Exploit,
    PopulationBasedTraining,
    ASHAScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    TrialScheduler,
)
from .search import (  # noqa: F401
    choice,
    generate_variants,
    grid_search,
    loguniform,
    randint,
    uniform,
)
from .tuner import ResultGrid, Trial, TrialStatus, TuneConfig, Tuner  # noqa: F401
