"""Tuner: trial FSM + concurrent execution + scheduler-driven control.

Reference parity: tune/tune.py Tuner → TuneController (tune/execution/
tune_controller.py:68) event loop over the actor manager, with trial
checkpointing + experiment-state persistence (tune/execution/
experiment_state.py) and PBT exploit/explore (tune/schedulers/pbt.py:221).
Trials are TrainWorker actors (reused from ray_tpu.train) reporting
through the session; the controller polls, feeds the scheduler, restarts
failed trials from their last checkpoint, and executes PBT exploits by
cloning a donor's checkpoint into the victim's trial dir.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, List, Optional

import cloudpickle

from .. import api
from ..core.exceptions import ActorDiedError, GetTimeoutError, TaskError
from ..train.worker_group import TrainWorker
from .schedulers import CONTINUE, STOP, Exploit, FIFOScheduler, TrialScheduler
from .search import generate_variants


class TrialStatus(enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    TERMINATED = "TERMINATED"  # finished normally
    STOPPED = "STOPPED"  # early-stopped by the scheduler
    ERRORED = "ERRORED"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: TrialStatus = TrialStatus.PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    actor: Any = None
    result_ref: Any = None
    cursor: int = 0
    trial_dir: Optional[str] = None
    num_failures: int = 0
    num_exploits: int = 0


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent: int = 4
    metric: Optional[str] = None
    mode: str = "max"
    scheduler: Optional[TrialScheduler] = None
    seed: int = 0
    resources_per_trial: Optional[Dict[str, float]] = None
    # storage for trial checkpoints + experiment state (enables restore);
    # None = a fresh temp dir per fit()
    storage_path: Optional[str] = None
    # restart a crashed trial from its last checkpoint up to this many times
    max_failures: int = 0


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Trial:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric configured")
        scored = [t for t in self.trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda t: t.last_result[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def __len__(self) -> int:
        return len(self.trials)

    def __iter__(self):
        return iter(self.trials)


class Tuner:
    """Tuner(trainable, param_space=..., tune_config=...).fit()"""

    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        *,
        param_space: Dict[str, Any],
        tune_config: Optional[TuneConfig] = None,
        _trials: Optional[List[Trial]] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space
        self.config = tune_config or TuneConfig()
        self._restored_trials = _trials

    @classmethod
    def restore(cls, path: str, trainable: Callable) -> "Tuner":
        """Resume an interrupted experiment from its storage dir: finished
        trials keep their results; unfinished ones re-run, resuming from
        their last checkpoint (reference: Tuner.restore +
        experiment_state.py)."""
        state_file = os.path.join(path, "experiment_state.pkl")
        with open(state_file, "rb") as f:
            state = cloudpickle.load(f)
        cfg: TuneConfig = state["config"]
        cfg.storage_path = path
        trials: List[Trial] = []
        for rec in state["trials"]:
            trial = Trial(
                trial_id=rec["trial_id"],
                config=rec["config"],
                status=TrialStatus(rec["status"]),
                last_result=rec["last_result"],
                history=rec["history"],
                error=rec["error"],
                cursor=0,
                trial_dir=os.path.join(path, rec["trial_id"]),
                num_failures=rec["num_failures"],
            )
            if trial.status in (TrialStatus.PENDING, TrialStatus.RUNNING,
                                TrialStatus.ERRORED):
                # will re-run; the trainable resumes via tune.get_checkpoint()
                trial.status = TrialStatus.PENDING
                trial.history = []
                trial.last_result = {}
            trials.append(trial)
        return cls(
            trainable, param_space=state["param_space"], tune_config=cfg,
            _trials=trials,
        )

    # ------------------------------------------------------------------ fit

    def fit(self, poll_interval: float = 0.05) -> ResultGrid:
        cfg = self.config
        scheduler = cfg.scheduler or FIFOScheduler()
        exp_dir = cfg.storage_path or tempfile.mkdtemp(prefix="ray_tpu_tune_")
        cfg.storage_path = exp_dir
        os.makedirs(exp_dir, exist_ok=True)
        if self._restored_trials is not None:
            trials = self._restored_trials
        else:
            trials = [
                Trial(
                    trial_id=f"trial_{i:05d}",
                    config=variant,
                    trial_dir=os.path.join(exp_dir, f"trial_{i:05d}"),
                )
                for i, variant in enumerate(
                    generate_variants(self.param_space, cfg.num_samples, cfg.seed)
                )
            ]
        for t in trials:
            scheduler.on_trial_config(t.trial_id, t.config)
        pending = [t for t in trials if t.status == TrialStatus.PENDING]
        running: List[Trial] = []
        actor_cls = api.remote(TrainWorker)

        def launch(trial: Trial) -> None:
            trial.actor = actor_cls.options(
                max_concurrency=2,
                resources=cfg.resources_per_trial or {"CPU": 1.0},
                num_cpus=0,
                name=f"tune-{trial.trial_id}-{trial.num_failures}-{trial.num_exploits}",
            ).remote(0, 1, trial.trial_id, trial.trial_dir)
            trial.result_ref = trial.actor.run.remote(self.trainable, trial.config)
            trial.status = TrialStatus.RUNNING
            running.append(trial)

        MAX_POLL_TIMEOUTS = 3
        poll_timeouts: Dict[str, int] = {}
        try:
            self._run_loop(
                cfg, scheduler, trials, pending, running, launch,
                poll_interval, poll_timeouts, MAX_POLL_TIMEOUTS, exp_dir,
            )
        finally:
            self._save_state(exp_dir, trials)
            # Never abandon live trial actors, whatever escapes the loop.
            for trial in running:
                try:
                    api.kill(trial.actor)
                except Exception:
                    pass
        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _save_state(self, exp_dir: str, trials: List[Trial]) -> None:
        state = {
            "config": self.config,
            "param_space": self.param_space,
            "trials": [
                {
                    "trial_id": t.trial_id,
                    "config": t.config,
                    "status": t.status.value,
                    "last_result": t.last_result,
                    "history": t.history,
                    "error": t.error,
                    "num_failures": t.num_failures,
                }
                for t in trials
            ],
        }
        tmp = os.path.join(exp_dir, "experiment_state.pkl.tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, os.path.join(exp_dir, "experiment_state.pkl"))

    def _clone_checkpoint(self, donor: Trial, victim: Trial) -> None:
        """PBT exploit: victim adopts the donor's latest checkpoint."""
        from ..train.session import list_checkpoints

        if victim.trial_dir is None:
            return
        ckpts = list_checkpoints(donor.trial_dir)
        if not ckpts:
            return
        os.makedirs(victim.trial_dir, exist_ok=True)
        # wipe the victim's own checkpoints so the donor's is the latest
        for f in list_checkpoints(victim.trial_dir):
            os.unlink(os.path.join(victim.trial_dir, f))
        src = os.path.join(donor.trial_dir, ckpts[-1])
        shutil.copy(src, os.path.join(victim.trial_dir, ckpts[-1]))

    def _restart(
        self, trial: Trial, launch, running: List[Trial],
        poll_timeouts: Optional[Dict[str, int]] = None,
    ) -> None:
        try:
            api.kill(trial.actor)
        except Exception:
            pass
        if trial in running:
            running.remove(trial)
        trial.cursor = 0
        if poll_timeouts is not None:
            # fresh actor, fresh patience: the new incarnation gets the
            # full max_poll_timeouts budget
            poll_timeouts.pop(trial.trial_id, None)
        launch(trial)

    def _run_loop(
        self, cfg, scheduler, trials, pending, running, launch,
        poll_interval, poll_timeouts, max_poll_timeouts, exp_dir,
    ) -> None:
        trial_by_id = {t.trial_id: t for t in trials}
        last_saved = 0.0
        while pending or running:
            while pending and len(running) < cfg.max_concurrent:
                launch(pending.pop(0))

            for trial in list(running):
                try:
                    poll = api.get(trial.actor.poll.remote(trial.cursor), timeout=30)
                except GetTimeoutError:
                    # Trial is blocking its actor past the poll timeout;
                    # retry, and only declare it failed after repeats.
                    n = poll_timeouts.get(trial.trial_id, 0) + 1
                    poll_timeouts[trial.trial_id] = n
                    if n >= max_poll_timeouts:
                        self._fail_or_retry(
                            trial, f"poll timed out {n} times", launch, running,
                            poll_timeouts,
                        )
                    continue
                except (ActorDiedError, TaskError) as e:
                    self._fail_or_retry(trial, repr(e), launch, running, poll_timeouts)
                    continue
                poll_timeouts.pop(trial.trial_id, None)
                decision: Any = CONTINUE
                for metrics, _ckpt, _rank, _ts in poll["reports"]:
                    trial.cursor += 1
                    metrics.setdefault("training_iteration", trial.cursor)
                    trial.history.append(metrics)
                    trial.last_result = metrics
                    verdict = scheduler.on_result(trial.trial_id, metrics)
                    if verdict == STOP:
                        decision = STOP
                    elif isinstance(verdict, Exploit):
                        decision = verdict
                if decision == STOP:
                    trial.status = TrialStatus.STOPPED
                    api.kill(trial.actor)
                    running.remove(trial)
                elif isinstance(decision, Exploit):
                    donor = trial_by_id.get(decision.donor_trial)
                    if donor is not None:
                        trial.config = dict(decision.new_config)
                        trial.num_exploits += 1
                        self._clone_checkpoint(donor, trial)
                        scheduler.on_trial_config(trial.trial_id, trial.config)
                        self._restart(trial, launch, running, poll_timeouts)
                elif poll["done"]:
                    if poll["error"]:
                        self._fail_or_retry(
                            trial, poll["error"], launch, running, poll_timeouts
                        )
                    else:
                        trial.status = TrialStatus.TERMINATED
                        api.kill(trial.actor)
                        running.remove(trial)
            now = time.monotonic()
            if now - last_saved > 1.0:
                self._save_state(exp_dir, trials)
                last_saved = now
            if running:
                time.sleep(poll_interval)

    def _fail_or_retry(
        self, trial, error: str, launch, running,
        poll_timeouts: Optional[Dict[str, int]] = None,
    ) -> None:
        trial.num_failures += 1
        if trial.num_failures <= self.config.max_failures:
            # resume from the trial's last checkpoint (the trainable picks
            # it up via tune.get_checkpoint())
            self._restart(trial, launch, running, poll_timeouts)
            return
        trial.status = TrialStatus.ERRORED
        trial.error = error
        try:
            api.kill(trial.actor)
        except Exception:
            pass
        if trial in running:
            running.remove(trial)
