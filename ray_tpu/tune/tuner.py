"""Tuner: trial FSM + concurrent execution + scheduler-driven early stop.

Reference parity: tune/tune.py Tuner → TuneController (tune/execution/
tune_controller.py:68) event loop over the actor manager. Trials are
TrainWorker actors (reused from ray_tpu.train) reporting through the
session; the controller polls, feeds the scheduler, and kills trials the
scheduler stops.
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Any, Callable, Dict, List, Optional

from .. import api
from ..core.exceptions import ActorDiedError, GetTimeoutError, TaskError
from ..train.worker_group import TrainWorker
from .schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from .search import generate_variants


class TrialStatus(enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    TERMINATED = "TERMINATED"  # finished normally
    STOPPED = "STOPPED"  # early-stopped by the scheduler
    ERRORED = "ERRORED"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: TrialStatus = TrialStatus.PENDING
    last_result: Dict[str, Any] = dataclasses.field(default_factory=dict)
    history: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    actor: Any = None
    result_ref: Any = None
    cursor: int = 0


@dataclasses.dataclass
class TuneConfig:
    num_samples: int = 1
    max_concurrent: int = 4
    metric: Optional[str] = None
    mode: str = "max"
    scheduler: Optional[TrialScheduler] = None
    seed: int = 0
    resources_per_trial: Optional[Dict[str, float]] = None


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str], mode: str):
        self.trials = trials
        self._metric = metric
        self._mode = mode

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Trial:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("no metric configured")
        scored = [t for t in self.trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        key = lambda t: t.last_result[metric]  # noqa: E731
        return max(scored, key=key) if mode == "max" else min(scored, key=key)

    def __len__(self) -> int:
        return len(self.trials)

    def __iter__(self):
        return iter(self.trials)


class Tuner:
    """Tuner(trainable, param_space=..., tune_config=...).fit()"""

    def __init__(
        self,
        trainable: Callable[[Dict[str, Any]], Any],
        *,
        param_space: Dict[str, Any],
        tune_config: Optional[TuneConfig] = None,
    ):
        self.trainable = trainable
        self.param_space = param_space
        self.config = tune_config or TuneConfig()

    def fit(self, poll_interval: float = 0.05) -> ResultGrid:
        cfg = self.config
        scheduler = cfg.scheduler or FIFOScheduler()
        trials = [
            Trial(trial_id=f"trial_{i:05d}", config=variant)
            for i, variant in enumerate(
                generate_variants(self.param_space, cfg.num_samples, cfg.seed)
            )
        ]
        pending = list(trials)
        running: List[Trial] = []
        actor_cls = api.remote(TrainWorker)

        def launch(trial: Trial) -> None:
            trial.actor = actor_cls.options(
                max_concurrency=2,
                resources=cfg.resources_per_trial or {"CPU": 1.0},
                num_cpus=0,
                name=f"tune-{trial.trial_id}",
            ).remote(0, 1, trial.trial_id)
            trial.result_ref = trial.actor.run.remote(self.trainable, trial.config)
            trial.status = TrialStatus.RUNNING
            running.append(trial)

        MAX_POLL_TIMEOUTS = 3
        poll_timeouts: Dict[str, int] = {}
        try:
            self._run_loop(
                cfg, scheduler, pending, running, launch,
                poll_interval, poll_timeouts, MAX_POLL_TIMEOUTS,
            )
        finally:
            # Never abandon live trial actors, whatever escapes the loop.
            for trial in running:
                try:
                    api.kill(trial.actor)
                except Exception:
                    pass
        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _run_loop(
        self, cfg, scheduler, pending, running, launch,
        poll_interval, poll_timeouts, max_poll_timeouts,
    ) -> None:
        while pending or running:
            while pending and len(running) < cfg.max_concurrent:
                launch(pending.pop(0))

            for trial in list(running):
                try:
                    poll = api.get(trial.actor.poll.remote(trial.cursor), timeout=30)
                except GetTimeoutError:
                    # Trial is blocking its actor past the poll timeout;
                    # retry, and only declare it failed after repeats.
                    n = poll_timeouts.get(trial.trial_id, 0) + 1
                    poll_timeouts[trial.trial_id] = n
                    if n >= max_poll_timeouts:
                        trial.status = TrialStatus.ERRORED
                        trial.error = f"poll timed out {n} times"
                        api.kill(trial.actor)
                        running.remove(trial)
                    continue
                except (ActorDiedError, TaskError) as e:
                    trial.status = TrialStatus.ERRORED
                    trial.error = repr(e)
                    running.remove(trial)
                    continue
                poll_timeouts.pop(trial.trial_id, None)
                decision = CONTINUE
                for metrics, _ckpt, _rank, _ts in poll["reports"]:
                    trial.cursor += 1
                    metrics.setdefault("training_iteration", trial.cursor)
                    trial.history.append(metrics)
                    trial.last_result = metrics
                    verdict = scheduler.on_result(trial.trial_id, metrics)
                    if verdict == STOP:
                        decision = STOP
                if decision == STOP:
                    trial.status = TrialStatus.STOPPED
                    api.kill(trial.actor)
                    running.remove(trial)
                elif poll["done"]:
                    if poll["error"]:
                        trial.status = TrialStatus.ERRORED
                        trial.error = poll["error"]
                    else:
                        trial.status = TrialStatus.TERMINATED
                    api.kill(trial.actor)
                    running.remove(trial)
            if running:
                time.sleep(poll_interval)
