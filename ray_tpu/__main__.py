"""`python -m ray_tpu` entry point (reference: the `ray` console script)."""

from .cli import main

raise SystemExit(main())
