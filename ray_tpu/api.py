"""Public API: init/shutdown, @remote, get/put/wait, actors, placement groups.

Mirrors the reference surface (/root/reference/python/ray/_private/worker.py:
ray.init :1286, ray.get :2718, ray.put :2854, ray.wait :2919, @ray.remote
:3307; python/ray/remote_function.py:308 RemoteFunction._remote;
python/ray/actor.py ActorClass).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from .core import runtime as _rt
from .core.resources import ResourceDict
from .core.runtime import ActorHandle, ObjectRef
from .core.scheduler import PlacementGroup


# ------------------------------------------------------------------- lifecycle


def init(
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[ResourceDict] = None,
    num_nodes: int = 1,
    object_store_capacity: Optional[int] = None,
    spill_dir: Optional[str] = None,
    detect_accelerators: bool = True,
    ignore_reinit_error: bool = True,
    labels: Optional[Dict[str, str]] = None,
    head: bool = False,
    address: Optional[str] = None,
    cluster_token: Optional[str] = None,
    gcs_port: int = 0,
    _system_config: Optional[Dict[str, Any]] = None,
) -> _rt.Runtime:
    """Start (or connect to) the in-process cluster runtime.

    `num_nodes > 1` creates multiple logical nodes in one process — the same
    multi-node-without-a-cluster trick the reference uses for testing
    (python/ray/cluster_utils.py:135).

    `head=True` makes this process a real multi-process cluster head: its
    GCS is served over RPC and other OS processes join with
    `init(address="host:port")` or `ray_tpu start --address` (reference:
    `ray start --head`, python/ray/scripts/scripts.py:706). The joined
    processes' resources appear in `cluster_resources()` and tasks
    dispatch to them over RPC (core/cluster.py).

    `_system_config` overrides central config flags for this process (the
    reference's ray.init(_system_config=...) escape hatch over
    common/ray_config_def.h); see `ray_tpu.core.config.cfg.describe()`.
    """
    if _system_config and _rt.is_initialized():
        # Components capture flags at construction; silently accepting an
        # override that can no longer take effect would be a lie (the
        # reference likewise rejects _system_config on reconnect).
        raise RuntimeError(
            "_system_config cannot be applied: the runtime is already "
            "initialized. Call shutdown() first."
        )
    if _system_config:
        from .core.config import cfg

        cfg.set(**_system_config)
    if _rt.is_initialized():
        if not ignore_reinit_error:
            raise RuntimeError("ray_tpu.init() called twice")
        return _rt.get_runtime()
    return _rt.init_runtime(
        num_cpus=num_cpus,
        num_tpus=num_tpus,
        resources=resources,
        num_nodes=num_nodes,
        object_store_capacity=object_store_capacity,
        spill_dir=spill_dir,
        detect_accelerators=detect_accelerators,
        labels=labels,
        head=head,
        address=address,
        cluster_token=cluster_token,
        gcs_port=gcs_port,
    )


def shutdown() -> None:
    _rt.shutdown_runtime()


def is_initialized() -> bool:
    return _rt.is_initialized()


def _runtime() -> _rt.Runtime:
    return _rt.get_or_init_runtime()


# --------------------------------------------------------------------- objects


def put(value: Any) -> ObjectRef:
    return _runtime().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None) -> Any:
    return _runtime().get(refs, timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    return _runtime().wait(refs, num_returns=num_returns, timeout=timeout)


def cancel(ref: ObjectRef) -> bool:
    return _runtime().cancel(ref)


# ----------------------------------------------------------------------- tasks


_DEFAULT_TASK_OPTIONS: Dict[str, Any] = dict(
    num_cpus=None,
    num_tpus=None,
    resources=None,
    num_returns=1,
    max_retries=0,
    retry_exceptions=False,
    scheduling_strategy="DEFAULT",
    name=None,
    runtime_env=None,
    executor="thread",  # "process" → pooled OS worker (GIL-free CPU work)
    stream_max_backlog=None,  # streaming producers: block when consumer lags
    locality_hint=None,  # NodeID: soft preference for the block-holding node
)

_DEFAULT_ACTOR_OPTIONS: Dict[str, Any] = dict(
    num_cpus=None,
    num_tpus=None,
    resources=None,
    max_restarts=0,
    max_concurrency=1,
    name=None,
    namespace="default",
    lifetime=None,
    scheduling_strategy="DEFAULT",
    executor="thread",  # "process" → dedicated OS worker process
    runtime_env=None,  # env_vars / working_dir for process actors
)


def _build_resources(options: Dict[str, Any], default_cpu: float) -> ResourceDict:
    res: ResourceDict = dict(options.get("resources") or {})
    num_cpus = options.get("num_cpus")
    num_tpus = options.get("num_tpus")
    res["CPU"] = float(num_cpus) if num_cpus is not None else default_cpu
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    return res


class RemoteFunction:
    """Handle produced by @remote on a function (reference
    remote_function.py:121)."""

    def __init__(self, func, options: Dict[str, Any]):
        self._func = func
        self._options = options
        functools.update_wrapper(self, func)

    def options(self, **overrides) -> "RemoteFunction":
        merged = dict(self._options)
        unknown = set(overrides) - set(_DEFAULT_TASK_OPTIONS)
        if unknown:
            raise TypeError(f"Unknown task options: {sorted(unknown)}")
        merged.update(overrides)
        return RemoteFunction(self._func, merged)

    def remote(self, *args, **kwargs):
        opts = self._options
        return _runtime().submit_task(
            self._func,
            args,
            kwargs,
            name=opts.get("name") or self._func.__name__,
            num_returns=opts["num_returns"],
            resources=_build_resources(opts, default_cpu=1.0),
            max_retries=opts["max_retries"],
            retry_exceptions=opts["retry_exceptions"],
            scheduling_strategy=opts["scheduling_strategy"],
            runtime_env=opts.get("runtime_env"),
            executor=opts.get("executor", "thread"),
            stream_max_backlog=opts.get("stream_max_backlog"),
            locality_hint=opts.get("locality_hint"),
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._func.__name__} cannot be called directly; "
            f"use .remote()"
        )


class ActorClass:
    """Handle produced by @remote on a class (reference actor.py ActorClass)."""

    def __init__(self, cls: type, options: Dict[str, Any]):
        self._cls = cls
        self._options = options

    def options(self, **overrides) -> "ActorClass":
        merged = dict(self._options)
        unknown = set(overrides) - set(_DEFAULT_ACTOR_OPTIONS)
        if unknown:
            raise TypeError(f"Unknown actor options: {sorted(unknown)}")
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        opts = self._options
        return _runtime().create_actor(
            self._cls,
            args,
            kwargs,
            resources=_build_resources(opts, default_cpu=1.0),
            max_restarts=opts["max_restarts"],
            max_concurrency=opts["max_concurrency"],
            name=opts.get("name"),
            namespace=opts.get("namespace", "default"),
            scheduling_strategy=opts["scheduling_strategy"],
            lifetime=opts.get("lifetime"),
            executor=opts.get("executor", "thread"),
            runtime_env=opts.get("runtime_env"),
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use .remote()"
        )


def remote(*args, **kwargs):
    """`@remote` / `@remote(num_cpus=..., num_tpus=..., resources=...)`.

    Works on functions (→ RemoteFunction) and classes (→ ActorClass), like
    the reference @ray.remote (worker.py:3307).
    """

    def decorate(target):
        if isinstance(target, type):
            opts = dict(_DEFAULT_ACTOR_OPTIONS)
            unknown = set(kwargs) - set(opts)
            if unknown:
                raise TypeError(f"Unknown actor options: {sorted(unknown)}")
            opts.update(kwargs)
            return ActorClass(target, opts)
        opts = dict(_DEFAULT_TASK_OPTIONS)
        unknown = set(kwargs) - set(opts)
        if unknown:
            raise TypeError(f"Unknown task options: {sorted(unknown)}")
        opts.update(kwargs)
        return RemoteFunction(target, opts)

    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only")
    return decorate


# ---------------------------------------------------------------------- actors


def kill(handle: ActorHandle, *, no_restart: bool = True) -> None:
    _runtime().kill_actor(handle, no_restart=no_restart)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    return _runtime().get_actor(name, namespace)


def list_actors() -> List[Dict[str, Any]]:
    return _runtime().list_actors()


# ------------------------------------------------------------ placement groups


def placement_group(
    bundles: Sequence[ResourceDict], strategy: str = "PACK", name: str = "",
    max_reschedules: Optional[int] = None,
) -> PlacementGroup:
    """Reserve a gang of bundles. `max_reschedules` bounds how many
    re-reservation attempts the group gets after a bundle host dies
    before it is marked FAILED (None = cfg.pg_reschedule_budget)."""
    return _runtime().create_placement_group(
        bundles, strategy, name, max_reschedules=max_reschedules
    )


def remove_placement_group(pg: PlacementGroup) -> None:
    _runtime().remove_placement_group(pg)


# ----------------------------------------------------------------- cluster info


def cluster_resources() -> ResourceDict:
    return _runtime().cluster_resources()


def available_resources() -> ResourceDict:
    return _runtime().available_resources()


def nodes() -> List[Dict[str, Any]]:
    return [
        {
            "node_id": n.node_id.hex(),
            "alive": n.alive,
            "is_head": n.is_head,
            "resources": n.resources.total,
            "labels": dict(n.labels),
        }
        for n in _runtime().scheduler.nodes()
    ]
