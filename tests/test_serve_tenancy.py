"""Multi-tenant overload protection drills.

Coverage for the tenancy tentpole: weighted-fair queueing at both
admission choke points (starvation-freedom, weight-proportional share,
priority tiers), token-bucket quotas with honest computed Retry-After,
preemptible decode lanes (trim-to-frontier park + token-exact resume,
prefix-shared pages never corrupted), tenant context propagation through
the handle path, and the noisy-tenant + replica-kill chaos capstone with
zero untyped errors.
"""

import pickle
import threading
import time

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core import chaos
from ray_tpu.core.chaos import ChaosInjectedError
from ray_tpu.core.config import cfg
from ray_tpu.core.exceptions import (
    BackPressureError,
    RequestTimeoutError,
    unwrap_error,
)
from ray_tpu.models import forward, get_config, init_params
from ray_tpu.serve import tenancy
from ray_tpu.serve.llm.paged import PagedConfig
from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine
from ray_tpu.serve.tenancy import FairQueue, _TokenBucket


@pytest.fixture(autouse=True)
def _clean_tenancy():
    tenancy.reset()
    yield
    tenancy.reset()
    cfg.reset()


def _greedy_reference(config, params, prompt, n):
    tokens = list(prompt)
    for _ in range(n):
        logits = forward(params, np.asarray([tokens], dtype=np.int32), config)
        tokens.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return tokens[len(prompt):]


def _tiny_engine(model="llama-tiny", seed=0, **over):
    config = get_config(model)
    params = init_params(config, jax.random.PRNGKey(seed))
    defaults = dict(
        max_slots=4,
        paged=PagedConfig(
            page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2
        ),
    )
    defaults.update(over)
    return config, params, PagedLLMEngine(
        config, params, PagedEngineConfig(**defaults)
    )


# ------------------------------------------------------------- fair queue


def test_fairqueue_weight_proportional_share():
    """A weight-4 tenant drains ~4x faster than a weight-1 tenant under
    sustained backlog (SCFQ virtual finish tags)."""
    fq = FairQueue()
    for i in range(40):
        fq.push(("heavy", i), "heavy", weight=4.0)
    for i in range(40):
        fq.push(("light", i), "light", weight=1.0)
    first = [fq.pop()[0] for _ in range(25)]
    heavy = first.count("heavy")
    # exact SCFQ share is 20/5; allow slack for tie-breaks
    assert 18 <= heavy <= 22, first


def test_fairqueue_starvation_free():
    """A single item from a light tenant lands near the front even when
    a flooding tenant queued hundreds of items first."""
    fq = FairQueue()
    for i in range(200):
        fq.push(("flood", i), "flood")
    # flood's lane has raced ahead in virtual time; a newcomer starts at
    # the tier clock and its first finish tag is immediately competitive
    for _ in range(5):
        fq.pop()
    fq.push(("light", 0), "light")
    drained = [fq.pop()[0] for _ in range(5)]
    assert "light" in drained, drained
    assert len(fq) == 200 - 5 + 1 - 5


def test_fairqueue_priority_tiers_strict():
    """Higher priority tiers always pop first, regardless of how much
    virtual time the lower tier has accumulated."""
    fq = FairQueue()
    for i in range(10):
        fq.push(("low", i), "bulk", priority=0)
    fq.push(("high", 0), "paid", priority=1)
    fq.push(("high", 1), "paid", priority=1)
    assert fq.pop() == ("high", 0)
    assert fq.pop() == ("high", 1)
    assert fq.pop() == ("low", 0)


def test_fairqueue_requeue_keeps_place():
    """requeue() returns an item to the front of its lane with no fresh
    virtual-time charge (deferred admissions never pay twice)."""
    fq = FairQueue()
    fq.push("a1", "a")
    fq.push("a2", "a")
    head = fq.pop()
    assert head == "a1"
    fq.requeue(head, "a")
    assert fq.peek() == "a1"
    assert fq.pop() == "a1" and fq.pop() == "a2"


def test_fairqueue_pop_if_head_and_remove():
    fq = FairQueue()
    fq.push("x", "t")
    fq.push("y", "t")
    assert not fq.pop_if_head("y")
    assert fq.pop_if_head("x")
    assert fq.remove("y")
    assert not fq.remove("y")
    assert len(fq) == 0 and fq.pop() is None


def test_fairqueue_work_conserving_drain():
    fq = FairQueue()
    for t in ("a", "b", "c"):
        for i in range(3):
            fq.push((t, i), t)
    assert len(fq.drain()) == 9
    assert len(fq) == 0


# ------------------------------------------------------------ token bucket


def test_token_bucket_computes_honest_retry_after():
    bucket = _TokenBucket(rate=1.0, burst=2.0)
    assert bucket.acquire() is None
    assert bucket.acquire() is None
    retry = bucket.acquire()
    assert retry is not None and 0.5 < retry <= 1.01


def test_quota_check_registry_and_defaults():
    tenancy.set_tenant("metered", quota_rps=1.0, quota_burst=1.0)
    assert tenancy.quota_check("metered") is None
    retry = tenancy.quota_check("metered")
    assert retry is not None and retry > 0
    # undeclared tenants ride the config default (0 = unlimited)
    for _ in range(50):
        assert tenancy.quota_check("anyone") is None


def test_backpressure_error_pickles_retry_after():
    err = BackPressureError("over quota", retry_after_s=2.5)
    clone = pickle.loads(pickle.dumps(err))
    assert isinstance(clone, BackPressureError)
    assert clone.retry_after_s == 2.5
    assert "over quota" in str(clone)


def test_http_status_maps_computed_retry_after():
    from ray_tpu.serve.llm.openai import _http_status_for

    code, _etype, retry = _http_status_for(
        BackPressureError("x", retry_after_s=3.2)
    )
    assert (code, retry) == (429, 4)
    # no estimate → the historical 1-second default
    code, _etype, retry = _http_status_for(BackPressureError("x"))
    assert (code, retry) == (429, 1)


def test_resolve_http_tenant_header_and_api_key():
    tenancy.set_tenant("acme", priority=2, api_key="sk-acme-1")
    assert tenancy.resolve_http_tenant(
        {"x-tenant": "acme"}) == ("acme", 2)
    assert tenancy.resolve_http_tenant(
        {"Authorization": "Bearer sk-acme-1"}) == ("acme", 2)
    assert tenancy.resolve_http_tenant(
        {"x-tenant": "acme", "x-priority": "5"}) == ("acme", 5)
    assert tenancy.resolve_http_tenant({}) == (None, None)


# --------------------------------------------------------- engine admission


def test_engine_quota_shed_is_typed_with_retry_after():
    """Over-quota submits shed with BackPressureError carrying the
    bucket's actual refill time; admitted traffic is unaffected."""
    tenancy.set_tenant("free", quota_rps=0.1, quota_burst=1.0)
    _config, _params, engine = _tiny_engine()
    try:
        ok = engine.submit([3, 1, 4], max_tokens=2, tenant="free")
        with pytest.raises(BackPressureError) as e:
            engine.submit([3, 1, 4], max_tokens=2, tenant="free")
        assert e.value.retry_after_s is not None
        assert e.value.retry_after_s > 0
        assert engine.metrics["shed"] >= 1
        # other tenants are not collateral damage
        other = engine.submit([2, 7, 1], max_tokens=2, tenant="other")
        assert len(ok.result()) == 2
        assert len(other.result()) == 2
    finally:
        engine.shutdown()


def test_engine_priority_queue_order():
    """With the only slot busy and preemption off, a later high-priority
    submit is admitted ahead of earlier low-priority backlog (strict
    tiers at the engine admit queue)."""
    cfg.set(serve_lane_preemption=False)
    _config, _params, engine = _tiny_engine(max_slots=1)
    try:
        blocker = engine.submit([9, 9, 9], max_tokens=24, tenant="blk")
        lows = [
            engine.submit([5, 5, i], max_tokens=2, tenant="bulk", priority=0)
            for i in range(3)
        ]
        high = engine.submit([8, 8, 8], max_tokens=2, tenant="paid",
                             priority=1)
        done = []
        lock = threading.Lock()

        def drain(name, stream):
            stream.result()
            with lock:
                done.append(name)

        threads = [
            threading.Thread(target=drain, args=(f"low{i}", s))
            for i, s in enumerate(lows)
        ] + [threading.Thread(target=drain, args=("high", high))]
        for t in threads:
            t.start()
        blocker.result()
        for t in threads:
            t.join(timeout=60)
        assert done[0] == "high", done
    finally:
        engine.shutdown()


def test_engine_sheds_expired_request_at_admit_pop():
    """A request whose deadline expired while queued is failed at the
    admit-queue pop — it never consumes a slot ahead of live traffic."""
    _config, _params, engine = _tiny_engine(max_slots=1)
    try:
        blocker = engine.submit([1, 2, 3], max_tokens=24)
        doomed = engine.submit([4, 5, 6], max_tokens=4,
                               deadline_ts=time.time() + 0.15)
        live = engine.submit([6, 5, 4], max_tokens=2)
        time.sleep(0.2)  # doomed expires while still queued
        with pytest.raises(RequestTimeoutError):
            doomed.result(timeout=60)
        assert len(live.result(timeout=60)) == 2
        blocker.result(timeout=60)
        assert engine.metrics["timeouts"] >= 1
    finally:
        engine.shutdown()


# ------------------------------------------------------- lane preemption


def test_lane_preemption_token_exact_resume_and_shared_pages_survive():
    """The acceptance drill: a high-priority admission preempts a
    low-priority decode lane. The victim is trimmed to its emitted
    frontier (never mid-flight), parked, re-admitted, and its stream
    resumes token-exact; pages it shared with the prefix cache are only
    un-refcounted, never corrupted — a later cache hit still reproduces
    the reference continuation."""
    # small decode blocks keep the victim mid-dispatch (preemptible) for
    # most of its decode, like a real long generation would be
    config, params, engine = _tiny_engine(max_slots=1,
                                          decode_block_steps=2)
    try:
        shared = [11, 22, 33, 44, 55, 66, 77, 88,
                  12, 23, 34, 45, 56, 67, 78, 89]  # 2 full pages
        # warm the prefix cache so the victim's first pages are shared
        warm = engine.submit(list(shared), max_tokens=4, tenant="warm")
        warm_tokens = warm.result(timeout=60)
        assert warm_tokens == _greedy_reference(config, params, shared, 4)

        victim_prompt = list(shared) + [7, 14, 21, 28, 35, 42, 49, 56]
        victim = engine.submit(victim_prompt, max_tokens=24,
                               tenant="bulk", priority=0)
        # wait until the victim is actually decoding before the preemptor
        victim_iter = iter(victim)
        first = next(victim_iter)

        high_prompt = [101, 102, 103, 104, 105, 106, 107, 108]
        high = engine.submit(high_prompt, max_tokens=6,
                             tenant="paid", priority=1)
        high_tokens = high.result(timeout=60)
        assert high_tokens == _greedy_reference(
            config, params, high_prompt, 6)

        rest = list(victim_iter)
        victim_tokens = [first] + rest
        assert victim_tokens == _greedy_reference(
            config, params, victim_prompt, 24)

        assert engine.metrics["lane_preemptions"] >= 1
        assert engine.metrics["lane_resumes"] >= 1
        assert engine.metrics["preempted_pages"] > 0

        # the shared prefix pages survived the victim's page release:
        # a fresh request over the warm prompt still matches reference
        again = engine.submit(list(shared), max_tokens=4, tenant="warm2")
        assert again.result(timeout=60) == warm_tokens
    finally:
        engine.shutdown()


def test_lane_preemption_restores_allocator_refcounts():
    """After a preemption round fully drains, every page is back in the
    free pool except the prefix cache's own pins (no leaked refs)."""
    _config, _params, engine = _tiny_engine(max_slots=1,
                                            decode_block_steps=2)
    try:
        victim = engine.submit([4] * 12, max_tokens=20,
                               tenant="bulk", priority=0)
        it = iter(victim)
        next(it)
        high = engine.submit([9] * 12, max_tokens=4,
                             tenant="paid", priority=1)
        high.result(timeout=60)
        list(it)
        assert engine.metrics["lane_preemptions"] >= 1
        deadline = time.time() + 10
        while time.time() < deadline:
            stats = engine.stats()
            # total allocatable = num_pages - 1 (page 0 reserved)
            if stats["pages_free"] + stats["prefix_cache_pages"] == 63:
                break
            time.sleep(0.05)
        stats = engine.stats()
        assert stats["pages_free"] + stats["prefix_cache_pages"] == 63, stats
    finally:
        engine.shutdown()


def test_lane_preemption_under_page_pool_pressure():
    """The page-pressure trigger (`_reclaim_pages`), distinct from the
    all-slots-wedged trigger: a free slot exists, but the pool cannot
    cover the high-priority admission because a low-priority lane holds
    nearly every page. The victim is marked, drains, parks, and its
    pages fund the admission; both streams finish token-exact."""
    # 7 allocatable pages (page 0 reserved). The victim's prompt spans 5
    # and its decode grows the lane to all 7; inflight=1 paces dispatch
    # so the lane is still mid-decode when the preemptor arrives.
    config, params, engine = _tiny_engine(
        max_slots=2,
        decode_block_steps=2,
        max_inflight_blocks=1,
        paged=PagedConfig(
            page_size=8, num_pages=8, max_pages_per_slot=8, chunk_pages=2
        ),
    )
    try:
        victim_prompt = [(i * 7 + 3) % 97 for i in range(40)]  # 5 pages
        victim = engine.submit(victim_prompt, max_tokens=16,
                               tenant="bulk", priority=0)
        it = iter(victim)
        first = next(it)  # lane decoding: >=6 pages held, <2 free

        high_prompt = [201, 202, 203, 204, 205, 206, 207, 208]
        high = engine.submit(high_prompt, max_tokens=4,
                             tenant="paid", priority=1)
        high_tokens = high.result(timeout=60)
        assert high_tokens == _greedy_reference(
            config, params, high_prompt, 4)

        victim_tokens = [first] + list(it)
        assert victim_tokens == _greedy_reference(
            config, params, victim_prompt, 16)

        # preemption came from page pressure, not a slot wedge: a slot
        # was free the whole time, and the admission page-stalled first
        assert engine.metrics["lane_preemptions"] >= 1
        assert engine.metrics["lane_resumes"] >= 1
        assert engine.metrics["page_stalls"] >= 1

        deadline = time.time() + 10
        while time.time() < deadline:
            stats = engine.stats()
            if stats["pages_free"] + stats["prefix_cache_pages"] == 7:
                break
            time.sleep(0.05)
        stats = engine.stats()
        assert stats["pages_free"] + stats["prefix_cache_pages"] == 7, stats
    finally:
        engine.shutdown()


def test_lane_preemption_config_gate():
    """serve_lane_preemption=False disables parking entirely: the
    high-priority request waits instead (strict queue order only)."""
    cfg.set(serve_lane_preemption=False)
    _config, _params, engine = _tiny_engine(max_slots=1,
                                            decode_block_steps=2)
    try:
        victim = engine.submit([4] * 8, max_tokens=12, tenant="bulk")
        high = engine.submit([9] * 8, max_tokens=2,
                             tenant="paid", priority=1)
        victim.result(timeout=60)
        high.result(timeout=60)
        assert engine.metrics["lane_preemptions"] == 0
    finally:
        engine.shutdown()


# ---------------------------------------------------- tenant SLO accounting


def test_per_tenant_ttft_windows_feed_slo_monitor():
    from ray_tpu.util.watchdog import ServeSLOMonitor

    tenancy.set_tenant("gold", ttft_slo_s=0.000001)  # everything violates
    tenancy.observe_ttft("gold", 0.5)
    tenancy.observe_ttft("gold", 0.7)
    tenancy.observe_ttft("casual", 0.5)  # no objective → never violates
    monitor = ServeSLOMonitor()
    out = monitor.check()
    assert out["ttft_p99:gold"] >= 0.5
    report = monitor.attainment_report()
    assert report["ttft_p99:gold"]["violated"] == 1
    assert report["ttft_p99:gold"]["attainment"] == 0.0
    assert report["ttft_p99:casual"]["violated"] == 0
    # window drained: a second check sees no new samples
    assert "ttft_p99:gold" not in monitor.check()
    assert tenancy.any_tenant_slo()


def test_engine_reports_tenant_ttft():
    _config, _params, engine = _tiny_engine()
    try:
        engine.submit([5, 6, 7], max_tokens=2, tenant="acme").result()
        window = tenancy.drain_ttft_window()
        assert "acme" in window and len(window["acme"]) == 1
        assert window["acme"][0] > 0
    finally:
        engine.shutdown()


# --------------------------------------------------------------- serve plane


@pytest.fixture()
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    chaos.clear_chaos()
    serve.shutdown()
    ray_tpu.shutdown()


def test_tenant_context_rides_the_handle_path(rt):
    """handle.options(tenant=, priority=) surfaces in the replica's
    ambient serve context, exactly like deadlines do."""
    @serve.deployment
    class WhoAmI:
        def __call__(self, _payload):
            return (serve.get_request_tenant(), serve.get_request_priority())

    handle = serve.run(WhoAmI.options(name="whoami").bind())
    assert ray_tpu.get(handle.remote(None), timeout=30) == (None, None)
    caller = handle.options(tenant="acme", priority=3)
    assert ray_tpu.get(caller.remote(None), timeout=30) == ("acme", 3)
    # options() must not leak across calls
    assert ray_tpu.get(handle.remote(None), timeout=30) == (None, None)


def test_router_parks_dispatch_in_priority_order(rt):
    """When a replica is saturated, parked resilient dispatches are
    granted strictly by priority tier: the high-priority call runs
    before a low-priority call parked earlier."""
    gate = threading.Event()
    order = []

    @serve.deployment(max_ongoing_requests=1)
    class Gated:
        def __call__(self, tag):
            if tag == "blocker":
                gate.wait(timeout=30)
            order.append(tag)
            return tag

    handle = serve.run(Gated.options(name="gated").bind())
    caller = handle.options(timeout_s=30)
    blocker = caller.remote("blocker")
    time.sleep(0.3)  # blocker occupies the only ongoing slot
    low = caller.options(tenant="bulk", priority=0).remote("low")
    time.sleep(0.2)  # low parks first
    high = caller.options(tenant="paid", priority=1).remote("high")
    time.sleep(0.2)
    gate.set()
    assert ray_tpu.get(blocker, timeout=30) == "blocker"
    assert ray_tpu.get(high, timeout=30) == "high"
    assert ray_tpu.get(low, timeout=30) == "low"
    assert order.index("high") < order.index("low"), order


def test_router_park_overflow_sheds_typed_with_drain_estimate(rt):
    """Past max_queued_requests the router sheds synchronously with the
    typed error; Retry-After rides the exception when the drain-rate
    estimator has samples (never a bogus value when it doesn't)."""
    gate = threading.Event()

    @serve.deployment(max_ongoing_requests=1, max_queued_requests=1)
    class Tight:
        def __call__(self, tag):
            gate.wait(timeout=30)
            return tag

    handle = serve.run(Tight.options(name="tight").bind())
    caller = handle.options(timeout_s=30)
    first = caller.remote(0)
    time.sleep(0.3)
    second = caller.remote(1)  # parks (the 1 queued slot)
    time.sleep(0.2)
    with pytest.raises(BackPressureError) as e:
        caller.remote(2)
    retry = e.value.retry_after_s
    assert retry is None or retry >= 1
    gate.set()
    assert sorted(
        ray_tpu.get([first, second], timeout=30)) == [0, 1]


def test_chaos_capstone_noisy_tenant_replica_kill_zero_untyped(rt):
    """Capstone: a flooding low-priority tenant plus a mid-run replica
    kill. Every request either succeeds or fails with a TYPED error —
    overload and failure recovery compose, nothing hangs."""
    @serve.deployment(num_replicas=2, max_ongoing_requests=4,
                      max_queued_requests=32)
    class Drill:
        def __call__(self, payload):
            time.sleep(0.01)
            return payload * 2

    handle = serve.run(Drill.options(name="tdrill").bind())
    deadline = time.time() + 15
    while time.time() < deadline:
        if serve.status()["tdrill"]["live_replicas"] == 2:
            break
        time.sleep(0.05)
    noisy = handle.options(timeout_s=30, max_retries=4,
                           tenant="noisy", priority=0)
    paid = handle.options(timeout_s=30, max_retries=4,
                          tenant="paid", priority=1)
    refs = []
    shed_at_submit = 0

    def submit(caller, i):
        nonlocal shed_at_submit
        try:
            refs.append((i, caller.remote(i)))
        except BackPressureError as e:
            # synchronous shed past the parked-dispatch bound: typed,
            # tenant-attributed, with a sane (or absent) Retry-After
            assert e.retry_after_s is None or e.retry_after_s >= 1
            shed_at_submit += 1

    for i in range(80):
        submit(noisy, i)
    for i in range(80, 100):
        submit(paid, i)
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["tdrill"]
    ray_tpu.kill(state.replicas[0])
    for i in range(100, 140):
        submit(noisy, i)
    ok, typed, hung = 0, 0, []
    for i, ref in refs:
        try:
            assert ray_tpu.get(ref, timeout=60) == i * 2
            ok += 1
        except ray_tpu.GetTimeoutError:
            hung.append(i)
        except Exception as e:  # noqa: BLE001 - drill classification
            cause = unwrap_error(e)
            assert isinstance(
                cause, (RequestTimeoutError, BackPressureError,
                        ChaosInjectedError)
            ), f"request {i} failed with untyped {cause!r}"
            typed += 1
    assert not hung, f"hung requests: {hung}"
    # burst submission overruns the parked-dispatch bound by design: the
    # acceptance bar is full accounting — every request either succeeded
    # or shed/failed TYPED, and overload protection actually engaged
    assert ok >= 30, (ok, typed, shed_at_submit)
    assert shed_at_submit > 0
    assert ok + typed + shed_at_submit == 140
    # the killed replica is replaced and the deployment still serves
    assert ray_tpu.get(handle.remote(7), timeout=30) == 14
