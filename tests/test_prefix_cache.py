"""Prefix/KV-cache reuse: refcounted allocator, page-level prefix cache,
copy-on-write guard, and the engine's end-to-end reuse path.

The load-bearing invariants (vLLM's automatic prefix caching, adapted to
the flat TPU page pool):
- a physical page may back several block tables at once; it returns to
  the free list only when the LAST holder releases it;
- the cache holds exactly one pin per entry, live slots take their own
  refs through `lookup`, and eviction never touches a page a slot holds;
- a slot about to WRITE a shared page copies it first (COW) — never
  observable through the public API today (sharing is page-granular and
  writes are forward-only), so these tests manufacture sharing directly.
"""

import time

import jax
import numpy as np
import pytest

from ray_tpu.core.exceptions import RequestTimeoutError
from ray_tpu.models import get_config, init_params
from ray_tpu.serve.llm.paged import PagedConfig, PageAllocator, PrefixCache
from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

from tests.test_paged_engine import _greedy_reference


def _prefix_engine(model="llama-tiny", seed=0, **paged_over):
    config = get_config(model)
    params = init_params(config, jax.random.PRNGKey(seed))
    paged = dict(
        page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2,
        prefix_cache=True,
    )
    paged.update(paged_over)
    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(max_slots=4, paged=PagedConfig(**paged)),
    )
    return config, params, engine


# ----------------------------------------------------------------- allocator


def test_refcount_shared_page_freed_only_at_last_holder():
    a = PageAllocator(num_pages=8)
    pages = a.alloc(2)
    assert a.refcount(pages[0]) == 1
    a.share([pages[0]])
    assert a.refcount(pages[0]) == 2
    a.free(pages)          # slot retires: shared page keeps one holder
    assert a.refcount(pages[0]) == 1
    assert a.refcount(pages[1]) == 0
    assert a.available == 6
    a.free([pages[0]])     # last holder lets go: page recycles
    assert a.available == 7
    assert pages[0] in a.alloc(7)


def test_share_of_unallocated_page_raises():
    a = PageAllocator(num_pages=4)
    with pytest.raises(ValueError, match="unallocated"):
        a.share([2])
    p = a.alloc(1)
    a.free(p)
    with pytest.raises(ValueError, match="unallocated"):
        a.share(p)  # freed: resurrecting it would corrupt the next owner


def test_scratch_page_never_refcounted():
    a = PageAllocator(num_pages=4)
    a.share([0])
    a.free([0])
    a.free([0])
    assert a.refcount(0) == 0
    assert a.available == 3
    assert 0 not in a.alloc(3)


def test_double_free_guard_survives_refcounting():
    a = PageAllocator(num_pages=4)
    p = a.alloc(1)
    a.free(p)
    a.free(p)  # buggy second free: ignored, not a second free-list entry
    assert a.available == 3
    got = a.alloc(3)
    assert len(set(got)) == 3


# -------------------------------------------------------------- prefix cache


def test_lookup_leaves_at_least_one_token_to_prefill():
    """Even a fully cached prompt must re-prefill its last token — its
    logits seed sampling (vLLM caps its hit identically)."""
    a = PageAllocator(num_pages=16)
    cache = PrefixCache(a, page_size=4)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    pages = a.alloc(2)
    assert cache.register(prompt, pages) == 2
    assert a.refcount(pages[0]) == 2  # cache pin on top of the slot's ref
    # exactly 2 pages of prompt: at most ONE page may be reused
    hit = cache.lookup(prompt)
    assert hit == [pages[0]]
    assert a.refcount(pages[0]) == 3  # caller took its own ref
    # longer prompt sharing the prefix reuses both pages
    hit2 = cache.lookup(prompt + [9])
    assert hit2 == pages
    stats = cache.stats()
    assert stats["hits"] == 3.0 and stats["hit_rate"] > 0.5


def test_lookup_stops_at_first_divergent_page():
    a = PageAllocator(num_pages=16)
    cache = PrefixCache(a, page_size=4)
    pages = a.alloc(3)
    cache.register([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12], pages)
    hit = cache.lookup([1, 2, 3, 4, 99, 6, 7, 8, 9, 10, 11, 12, 13])
    assert hit == [pages[0]]  # page 2 diverges: chain hash misses


def test_eviction_is_lru_and_skips_pinned_pages():
    a = PageAllocator(num_pages=16)
    cache = PrefixCache(a, page_size=4)
    pa = a.alloc(1)
    pb = a.alloc(1)
    cache.register([1, 2, 3, 4], pa)
    cache.register([5, 6, 7, 8], pb)
    a.free(pa)
    a.free(pb)  # both now held only by the cache
    a.share(pa)  # ...then a "live slot" pins the LRU entry
    assert cache.evict(2) == 1  # only the unpinned page drops
    assert a.refcount(pa[0]) == 2  # pinned entry survived the sweep
    assert a.refcount(pb[0]) == 0
    assert cache.lookup([1, 2, 3, 4, 0]) == pa  # still cached
    assert cache.stats()["evictions"] == 1.0


def test_capacity_cap_stops_register_and_evicts_when_unpinned():
    a = PageAllocator(num_pages=16)
    cache = PrefixCache(a, page_size=4, capacity_pages=2)
    pages = a.alloc(3)
    added = cache.register([1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12], pages)
    # third entry blocked: capacity full and both entries are pinned by
    # the registering slot itself (live refs), so nothing can evict yet
    assert added == 2
    assert len(cache) == 2
    assert a.refcount(pages[2]) == 1  # no cache pin taken on the overflow
    a.free(pages)  # slot retires: only the cache pins remain
    other = a.alloc(1)
    assert cache.register([9, 9, 9, 9], other) == 1  # now LRU evicts
    assert len(cache) == 2
    assert cache.stats()["evictions"] == 1.0


# ------------------------------------------------------- engine: reuse path


def test_engine_prefix_reuse_matches_greedy_and_counts_hits():
    """End-to-end: a repeated prompt and a shared-prefix prompt both reuse
    cached KV pages AND still emit exactly the unpaged greedy tokens —
    reuse is a latency optimization, never a semantics change."""
    config, params, engine = _prefix_engine()
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(5).integers(1, 200, size=20)]
        first = engine.generate(prompt, max_tokens=6)
        assert first == _greedy_reference(config, params, prompt, 6)
        base = engine.stats()
        assert base["prefix_cache_pages"] >= 2.0  # 16/8 full prompt pages
        assert base["prefix_cache_hits"] == 0.0

        # identical prompt: both full pages come from the cache
        again = engine.generate(prompt, max_tokens=6)
        assert again == first
        stats = engine.stats()
        assert stats["prefix_cache_hits"] >= 2.0
        assert stats["prefix_cache_hit_rate"] > 0.0

        # shared system prefix, different tail: cached pages + fresh KV
        forked = prompt[:16] + [int(t) for t in
                                np.random.default_rng(9).integers(1, 200, 8)]
        got = engine.generate(forked, max_tokens=6)
        assert got == _greedy_reference(config, params, forked, 6)
        assert engine.stats()["prefix_cache_hits"] >= 4.0
    finally:
        engine.shutdown()


def test_engine_alloc_under_pressure_evicts_cache_not_admissions():
    """Pool exhaustion with cache-pinned pages: admission reclaims LRU
    cache pages instead of stalling behind retired prompts forever."""
    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(max_slots=2, paged=PagedConfig(
            page_size=8, num_pages=10, max_pages_per_slot=4, chunk_pages=1,
            prefix_cache=True,
        )),
    )
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(1).integers(1, 200, size=16)]
        engine.generate(prompt, max_tokens=4)
        assert engine.stats()["prefix_cache_pages"] >= 2.0
        # starve the free list so the next admission MUST evict
        hoard = engine.allocator.alloc(engine.allocator.available)
        assert hoard
        fresh = [int(t) for t in
                 np.random.default_rng(2).integers(200, 400, size=8)]
        got = engine.submit(fresh, max_tokens=4).result(timeout=60)
        assert got == _greedy_reference(config, params, fresh, 4)
        assert engine.stats()["prefix_cache_evictions"] >= 1.0
        engine.allocator.free(hoard)
    finally:
        engine.shutdown()


# -------------------------------------------- engine: COW + deadline (manual)


def _manual_engine(monkeypatch, **paged_over):
    monkeypatch.setattr(PagedLLMEngine, "_loop", lambda self: None)
    return _prefix_engine(**paged_over)


def test_cow_guard_copies_shared_page_and_drops_ref(monkeypatch):
    """_ensure_private_page on a shared page: fresh page swapped into the
    block table, the shared original keeps its other holder, and the COW
    metric ticks. Sharing is manufactured via allocator.share — the engine
    never organically writes a shared page (page-granular lookup stops
    short of the first written page)."""
    config, params, engine = _manual_engine(monkeypatch)
    try:
        engine.submit([5, 17, 42, 7, 3, 11, 9, 2, 8], max_tokens=4)
        engine._admit()
        slot = engine.slots[0]
        while slot.prefilling:
            assert engine._prefill_tick()
        victim = slot.pages[0]
        # prefill registered the one full prompt page: cache pin is live
        assert engine.allocator.refcount(victim) == 2
        engine.allocator.share([victim])  # simulate another holder
        assert engine._ensure_private_page(0, slot, 0)
        assert slot.pages[0] != victim
        assert engine.block_tables[0, 0] == slot.pages[0]
        assert engine.allocator.refcount(victim) == 2  # our ref dropped
        assert engine.allocator.refcount(slot.pages[0]) == 1
        assert engine.metrics["prefix_cache_cow"] == 1.0
        # private and scratch pages short-circuit without copying
        assert engine._ensure_private_page(0, slot, 0)
        assert engine.metrics["prefix_cache_cow"] == 1.0
        engine.allocator.free([victim])
    finally:
        engine.shutdown()


def test_cow_guard_stalls_lane_when_pool_exhausted(monkeypatch):
    config, params, engine = _manual_engine(monkeypatch)
    try:
        engine.submit([5, 17, 42, 7, 3, 11, 9, 2, 8], max_tokens=4)
        engine._admit()
        slot = engine.slots[0]
        while slot.prefilling:
            assert engine._prefill_tick()
        engine.allocator.share([slot.pages[0]])
        hoard = engine.allocator.alloc(engine.allocator.available)
        assert not engine._ensure_private_page(0, slot, 0)
        assert slot.stalled
        assert engine.metrics["page_stalls"] >= 1.0
        engine.allocator.free(hoard)
        engine.allocator.free([slot.pages[0]])
    finally:
        engine.shutdown()


def test_deadline_sweep_releases_refs_but_keeps_cache_entries(monkeypatch):
    """A slot evicted by the deadline sweep releases its refs through the
    refcounted free path: shared prefix pages drop back to their cache pin
    (NOT the free list), fresh pages recycle, and the cache still hits."""
    config, params, engine = _manual_engine(monkeypatch)
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(3).integers(1, 200, size=20)]
        # A prefills fully -> registers the 2 full prompt pages
        a_stream = engine.submit(prompt, max_tokens=4)
        engine._admit()
        slot_a = engine.slots[0]
        while slot_a.prefilling:
            assert engine._prefill_tick()
        cached = engine.prefix_cache.lookup(prompt)  # probe: take + return refs
        assert len(cached) == 2
        engine.allocator.free(cached)
        # B reuses them (refs now: A + cache + B = 3 per shared page)
        b_stream = engine.submit(prompt, max_tokens=4,
                                 deadline_ts=time.time() + 30)
        engine._admit()
        slot_b = engine.slots[1]
        assert slot_b.pages[:2] == cached
        assert engine.allocator.refcount(cached[0]) == 3
        n_b_pages = len(slot_b.pages)
        free_before = engine.allocator.available
        # B's deadline expires: sweep retires it through the refcounted path
        slot_b.request.deadline_ts = time.time() - 1.0
        engine._deadline_sweep()
        with pytest.raises(RequestTimeoutError):
            b_stream.result(timeout=5)
        assert engine.slots[1].free
        assert engine.allocator.refcount(cached[0]) == 2  # A + cache pin
        # only B's PRIVATE pages returned to the free list
        assert engine.allocator.available == free_before + (n_b_pages - 2)
        assert engine.prefix_cache.lookup(prompt) == cached  # entries intact
        engine.allocator.free(cached)
    finally:
        engine.shutdown()
