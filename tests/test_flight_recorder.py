"""Flight recorder & goodput plane (ISSUE 9).

Covers: typed event emission (kind registry, severity normalization,
dual timestamps), durable bounded segments, heartbeat federation into
the GCS `_events` table, the cluster-wide `state.events()` query,
Perfetto flow events across lanes, postmortem bundle construction, the
goodput accountant's wall-time invariant, and the chaos capstone: a
`preempt_node` episode during an in-process training run reconstructed
causally from one bundle, with the run's wall time fully attributed.
"""

import json
import threading
import time

import pytest

import ray_tpu
from ray_tpu.core import chaos
from ray_tpu.util.events import (
    EVENT_KINDS, EventLog, events, normalize_severity, read_segments,
)


@pytest.fixture
def runtime():
    rt = ray_tpu.init(num_cpus=2, detect_accelerators=False)
    yield rt
    ray_tpu.shutdown()


@pytest.fixture
def nodes4():
    rt = ray_tpu.init(num_cpus=1, num_nodes=4, detect_accelerators=False)
    yield rt
    chaos.clear_chaos()
    ray_tpu.shutdown()


# ------------------------------------------------------------- event typing


def test_emit_normalizes_severity_and_records_both_clocks():
    log = EventLog(capacity=16)
    assert normalize_severity("warn") == "WARNING"
    assert normalize_severity("FATAL") == "ERROR"
    assert normalize_severity("nonsense") == "INFO"
    e = log.emit("warning", "test", "lower-case severity",
                 kind="node.dead", node="abcd1234")
    assert e["severity"] == "WARNING"
    assert e["kind"] == "node.dead" and e["node"] == "abcd1234"
    assert isinstance(e["ts"], float) and isinstance(e["mono"], float)
    # monotonic and wall clocks are distinct domains
    assert abs(e["ts"] - e["mono"]) > 1.0
    log.emit("BOGUS-LEVEL", "test", "unknown level degrades")
    assert log.list()[-1]["severity"] == "INFO"
    # case-insensitive severity filter; kind/node filters
    assert log.list(severity="warning")[-1]["message"].startswith("lower")
    assert log.list(kind="node.dead") and log.list(node="abcd")
    assert log.list(node="ffff") == []


def test_event_kind_catalog_covers_runtime_call_sites():
    """The registered schema names the planes the issue demands."""
    for kind in ("node.discovered", "node.dead", "preempt.announced",
                 "preempt.drain", "pg.transition", "ckpt.saved",
                 "ckpt.quarantine", "train.gang_started",
                 "train.preempt_restart", "serve.scaled", "serve.drain",
                 "chaos.injected", "watchdog.stall", "watchdog.slo_burn"):
        assert kind in EVENT_KINDS, kind


def test_event_segments_rotate_bounded_and_tolerate_torn_tail(tmp_path):
    seg_dir = str(tmp_path / "seg")
    log = EventLog(capacity=4096)
    log.configure_segments(seg_dir, max_bytes=512, keep=3)
    for i in range(200):
        log.emit("INFO", "test", f"event {i}", kind="node.discovered", n=i)
    names = sorted(p.name for p in (tmp_path / "seg").iterdir())
    rotated = [n for n in names if n.startswith("events-")]
    assert rotated, "no rotation happened"
    assert len(rotated) <= 3, names  # retention bound holds
    assert "events.jsonl" in names
    replay = read_segments(seg_dir)
    assert replay and replay[-1]["extra"]["n"] == 199
    # events replay in order within the retained window
    ns = [e["extra"]["n"] for e in replay]
    assert ns == sorted(ns)
    # a torn tail line (crash mid-append) is skipped, not raised
    with open(tmp_path / "seg" / "events.jsonl", "a") as f:
        f.write('{"torn": ')
    replay2 = read_segments(seg_dir)
    assert [e["extra"]["n"] for e in replay2] == ns
    log.configure_segments(None)


# --------------------------------------------------------- raylint coverage


def test_event_kinds_rule_fixtures(tmp_path):
    """event-kinds: unregistered/missing/dynamic kinds are findings;
    registered literals and register_event_kind extensions pass."""
    from scripts.raylint import Project, run

    pkg = tmp_path / "ray_tpu"
    (pkg / "util").mkdir(parents=True)
    (pkg / "util" / "events.py").write_text(
        'EVENT_KINDS = {"good.kind": "doc"}\n'
        "def emit(*a, **k):\n    pass\n"
    )
    (pkg / "mod.py").write_text(
        "from .util.events import emit\n"
        "from .util.events import register_event_kind\n"
        'register_event_kind("extra.kind")\n'
        "def f(dyn):\n"
        '    emit("INFO", "m", "ok", kind="good.kind")\n'
        '    emit("INFO", "m", "ok2", kind="extra.kind")\n'
        '    emit("INFO", "m", "missing kind")\n'
        '    emit("INFO", "m", "bad", kind="not.registered")\n'
        '    emit("INFO", "m", "dynamic", kind=dyn)\n'
    )
    result = run(Project(tmp_path), rules=["event-kinds"])
    msgs = sorted(f.message for f in result.findings)
    assert len(msgs) == 3, msgs
    assert any("without kind=" in m for m in msgs)
    assert any("not registered" in m for m in msgs)
    assert any("string literal" in m for m in msgs)


def test_event_kinds_rule_clean_on_repo():
    """Every emit call site in the real tree passes the registry."""
    import pathlib

    from scripts.raylint import Project, run

    root = pathlib.Path(__file__).resolve().parents[1]
    result = run(Project(root), rules=["event-kinds"])
    assert result.counts["event-kinds"] == 0, [
        f"{f.location}: {f.message}" for f in result.findings
    ]


# ----------------------------------------------------- federation + queries


def test_events_federate_into_gcs_table_and_state_query():
    from ray_tpu.core.gcs import EVENT_NS
    from ray_tpu.util import state

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    try:
        ctx = rt.cluster
        my_hex = ctx.node_id.hex()
        events().emit("WARNING", "test", "flight recorder drill",
                      kind="chaos.injected", mode="drill")
        # force federation passes (normally they ride the stats
        # piggyback) until the cursor has drained the whole ring — the
        # process-global event log may hold a backlog from earlier tests
        # larger than one bounded federate batch
        prev, tail = -1, []
        while len(tail) != prev:
            prev = len(tail)
            ctx._last_stats_ts = 0.0
            ctx._report_stats()
            tail = ctx.gcs.kv_get(my_hex, namespace=EVENT_NS) or []
        assert tail, "no events federated into the _events table"
        assert any(e.get("kind") == "chaos.injected" for e in tail)
        # every federated event carries node attribution
        assert all(e.get("node") for e in tail)
        # cursor advanced: another pass without new events is a no-op
        before = len(tail)
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        assert len(ctx.gcs.kv_get(my_hex, namespace=EVENT_NS)) == before
        # the state query merges + filters + dedupes
        drill = state.events(kind="chaos.injected")
        assert drill and drill[-1]["message"] == "flight recorder drill"
        keys = [(e.get("node"), e.get("seq")) for e in drill]
        assert len(keys) == len(set(keys)), "duplicate (node, seq) entries"
        assert state.events(kind="chaos.injected", node=my_hex[:8])
        assert state.events(kind="chaos.injected",
                            since=time.time() + 60) == []
        assert state.events(kind="chaos.injected", severity="warning")
    finally:
        ray_tpu.shutdown()


def test_events_table_is_bounded():
    from ray_tpu.core.config import cfg
    from ray_tpu.core.gcs import EVENT_NS

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    cfg.set(events_table_cap=20, events_federate_batch=500)
    try:
        ctx = rt.cluster
        for i in range(80):
            events().emit("INFO", "test", f"burst {i}", kind="node.discovered")
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        tail = ctx.gcs.kv_get(ctx.node_id.hex(), namespace=EVENT_NS)
        assert len(tail) <= 20
        assert tail[-1]["message"] == "burst 79"  # newest survive
    finally:
        cfg.reset()
        ray_tpu.shutdown()


# ------------------------------------------------------- flow events export


def test_trace_dump_emits_cross_lane_flow_events():
    from ray_tpu.util.tracing import Tracer, export_chrome_trace

    tracer = Tracer(capacity=100, sample_ratio=1.0)
    t0 = time.time()
    parent = tracer.start_span("task.submit", lane="node:aaaa", start_ts=t0)
    child = tracer.start_span("task.execute", parent=parent.context,
                              lane="node:bbbb", start_ts=t0 + 0.01)
    sibling = tracer.start_span("task.queue", parent=parent.context,
                                lane="node:aaaa", start_ts=t0 + 0.001)
    sibling.end(end_ts=t0 + 0.005)
    child.end(end_ts=t0 + 0.02)
    parent.end(end_ts=t0 + 0.03)
    payload = json.loads(export_chrome_trace(tracer.spans()))
    flows = [e for e in payload["traceEvents"] if e.get("cat") == "flow"]
    # exactly one cross-lane edge (parent->child); same-lane nesting
    # renders as slices, not arrows
    starts = [e for e in flows if e["ph"] == "s"]
    finishes = [e for e in flows if e["ph"] == "f"]
    assert len(starts) == 1 and len(finishes) == 1
    assert starts[0]["id"] == finishes[0]["id"]
    assert starts[0]["pid"] == "node:aaaa"
    assert finishes[0]["pid"] == "node:bbbb"
    assert finishes[0]["bp"] == "e"
    assert finishes[0]["ts"] >= starts[0]["ts"]


# ------------------------------------------------------------ goodput plane


def test_goodput_accountant_partition_invariant():
    from ray_tpu.util.goodput import GoodputAccountant
    from ray_tpu.util.metrics import registry

    acct = GoodputAccountant("acct-drill")
    acct.begin("init")
    time.sleep(0.03)
    acct.begin("step_compute")
    time.sleep(0.05)
    acct.begin("ckpt_save")
    time.sleep(0.02)
    acct.begin("step_compute")
    time.sleep(0.03)
    acct.finish()
    report = acct.report()
    total = sum(report["buckets"].values())
    assert report["wall_time_s"] > 0
    assert abs(total - report["wall_time_s"]) < 1e-4
    assert report["buckets"]["step_compute"] >= 0.07
    assert report["goodput_s"] == report["buckets"]["step_compute"]
    assert 0.0 < report["goodput_fraction"] < 1.0
    # transfer preserves the partition and clamps to the source bucket
    acct.transfer("step_compute", "input_wait", 0.01)
    acct.transfer("init", "compile", 999.0)  # clamped to what init holds
    r2 = acct.report()
    assert abs(sum(r2["buckets"].values()) - r2["wall_time_s"]) < 1e-4
    assert r2["buckets"]["input_wait"] >= 0.01
    assert r2["buckets"]["init"] == 0.0
    # gauges published with run+bucket labels
    text = registry().prometheus_text()
    assert 'raytpu_train_goodput_seconds' in text
    assert 'run="acct-drill"' in text and 'bucket="step_compute"' in text
    assert "raytpu_train_goodput_fraction" in text


def test_serve_slo_attainment_ledger():
    from ray_tpu.core.config import cfg
    from ray_tpu.util.goodput import serve_slo_report
    from ray_tpu.util.metrics import get_or_create_histogram, registry
    from ray_tpu.util.watchdog import ServeSLOMonitor

    cfg.set(serve_slo_ttft_p99_s=0.05)
    try:
        hist = get_or_create_histogram(
            "raytpu_serve_ttft_seconds",
            "Time to first generated token, from engine request spans.",
            boundaries=(0.005, 0.025, 0.1, 0.5, 2.0, 10.0),
        )
        monitor = ServeSLOMonitor()
        for _ in range(50):
            hist.observe(0.3)  # way over the 50ms objective
        monitor.check()
        for _ in range(50):
            hist.observe(0.01)  # healthy window
        monitor.check()
        ledger = monitor.attainment_report()
        assert ledger["ttft_p99"]["windows"] == 2
        assert ledger["ttft_p99"]["violated"] == 1
        assert ledger["ttft_p99"]["attainment"] == 0.5
        assert 'raytpu_serve_slo_attainment' in registry().prometheus_text()
        # module-level report (the serve goodput analogue)
        import ray_tpu.util.watchdog as wd

        prev = wd._slo_monitor
        wd._slo_monitor = monitor
        try:
            rep = serve_slo_report()
        finally:
            wd._slo_monitor = prev
        assert rep["attainment"] == 0.5
        assert rep["slos"]["ttft_p99"]["requests"] == 100
    finally:
        cfg.reset()


def test_bench_goodput_block_shape():
    from ray_tpu.util.goodput import GoodputAccountant

    import bench

    acct = GoodputAccountant("bench")
    acct.begin("init")
    time.sleep(0.01)
    acct.begin("compile")
    time.sleep(0.01)
    acct.begin("step_compute")
    time.sleep(0.02)
    acct.finish()
    block = bench._goodput_block(acct)
    assert set(block) == {"wall_time_s", "buckets", "goodput_s",
                          "goodput_fraction"}
    assert block["buckets"]["step_compute"] > 0
    assert abs(sum(block["buckets"].values()) - block["wall_time_s"]) < 1e-4
    json.dumps(block)  # BENCH line must stay JSON-serializable


# ------------------------------------------------------- postmortem bundles


def test_postmortem_bundle_smoke(runtime, tmp_path):
    """Tier-1 smoke: the bundle builds from a live runtime and its
    timeline parses as valid Perfetto JSON."""
    from ray_tpu.util import state
    from ray_tpu.util.postmortem import load_bundle

    @ray_tpu.remote
    def work(x):
        return x * 2

    assert ray_tpu.get([work.remote(i) for i in range(4)], timeout=30) == [
        0, 2, 4, 6,
    ]
    events().emit("INFO", "test", "bundle smoke", kind="node.discovered")
    out = str(tmp_path / "bundle.tgz")
    manifest = state.postmortem(out, note="smoke drill")
    assert manifest["note"] == "smoke drill"
    assert manifest["counts"]["events"] > 0
    assert manifest["counts"]["spans"] > 0
    bundle = load_bundle(out)
    assert set(manifest["files"]) <= set(bundle) | {"manifest.json"}
    timeline = bundle["timeline.json"]
    assert isinstance(timeline["traceEvents"], list) and timeline["traceEvents"]
    phases = {e.get("ph") for e in timeline["traceEvents"]}
    assert "X" in phases and "i" in phases  # slices AND instant events
    assert any(e.get("cat") == "events" for e in timeline["traceEvents"])
    assert bundle["manifest.json"]["counts"] == manifest["counts"]
    # the exposition rode along
    assert "raytpu_" in bundle["metrics_cluster.prom"]


# ------------------------------------------------------------ capstone drill


def test_preempt_postmortem_capstone(nodes4, tmp_path):
    """A preempt_node episode during an in-process training run yields
    ONE postmortem bundle whose single timeline contains the preemption
    announcement, emergency checkpoint, gang restart, and resumed steps
    in causal order from >=2 logical nodes — and the goodput report
    attributes the run's whole wall time to buckets, with the same
    numbers in Result.goodput and the goodput gauges."""
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy
    from ray_tpu.train import (
        FailureConfig, RunConfig, RunStatus, ScalingConfig, TrainController,
    )
    from ray_tpu.util import state
    from ray_tpu.util.metrics import registry
    from ray_tpu.util.postmortem import load_bundle

    rt = nodes4
    events().clear()

    def train_fn(config):
        from ray_tpu import train

        ctx = train.get_context()
        ckpt = train.get_checkpoint()
        start = int(ckpt["step"]) + 1 if ckpt is not None else 0
        for step in range(start, 40):
            time.sleep(0.02)
            if ctx.world_rank != 0:
                if train.is_preempted():
                    return "preempted"
                continue
            if train.should_checkpoint():
                train.report({"step": step}, checkpoint={"step": step},
                             checkpoint_step=step)
            elif train.is_preempted():
                return "preempted"
            elif step % 10 == 9:
                train.report({"step": step}, checkpoint={"step": step},
                             checkpoint_step=step)
            else:
                train.report({"step": step})
        return "done"

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=3),
        RunConfig(name="preempt-pm", storage_path=str(tmp_path / "trial"),
                  failure=FailureConfig(max_failures=0)),
        train_config={},
        restart_backoff_s=0.0,
    )
    box = {}
    thread = threading.Thread(
        target=lambda: box.update(result=controller.run()), daemon=True
    )
    thread.start()
    deadline = time.monotonic() + 60
    while not controller.metrics_history and time.monotonic() < deadline:
        time.sleep(0.02)
    assert controller.metrics_history, "gang never started reporting"

    chaos.set_chaos(preempt_node=True, preempt_warning_s=3.0,
                    name_filter="pm-trigger", max_injections=1)
    # a NON-head node hosting a gang worker: the announcement then comes
    # from a different logical node than the driver's train events, so
    # the bundle provably spans >=2 nodes
    victim = next(
        n for n in rt.scheduler.nodes()
        if not n.is_head and n.resources.available().get("CPU", 0.0) < 0.5
    )

    @ray_tpu.remote(name="pm-trigger", num_cpus=0)
    def trigger():
        return "sent"

    ref = trigger.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(victim.node_id)
    ).remote()
    assert ray_tpu.get(ref, timeout=30) == "sent"

    thread.join(timeout=120)
    assert not thread.is_alive(), "controller never finished"
    result = box["result"]
    assert result.status == RunStatus.FINISHED, result.error
    assert result.num_preempt_restarts == 1

    # ---- one bundle, one causally-ordered timeline
    out = str(tmp_path / "episode.tgz")
    state.postmortem(out, note="preempt capstone")
    bundle = load_bundle(out)
    evs = bundle["events.jsonl"]

    def first(kind, **match):
        for e in evs:
            if e.get("kind") != kind:
                continue
            extra = e.get("extra") or {}
            if all(extra.get(k) == v for k, v in match.items()):
                return e
        raise AssertionError(
            f"no {kind} event matching {match} in "
            f"{[(e.get('kind'), e.get('extra')) for e in evs]}"
        )

    announced = first("preempt.announced")
    emergency = first("ckpt.saved", emergency=True)
    restart = first("train.preempt_restart")
    resumed = first("train.gang_started", attempt=2)
    # causal order on the shared wall clock
    assert (announced["ts"] <= emergency["ts"] <= restart["ts"]
            <= resumed["ts"]), [announced, emergency, restart, resumed]
    # the resumed attempt picked up the emergency checkpoint
    assert resumed["extra"]["resume_from_step"] is not None
    # events span >=2 logical nodes (victim + driver/head)
    episode_nodes = {e.get("node") for e in
                     (announced, emergency, restart, resumed)}
    assert len(episode_nodes) >= 2, episode_nodes
    assert announced["node"] == victim.node_id.hex()

    # the SAME events appear as instant marks on the Perfetto timeline,
    # wall-clock aligned with the run's span slices
    timeline = bundle["timeline.json"]["traceEvents"]
    marks = {e["args"].get("kind"): e for e in timeline
             if e.get("ph") == "i" and e.get("cat") == "events"}
    for kind in ("preempt.announced", "ckpt.saved",
                 "train.preempt_restart", "train.gang_started"):
        assert kind in marks, sorted(marks)
    slices = [e for e in timeline if e.get("ph") == "X"
              and e.get("name") == "train.attempt"]
    assert len(slices) >= 2  # both gang attempts made it into the export
    lo = min(e["ts"] for e in slices)
    hi = max(e["ts"] + e.get("dur", 0) for e in slices)
    assert lo <= marks["preempt.announced"]["ts"] <= hi

    # ---- goodput: buckets partition the wall time (±5% demanded; the
    # accountant makes it exact) and surface identically everywhere
    goodput = result.goodput
    assert goodput is not None and goodput["wall_time_s"] > 0
    total = sum(goodput["buckets"].values())
    assert abs(total - goodput["wall_time_s"]) <= 0.05 * goodput["wall_time_s"]
    assert goodput["buckets"]["step_compute"] > 0
    assert goodput["buckets"]["ckpt_save"] > 0       # the emergency window
    assert goodput["buckets"]["preempt_restart"] > 0  # the re-mesh
    assert goodput["buckets"]["init"] > 0
    assert 0 < goodput["goodput_fraction"] < 1
    # gauges carry the same numbers
    gauge_total = 0.0
    for line in registry().prometheus_text().splitlines():
        if (line.startswith("raytpu_train_goodput_seconds")
                and 'run="preempt-pm"' in line):
            gauge_total += float(line.rsplit(" ", 1)[1])
    assert abs(gauge_total - total) < 1e-3, (gauge_total, total)


def test_autoscaler_events_and_gauges(runtime):
    """Capacity-plane actions land in the flight recorder as typed,
    demand-origin-tagged events, and the autoscaler gauges expose the
    same episode through /metrics."""
    from ray_tpu.core.capacity import (
        DEMAND_ORIGINS, CapacityAutoscaler, FakeNodeProvider, NodeType,
    )
    from ray_tpu.util.metrics import registry

    rt = runtime
    events().clear()
    for kind in ("autoscaler.scale_up", "autoscaler.scale_down",
                 "autoscaler.replace", "autoscaler.blocked",
                 "autoscaler.error"):
        assert kind in EVENT_KINDS, kind

    scaler = CapacityAutoscaler(
        rt.scheduler, FakeNodeProvider(rt.scheduler),
        [NodeType("cpu4", {"CPU": 4.0})],
        poll_interval_s=0.05, idle_timeout_s=0.3, drain_grace_s=5.0,
    )
    scaler.start()
    try:
        @ray_tpu.remote(num_cpus=4)
        def big():
            return "ran"

        assert ray_tpu.get(big.remote(), timeout=60) == "ran"
        deadline = time.monotonic() + 30
        while scaler.stats["scale_downs"] == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert scaler.stats["scale_downs"] >= 1
    finally:
        scaler.stop()

    ups = events().list(kind="autoscaler.scale_up")
    downs = events().list(kind="autoscaler.scale_down")
    assert ups and downs
    up, down = ups[0], downs[0]
    # demand-origin tagging on the way up, drain reason on the way down
    assert up["extra"]["origin"] in DEMAND_ORIGINS
    assert up["extra"]["node_type"] == "cpu4"
    assert up["extra"]["capacity_class"] == "on_demand"
    assert down["extra"]["reason"]
    assert down["extra"]["forced"] is False  # drain completed, not expired
    assert down["node"] == up["node"]  # the same launched node retired
    assert up["ts"] <= down["ts"]

    text = registry().prometheus_text()
    assert "raytpu_autoscaler_managed_nodes" in text
    assert "raytpu_autoscaler_pending_demands" in text
    up_n = down_n = None
    for line in text.splitlines():
        if line.startswith('raytpu_autoscaler_scale_total{direction="up"}'):
            up_n = float(line.rsplit(" ", 1)[1])
        if line.startswith('raytpu_autoscaler_scale_total{direction="down"}'):
            down_n = float(line.rsplit(" ", 1)[1])
    assert up_n is not None and up_n >= 1.0
    assert down_n is not None and down_n >= 1.0
