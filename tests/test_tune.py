"""Tune: variants, ASHA early stopping, end-to-end sweep."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune


@pytest.fixture(autouse=True)
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()


def test_generate_variants_grid_and_random():
    space = {
        "a": tune.grid_search([1, 2, 3]),
        "b": tune.uniform(0.0, 1.0),
        "c": "fixed",
    }
    variants = list(tune.generate_variants(space, num_samples=2, seed=0))
    assert len(variants) == 6  # 3 grid × 2 samples
    assert {v["a"] for v in variants} == {1, 2, 3}
    assert all(0.0 <= v["b"] <= 1.0 for v in variants)
    assert all(v["c"] == "fixed" for v in variants)


def test_domains_sample_in_range():
    rng = np.random.default_rng(0)
    assert 1e-4 <= tune.loguniform(1e-4, 1e-1).sample(rng) <= 1e-1
    assert tune.randint(3, 7).sample(rng) in (3, 4, 5, 6)
    assert tune.choice(["x", "y"]).sample(rng) in ("x", "y")


def test_asha_stops_bad_trials_unit():
    sched = tune.ASHAScheduler(
        metric="score", mode="max", grace_period=2, reduction_factor=2, max_t=16
    )
    # first at a rung is trivially in the top fraction
    assert sched.on_result("good1", {"training_iteration": 2, "score": 10}) == "CONTINUE"
    # ties with the cutoff → stays (async halving keeps >= cutoff)
    assert sched.on_result("good2", {"training_iteration": 2, "score": 10}) == "CONTINUE"
    # clearly worse at the same rung → cut
    assert sched.on_result("bad", {"training_iteration": 2, "score": 1}) == "STOP"
    # once stopped, stays stopped
    assert sched.on_result("bad", {"training_iteration": 3, "score": 99}) == "STOP"


def test_tuner_end_to_end_sweep():
    def trainable(config):
        # quadratic: best at x=3
        score = -((config["x"] - 3.0) ** 2)
        for i in range(3):
            tune.report({"score": score + 0.01 * i})
        return score

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(num_samples=1, metric="score", mode="max"),
    )
    results = tuner.fit()
    assert len(results) == 4
    best = results.get_best_result()
    assert best.config["x"] == 3.0
    assert all(t.status == tune.TrialStatus.TERMINATED for t in results)


def test_tuner_with_asha_stops_some():
    def trainable(config):
        for i in range(1, 9):
            tune.report({"loss": config["badness"] * i})

    sched = tune.ASHAScheduler(
        metric="loss", mode="min", grace_period=2, reduction_factor=2, max_t=8
    )
    tuner = tune.Tuner(
        trainable,
        param_space={"badness": tune.grid_search([1.0, 2.0, 5.0, 10.0])},
        tune_config=tune.TuneConfig(
            metric="loss", mode="min", scheduler=sched, max_concurrent=4
        ),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.config["badness"] == 1.0
    stopped = [t for t in results if t.status == tune.TrialStatus.STOPPED]
    assert stopped, "ASHA never stopped anything"


def test_tuner_handles_erroring_trial():
    def trainable(config):
        if config["x"] == 1:
            raise RuntimeError("bad trial")
        tune.report({"score": config["x"]})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    )
    results = tuner.fit()
    statuses = {t.config["x"]: t.status for t in results}
    assert statuses[1] == tune.TrialStatus.ERRORED
    assert results.get_best_result().config["x"] == 2
