"""Pipeline parallelism: the dp×pp shard_map schedule must match the plain
(non-pipelined) computation exactly — same loss, same gradients, and a
full train step that optimizes. (VERDICT round-1 item 5; SURVEY §2.4 PP.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import get_config
from ray_tpu.models.transformer import forward, init_params
from ray_tpu.ops import cross_entropy_loss
from ray_tpu.parallel import MeshSpec, build_mesh
from ray_tpu.parallel.pipeline import (
    create_pp_train_state,
    make_pp_loss_fn,
    make_pp_train_step,
)
from ray_tpu.train import default_optimizer


def _cfg():
    # 4 layers → 2 per stage at pp=2; fp32 for exact comparison on CPU
    return get_config("gpt2-small").replace(
        n_layers=4, d_model=64, n_heads=4, d_ff=128, vocab_size=128,
        max_seq=32, dtype=jnp.float32, param_dtype=jnp.float32,
    )


def _mesh(dp, pp):
    spec = MeshSpec(dp=dp, pp=pp)
    return build_mesh(spec, devices=jax.devices()[: spec.num_devices])


def _ref_loss(params, tokens, config):
    logits = forward(params, tokens[:, :-1], config)
    loss, _ = cross_entropy_loss(logits, tokens[:, 1:])
    return loss


def test_pp_loss_matches_reference():
    config = _cfg()
    mesh = _mesh(dp=2, pp=2)
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size)

    pp_loss = make_pp_loss_fn(config, mesh, num_microbatches=2)
    got = float(jax.jit(pp_loss)(params, tokens))
    want = float(jax.jit(lambda p, t: _ref_loss(p, t, config))(params, tokens))
    assert got == pytest.approx(want, rel=1e-5), (got, want)


def test_pp_grads_match_reference():
    config = _cfg()
    mesh = _mesh(dp=2, pp=2)
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size)

    pp_loss = make_pp_loss_fn(config, mesh, num_microbatches=4)
    g_pp = jax.jit(jax.grad(pp_loss))(params, tokens)
    g_ref = jax.jit(jax.grad(lambda p, t: _ref_loss(p, t, config)))(params, tokens)

    flat_pp = jax.tree_util.tree_leaves_with_path(g_pp)
    flat_ref = {jax.tree_util.keystr(p): l for p, l in
                jax.tree_util.tree_leaves_with_path(g_ref)}
    for path, leaf in flat_pp:
        ref_leaf = flat_ref[jax.tree_util.keystr(path)]
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=2e-4, atol=2e-6,
            err_msg=jax.tree_util.keystr(path),
        )


def test_pp_train_step_optimizes():
    config = _cfg()
    mesh = _mesh(dp=2, pp=2)
    opt = default_optimizer(1e-2, total_steps=20)
    state, shardings = create_pp_train_state(
        config, opt, jax.random.PRNGKey(0), mesh
    )
    step = make_pp_train_step(
        config, opt, mesh, num_microbatches=2, state_shardings=shardings
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size)
    losses = []
    for _ in range(8):
        state, metrics = step(state, {"tokens": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.3, losses
    assert int(state.step) == 8
    # the layer stack is really sharded over pp
    blocks_sharding = state.params["blocks"]["wq"].sharding
    assert "pp" in (blocks_sharding.spec[0] or ()), blocks_sharding


def test_pp_requires_divisible_layers():
    config = _cfg().replace(n_layers=3)
    mesh = _mesh(dp=1, pp=2)
    with pytest.raises(ValueError, match="not divisible"):
        make_pp_loss_fn(config, mesh, num_microbatches=2)


def test_pp4_deep_stack_matches_reference():
    config = _cfg().replace(n_layers=8)
    mesh = _mesh(dp=2, pp=4)
    params = init_params(config, jax.random.PRNGKey(2))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 33), 0, config.vocab_size)
    pp_loss = make_pp_loss_fn(config, mesh, num_microbatches=4)
    got = float(jax.jit(pp_loss)(params, tokens))
    want = float(jax.jit(lambda p, t: _ref_loss(p, t, config))(params, tokens))
    assert got == pytest.approx(want, rel=1e-5), (got, want)


# ------------------------------------------------------------------ 1F1B


def test_1f1b_matches_gpipe_loss_and_grads():
    """VERDICT r3 #6: the manual 1F1B schedule (bounded activation stash,
    interleaved fwd/bwd ticks) must produce exactly the GPipe-through-AD
    loss and gradients — only schedule and memory differ."""
    from ray_tpu.parallel.pipeline import (
        make_pp_loss_and_grad_1f1b,
        make_pp_loss_fn,
    )

    config = get_config("llama-tiny").replace(dtype=jnp.float32, n_layers=4)
    mesh = build_mesh(MeshSpec(dp=2, pp=4))
    opt = default_optimizer(1e-3, total_steps=10)
    state, _ = create_pp_train_state(config, opt, jax.random.PRNGKey(0), mesh)
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, 33), 0, config.vocab_size
    )

    loss_fn = make_pp_loss_fn(config, mesh, 2)
    l_gpipe, g_gpipe = jax.jit(jax.value_and_grad(loss_fn))(state.params, tokens)
    l_1f1b, g_1f1b = jax.jit(make_pp_loss_and_grad_1f1b(config, mesh, 2))(
        state.params, tokens
    )
    assert abs(float(l_gpipe) - float(l_1f1b)) < 1e-5
    flat_1f1b = {
        jax.tree_util.keystr(p): v
        for p, v in jax.tree_util.tree_flatten_with_path(g_1f1b)[0]
    }
    for path, v in jax.tree_util.tree_flatten_with_path(g_gpipe)[0]:
        err = float(jnp.max(jnp.abs(v - flat_1f1b[jax.tree_util.keystr(path)])))
        assert err < 2e-5, (jax.tree_util.keystr(path), err)


def test_1f1b_train_step_learns():
    config = get_config("llama-tiny").replace(dtype=jnp.float32)
    mesh = build_mesh(MeshSpec(dp=4, pp=2))
    opt = default_optimizer(1e-2, total_steps=20)
    state, shardings = create_pp_train_state(
        config, opt, jax.random.PRNGKey(0), mesh
    )
    step = make_pp_train_step(
        config, opt, mesh, num_microbatches=2,
        state_shardings=shardings, schedule="1f1b",
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(3), (8, 33), 0, config.vocab_size
    )
    losses = []
    for _ in range(8):
        state, metrics = step(state, {"tokens": tokens})
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.1, losses
