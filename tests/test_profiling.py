"""Profiling plane: typed device-trace guards, coordinated capture,
cost-model MFU/roofline accounting, Perfetto device-track merge."""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

import jax
import jax.numpy as jnp

import ray_tpu
from ray_tpu import ProfilingError
from ray_tpu.util import profiling, state
from ray_tpu.util.metrics import registry


@pytest.fixture
def rt():
    registry().clear()
    runtime = ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()
    registry().clear()


@pytest.fixture
def rt3():
    """Three logical nodes: the in-process fan-out capture target."""
    registry().clear()
    runtime = ray_tpu.init(num_cpus=4, num_nodes=3, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()
    registry().clear()


def _busy_jit():
    f = jax.jit(lambda a: a @ a)
    x = jnp.ones((128, 128))
    f(x).block_until_ready()  # compile outside any capture window
    return lambda: f(x).block_until_ready()


# ------------------------------------------------------- typed trace guards


def test_stop_without_active_trace_is_typed():
    with pytest.raises(ProfilingError, match="no active device trace"):
        profiling.stop_device_trace()


def test_double_start_is_typed(tmp_path):
    profiling.start_device_trace(str(tmp_path / "a"))
    try:
        with pytest.raises(ProfilingError, match="already active"):
            profiling.start_device_trace(str(tmp_path / "b"))
    finally:
        profiling.stop_device_trace()
    # the latch cleared: a fresh stop is typed again, not a jax error
    with pytest.raises(ProfilingError):
        profiling.stop_device_trace()


def test_device_trace_roundtrip_cpu(tmp_path):
    """CPU-backend capture round-trip: the context manager records a
    loadable chrome-trace artifact."""
    work = _busy_jit()
    logdir = tmp_path / "trace"
    with profiling.device_trace(str(logdir)):
        work()
    found = list(logdir.rglob("*.trace.json.gz"))
    assert found, "device trace produced no chrome-trace artifact"
    assert not profiling.device_trace_active()


def test_profiler_server_idempotent():
    try:
        first = profiling.start_profiler_server(9876)
    except ProfilingError as exc:
        pytest.skip(f"profiler server unavailable here: {exc}")
    second = profiling.start_profiler_server(9876)
    assert second is first
    assert profiling.profiler_server_port() == 9876
    assert profiling.node_snapshot()["server_port"] == 9876


# ----------------------------------------------------------- local capture


def test_capture_local_profile_roundtrip():
    work = _busy_jit()
    res = profiling.capture_local_profile(0.3, workload=work)
    meta, artifacts = res["meta"], res["artifacts"]
    assert meta["device"] == "ok" and meta["host"] == "ok"
    assert meta["bytes"] == sum(len(b) for b in artifacts.values()) > 0
    assert any(n.endswith(".trace.json.gz") for n in artifacts)
    report = artifacts["host_profile.txt"].decode()
    assert "host sampling profile" in report
    # the capture is reflected in the node snapshot for `status --verbose`
    snap = profiling.node_snapshot()
    assert snap["active_capture"] is None
    assert snap["last_capture"]["bytes"] == meta["bytes"]


def test_device_trace_events_align_to_wall_clock():
    work = _busy_jit()
    res = profiling.capture_local_profile(0.2, workload=work, host=False)
    events = profiling.load_device_trace_events(
        res["artifacts"], started_at=res["meta"]["started_at"],
        lane_prefix="device:test", max_events=500,
    )
    assert 0 < len(events) <= 500
    for e in events[:20]:
        assert e["pid"].startswith("device:test")
        # wall-clock aligned: inside ~a minute of the capture window
        assert abs(e["ts"] / 1e6 - res["meta"]["started_at"]) < 60.0


# -------------------------------------------------------- cost model / MFU


def test_step_cost_and_roofline():
    f = jax.jit(lambda a, b: a @ b)
    x = jnp.ones((256, 128))
    w = jnp.ones((128, 64))
    cost = profiling.step_cost(f, x, w)
    assert cost.flops > 0 and cost.bytes_accessed > 0
    assert cost.top_buckets(3)[0][0] == "flops"
    roof = profiling.roofline(cost, 0.001)
    assert roof["mfu"] > 0 and roof["hbm_fraction"] > 0
    assert roof["bound"] in ("compute", "memory")
    # CPU backend: unknown chip prices against the documented fallback
    assert roof["estimated_peaks"] is True
    with pytest.raises(ProfilingError):
        profiling.roofline(cost, 0.0)


def test_step_cost_rejects_plain_callable():
    with pytest.raises(ProfilingError, match="jitted or compiled"):
        profiling.step_cost(lambda: 1)


def test_sharded_step_cost_counts_devices():
    from jax.sharding import NamedSharding, PartitionSpec
    import numpy as np

    mesh = jax.sharding.Mesh(
        np.array(jax.devices()).reshape(len(jax.devices())), ("dp",)
    )
    x = jax.device_put(
        jnp.ones((256, 128)), NamedSharding(mesh, PartitionSpec("dp", None))
    )
    w = jax.device_put(jnp.ones((128, 64)), NamedSharding(mesh, PartitionSpec()))
    f = jax.jit(lambda a, b: a @ b)
    cost = profiling.step_cost(f, x, w)
    assert cost.n_devices == len(jax.devices())
    # cost_analysis is per-device: the whole program is N shards' worth
    assert cost.total_flops == pytest.approx(cost.flops * cost.n_devices)


# ------------------------------------------------- coordinated capture plane


def test_fanout_capture_in_process_runtime(rt3):
    """One state.profile() call covers >=2 logical nodes, registers the
    capture, and serves metas + artifact bytes through the state API."""
    work = _busy_jit()
    stop = threading.Event()

    def pump():
        while not stop.is_set():
            work()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    try:
        record = state.profile(duration_s=0.4)
    finally:
        stop.set()
        t.join(timeout=5)
    assert len(record["nodes"]) >= 2
    assert record["total_bytes"] > 0
    listed = state.list_profiles()
    assert record["profile_id"] in [p["profile_id"] for p in listed]
    full = state.get_profile(record["profile_id"])
    holders = [
        (nh, m) for nh, m in full["nodes"].items() if not m.get("artifacts_at")
    ]
    assert holders, "no node holds the capture artifacts"
    node_hex, meta = holders[0]
    assert meta["device"] == "ok" and meta["host"] == "ok"
    name = meta["artifact_names"][0]
    assert len(state.profile_artifact(record["profile_id"], node_hex, name)) > 0
    # aliased logical nodes point at the holder instead of duplicating
    aliased = [m for m in full["nodes"].values() if m.get("artifacts_at")]
    assert all(m["artifacts_at"] == node_hex for m in aliased)
    with pytest.raises(ValueError):
        state.get_profile("no-such-profile")


def test_capture_selector_and_unknown_selector(rt3):
    head_hex = rt3.scheduler.head_node().node_id.hex()
    record = state.profile(nodes=[head_hex[:8]], duration_s=0.1, device=False)
    assert list(record["nodes"]) == [head_hex]
    with pytest.raises(ValueError, match="selector"):
        state.profile(nodes=["ffff-no-such-node"], duration_s=0.1)


def test_status_verbose_shows_profiler_and_capture(rt3):
    state.profile(duration_s=0.1, device=False)
    report = state.status_report(verbose=True)
    assert "profiler:" in report
    assert "last capture" in report


def test_trace_dump_merges_device_tracks(rt3):
    """trace_dump(profile_id=...) is valid Perfetto JSON holding BOTH
    runtime spans and per-device tracks from the capture."""
    from ray_tpu.core.config import cfg

    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    work = _busy_jit()
    record = state.profile(duration_s=0.3)
    t = threading.Thread(target=work, daemon=True)
    t.start()
    t.join()
    cfg.set(profile_merge_max_events=500)
    try:
        payload = state.trace_dump(profile_id=record["profile_id"])
    finally:
        cfg.reset("profile_merge_max_events")
    trace = json.loads(payload)
    events = trace["traceEvents"]
    device = [e for e in events if str(e.get("pid", "")).startswith("device:")]
    spans = [e for e in events if not str(e.get("pid", "")).startswith("device:")]
    assert device, "no device tracks merged"
    assert spans, "runtime spans missing from the merged export"
    assert any(e["name"] == "task.execute" for e in spans)
    with pytest.raises(ValueError, match="no registered profile"):
        state.trace_dump(profile_id="bogus")


def test_check_lazy_jax_wired():
    """scripts/check_lazy_jax.py is now a shim over the raylint lazy-jax
    rule; the repo-wide gate runs ONCE in tests/test_raylint.py. Here:
    the shim's compat API still flags a module-level jax import and
    accepts a function-local one."""
    import ast
    import importlib.util

    repo = Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_lazy_jax.py"
    spec = importlib.util.spec_from_file_location("clj", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    bad = ast.parse("import jax\n")
    assert mod.module_level_jax_imports(bad) == [1]
    good = ast.parse("def f():\n    import jax\n")
    assert mod.module_level_jax_imports(good) == []


# --------------------------------------------------------- train MFU gauges


def test_train_run_publishes_mfu_from_cost_analysis(rt):
    """A short CPU-backend train run publishes a nonzero raytpu_train_mfu
    gauge derived from the compiled step's cost_analysis(), and the
    accounting lands in the Result."""
    from ray_tpu.train import RunConfig, ScalingConfig, Trainer

    def loop(config):
        from ray_tpu.models import get_config
        from ray_tpu.train.trainer import LMTrainer

        model = get_config("gpt2-tiny")
        trainer = LMTrainer(model, learning_rate=1e-3, total_steps=4)

        def batches():
            key = jax.random.PRNGKey(0)
            for _ in range(4):
                key, sub = jax.random.split(key)
                yield {"tokens": jax.random.randint(
                    sub, (8, 17), 0, model.vocab_size
                )}

        trainer.train(batches(), num_steps=4, report_every=2)

    result = Trainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="mfu_run"),
        train_loop_config={},
    ).fit()
    assert result.profiling is not None
    assert result.profiling["mfu"] > 0
    assert result.profiling["step_flops"] > 0
    assert result.metrics["mfu"] > 0  # rides the ordinary report metrics
    text = registry().prometheus_text()
    assert 'raytpu_train_mfu{run="mfu_run"}' in text
    mfu_line = [
        l for l in text.splitlines()
        if l.startswith('raytpu_train_mfu{run="mfu_run"}')
    ][0]
    assert float(mfu_line.split()[-1]) > 0
    assert 'raytpu_train_roofline_fraction{resource="hbm",run="mfu_run"}' in text


# ----------------------------------------------------- engine tick gauges


def test_engine_batch_occupancy_accounting(rt):
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine

    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        engine.generate([5, 17, 42], max_tokens=6)
        deadline = time.time() + 10
        while engine.metrics.get("tick_seconds", 0.0) == 0.0:
            assert time.time() < deadline, "engine never recorded a tick"
            time.sleep(0.01)
        assert engine.metrics["prefill_tokens"] >= 3
        assert engine.metrics["decode_tokens"] > 0
        # the compiled decode program priced itself via cost_analysis
        assert engine.metrics.get("decode_mfu", 0.0) > 0
        text = registry().prometheus_text()
        assert "raytpu_engine_batch_fill" in text
        assert 'raytpu_engine_token_mix{engine="%s",phase="prefill"}' % (
            engine.metrics_label
        ) in text
    finally:
        engine.shutdown()


def test_paged_engine_batch_occupancy_accounting(rt):
    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm.paged import PagedConfig
    from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine

    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = PagedLLMEngine(
        config, params,
        PagedEngineConfig(max_slots=2, paged=PagedConfig(
            page_size=8, num_pages=32, max_pages_per_slot=8, chunk_pages=2
        )),
    )
    try:
        engine.generate([5, 17, 42, 7], max_tokens=6)
        assert engine.metrics["prefill_tokens"] >= 4
        assert engine.metrics["decode_tokens"] > 0
        assert engine.metrics["tick_seconds"] > 0
        assert engine.metrics_label.startswith("paged-")
    finally:
        engine.shutdown()


# ------------------------------------------------- cluster RPC capture


def test_cluster_profile_capture_rpc():
    """Coordinated capture over a real subprocess agent: the RPC fans
    out, the remote answers with its host profile (device skipped — the
    agent process never imported jax), artifacts land in the head's
    store."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.config import cfg

    registry().clear()
    c = Cluster(head_node_args={
        "num_cpus": 2,
        "_system_config": {"node_heartbeat_s": 0.2},
    })
    try:
        c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
        c.wait_for_nodes(2)
        record = state.profile(duration_s=0.4, device=False)
        assert len(record["nodes"]) == 2
        for node_hex, meta in record["nodes"].items():
            assert meta.get("host") == "ok", meta
            data = state.profile_artifact(
                record["profile_id"], node_hex, "host_profile.txt"
            )
            assert b"host sampling profile" in data
    finally:
        c.shutdown()
        cfg.reset()
        registry().clear()
