"""Metrics registry, /metrics endpoint, state API, chrome-trace timeline."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import (
    Counter,
    Gauge,
    Histogram,
    chrome_tracing_dump,
    list_nodes,
    list_objects,
    list_tasks,
    registry,
    start_metrics_server,
    summary,
)


@pytest.fixture(autouse=True)
def rt():
    registry().clear()
    runtime = ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()
    registry().clear()


def test_counter_gauge_histogram_collect():
    c = Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    collected = dict(
        (tuple(sorted(t.items())), v) for t, v in c.collect()
    )
    assert collected[(("route", "/a"),)] == 3.0

    g = Gauge("queue_depth", "depth")
    g.set(7)
    assert g.collect() == [({}, 7.0)]

    h = Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    ((_, data),) = h.collect()
    assert data["count"] == 3
    assert data["sum"] == pytest.approx(5.55)
    assert data["buckets"] == [(0.1, 1), (1.0, 1)]


def test_prometheus_text_format():
    Counter("mycount", "a counter").inc(5)
    text = registry().prometheus_text()
    assert "# TYPE mycount counter" in text
    assert "mycount 5.0" in text


def test_metrics_http_endpoint():
    Gauge("live_gauge", "x").set(42)
    port = start_metrics_server()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        body = r.read().decode()
    assert "live_gauge 42.0" in body


def test_callback_gauge_samples_at_scrape():
    state = {"v": 1.0}
    Gauge("cb_gauge", "callback", fn=lambda: state["v"])
    assert "cb_gauge 1.0" in registry().prometheus_text()
    state["v"] = 9.0
    assert "cb_gauge 9.0" in registry().prometheus_text()


def test_state_api_lists():
    @ray_tpu.remote
    def work(x):
        return x * 2

    refs = [work.remote(i) for i in range(5)]  # held: dropping them GC's the objects
    ray_tpu.get(refs)
    tasks = list_tasks()
    assert len(tasks) >= 5
    assert all(t["ok"] for t in tasks if t["name"] == "work")
    nodes = list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    assert any(o["state"] == "READY" for o in list_objects())
    s = summary()
    assert s["tasks_finished"] >= 5


def test_chrome_tracing_dump(tmp_path):
    @ray_tpu.remote
    def traced():
        import time

        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    path = tmp_path / "trace.json"
    payload = chrome_tracing_dump(str(path))
    trace = json.loads(payload)
    events = [e for e in trace["traceEvents"] if e["name"] == "traced"]
    assert len(events) == 3
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 10_000  # ≥10ms in microseconds
    assert path.exists()


def test_device_trace_captures_xla_profile(tmp_path):
    """util.profiling.device_trace writes a TensorBoard-loadable XLA
    profile for work dispatched inside the block (SURVEY §5 tracing)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import annotate, device_trace, step_annotation

    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    with device_trace(logdir):
        with annotate("warmup"):
            f(x).block_until_ready()
        for step in range(2):
            with step_annotation(step):
                f(x).block_until_ready()
    import os

    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(files)
    assert found, "device trace produced no profile files"
    assert any("trace" in name or name.endswith(".pb") for name in found), found
