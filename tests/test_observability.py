"""Metrics registry, /metrics endpoint, state API, distributed tracing."""

import json
import re
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import (
    Counter,
    Gauge,
    Histogram,
    chrome_tracing_dump,
    get_trace,
    list_nodes,
    list_objects,
    list_tasks,
    list_traces,
    registry,
    start_metrics_server,
    summary,
    trace_dump,
)


@pytest.fixture(autouse=True)
def rt():
    registry().clear()
    runtime = ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()
    registry().clear()


def test_counter_gauge_histogram_collect():
    c = Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    collected = dict(
        (tuple(sorted(t.items())), v) for t, v in c.collect()
    )
    assert collected[(("route", "/a"),)] == 3.0

    g = Gauge("queue_depth", "depth")
    g.set(7)
    assert g.collect() == [({}, 7.0)]

    h = Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    ((_, data),) = h.collect()
    assert data["count"] == 3
    assert data["sum"] == pytest.approx(5.55)
    assert data["buckets"] == [(0.1, 1), (1.0, 1)]


def test_prometheus_text_format():
    Counter("mycount", "a counter").inc(5)
    text = registry().prometheus_text()
    assert "# TYPE mycount counter" in text
    assert "mycount 5.0" in text


def test_metrics_http_endpoint():
    Gauge("live_gauge", "x").set(42)
    port = start_metrics_server()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        body = r.read().decode()
    assert "live_gauge 42.0" in body


def test_callback_gauge_samples_at_scrape():
    state = {"v": 1.0}
    Gauge("cb_gauge", "callback", fn=lambda: state["v"])
    assert "cb_gauge 1.0" in registry().prometheus_text()
    state["v"] = 9.0
    assert "cb_gauge 9.0" in registry().prometheus_text()


def test_state_api_lists():
    @ray_tpu.remote
    def work(x):
        return x * 2

    refs = [work.remote(i) for i in range(5)]  # held: dropping them GC's the objects
    ray_tpu.get(refs)
    tasks = list_tasks()
    assert len(tasks) >= 5
    assert all(t["ok"] for t in tasks if t["name"] == "work")
    nodes = list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    assert any(o["state"] == "READY" for o in list_objects())
    s = summary()
    assert s["tasks_finished"] >= 5


def test_chrome_tracing_dump_deprecated_delegates(tmp_path):
    """chrome_tracing_dump is a thin wrapper over trace_dump now: same
    payload (the span export), one DeprecationWarning per process."""
    import warnings as _warnings

    from ray_tpu.util import state as _state

    @ray_tpu.remote
    def traced():
        import time

        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    _state._chrome_dump_warned[0] = False  # reset the one-shot latch
    path = tmp_path / "trace.json"
    with pytest.warns(DeprecationWarning, match="trace_dump"):
        payload = chrome_tracing_dump(str(path))
    trace = json.loads(payload)
    execs = [
        e for e in trace["traceEvents"]
        if e["name"] == "task.execute" and e["args"].get("task") == "traced"
    ]
    assert len(execs) == 3
    for e in execs:
        assert e["ph"] == "X"
        assert e["dur"] >= 10_000  # ≥10ms in microseconds
    assert path.exists()
    # delegation means the two exports CANNOT drift
    assert json.loads(chrome_tracing_dump()) == json.loads(trace_dump())
    # ...and the warning is one-shot
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        chrome_tracing_dump()
    assert not [w for w in caught if w.category is DeprecationWarning]


# ---------------------------------------------------------- exposition format

# one exposition line: name{labels} value  (labels optional)
_EXPO_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{([a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*",?)*\})? '
    r"[0-9.eE+-]+(inf|nan)?$"
)


def test_metrics_scrape_parses_with_escaped_labels():
    """Fetch /metrics and validate the exposition format line by line:
    tagged histogram series stay distinct, and backslash/quote/newline in
    label values are escaped instead of corrupting the payload."""
    c = Counter("evil_labels_total", 'desc with "quotes"\nand newline',
                tag_keys=("path",))
    c.inc(tags={"path": 'C:\\tmp\n"quoted"'})
    h = Histogram("lat_seconds", "latency", boundaries=[0.1, 1.0],
                  tag_keys=("route",))
    h.observe(0.05, tags={"route": "a"})
    h.observe(5.0, tags={"route": 'b\\"x\n'})
    port = start_metrics_server()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        body = r.read().decode()
    for line in body.strip().splitlines():
        if line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), f"unparseable exposition line: {line!r}"
    # escaped sequences present, raw ones absent
    assert '\\\\tmp' in body and '\\"quoted\\"' in body and "\\n" in body
    # tagged histogram series: labels + le on bucket lines, both routes
    assert re.search(r'lat_seconds_bucket\{route="a",le="0.1"\} 1', body)
    assert re.search(r'lat_seconds_count\{route="a"\} 1', body)
    assert 'route="b' in body


def test_callback_gauge_tagged_samples_and_sampler_warning():
    state = {"fail": False}

    def sample():
        if state["fail"]:
            raise RuntimeError("sampler broke")
        return [({"shard": "a"}, 1.0), ({"shard": "b"}, 2.0)]

    Gauge("cb_tagged", "tagged callback", tag_keys=("shard",), fn=sample)
    text = registry().prometheus_text()
    assert 'cb_tagged{shard="a"} 1.0' in text
    assert 'cb_tagged{shard="b"} 2.0' in text
    # a raising sampler suppresses the series AND emits one WARNING event
    from ray_tpu.util.events import events

    before = len(events().list(severity="WARNING", source="metrics",
                               limit=1000))
    state["fail"] = True
    assert registry().prometheus_text().count("cb_tagged") == 2  # HELP/TYPE only
    registry().prometheus_text()  # second failing scrape: no duplicate event
    warnings = events().list(severity="WARNING", source="metrics", limit=1000)
    mine = [w for w in warnings if "cb_tagged" in w["message"]]
    assert len(mine) == 1 and len(warnings) == before + 1


def test_event_sink_cached_handle(tmp_path):
    from ray_tpu.util.events import EventLog

    path = str(tmp_path / "ev.jsonl")
    log = EventLog()
    log.set_sink(path)
    log.emit("INFO", "test", "one")
    log.emit("INFO", "test", "two")
    lines = [json.loads(l) for l in open(path).read().splitlines()]
    assert [e["message"] for e in lines] == ["one", "two"]
    # the handle is cached (no reopen per event) and swapped on set_sink
    first_handle = log._sink_file
    assert first_handle is not None
    log.emit("INFO", "test", "three")
    assert log._sink_file is first_handle
    other = str(tmp_path / "ev2.jsonl")
    log.set_sink(other)
    assert log._sink_file is not first_handle
    log.emit("INFO", "test", "four")
    assert "four" in open(other).read()
    log.set_sink(None)
    log.emit("INFO", "test", "five")
    assert "five" not in open(other).read()


# ------------------------------------------------------------------- tracing


def test_local_task_trace_spans_and_metrics():
    """submit → queue → execute → result share one trace; queue/exec
    histograms derive from the spans."""

    @ray_tpu.remote
    def traced_work():
        import time

        time.sleep(0.01)
        return 1

    assert ray_tpu.get(traced_work.remote(), timeout=30) == 1
    trace = [t for t in list_traces() if t["root"] == "task.submit"][-1]
    spans = get_trace(trace["trace_id"])
    names = {s["name"] for s in spans}
    assert {"task.submit", "task.queue", "task.execute", "task.result"} <= names
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        assert s["trace_id"] == trace["trace_id"]
        if s["parent_id"] is not None:
            assert s["parent_id"] in by_id, f"orphan parent for {s['name']}"
    execute = next(s for s in spans if s["name"] == "task.execute")
    assert execute["duration_s"] >= 0.01
    text = registry().prometheus_text()
    assert "raytpu_task_queue_seconds_count" in text
    assert "raytpu_task_exec_seconds_count" in text


def test_trace_export_valid_chrome_json(tmp_path):
    @ray_tpu.remote
    def exported():
        return 2

    ray_tpu.get(exported.remote(), timeout=30)
    path = tmp_path / "spans.json"
    payload = trace_dump(str(path))
    trace = json.loads(payload)  # must load as valid chrome-trace JSON
    assert path.exists() and json.loads(path.read_text()) == trace
    events = trace["traceEvents"]
    assert events, "no span events exported"
    for e in events:
        # span slices are complete events; cross-lane parent->child
        # links additionally export as flow start/finish pairs (PR 9)
        assert e["ph"] in ("X", "s", "f")
        assert isinstance(e["ts"], float)
        if e["ph"] == "X":
            assert e["dur"] >= 0.0
        assert "trace_id" in e["args"]
    assert any(e["name"] == "task.execute" for e in events)
    # CLI path: ray_tpu timeline --trace
    from ray_tpu.cli import main as cli_main

    out = tmp_path / "cli_trace.json"
    assert cli_main(["timeline", "--trace", str(out)]) == 0
    assert json.loads(out.read_text())["traceEvents"]


def test_trace_sampling_knob():
    from ray_tpu.core.config import cfg
    from ray_tpu.util.tracing import tracer

    @ray_tpu.remote
    def unsampled():
        return 3

    cfg.set(trace_sample_ratio=0.0)
    try:
        before = len(tracer().spans())
        ray_tpu.get(unsampled.remote(), timeout=30)
        new = [
            s for s in tracer().spans()[before:]
            if s["attrs"].get("task") == "unsampled"
        ]
        assert new == [], f"unsampled trace still recorded: {new}"
    finally:
        cfg.reset("trace_sample_ratio")


def test_remote_task_span_parents_to_driver_submit_across_rpc():
    """Acceptance: a remote task yields ONE trace whose execute span (on
    the agent process) walks back to the driver's submit span, stitched
    through the state API across the RPC boundary."""
    import time as _time

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    ray_tpu.shutdown()  # the autouse fixture runtime is not a cluster head
    from ray_tpu.core.config import cfg

    c = Cluster(head_node_args={
        "num_cpus": 2,
        "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
    })
    try:
        c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
        c.wait_for_nodes(2)
        remote_node = next(
            n for n in c.runtime.scheduler.nodes() if n.is_remote
        )

        @ray_tpu.remote
        def remote_probe():
            import os

            return os.getpid()

        pid = ray_tpu.get(
            remote_probe.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    remote_node.node_id
                )
            ).remote(),
            timeout=60,
        )
        import os

        assert pid != os.getpid(), "task did not land on the agent"
        _time.sleep(0.3)  # let the agent finish recording result spans
        trace = next(
            t for t in reversed(list_traces())
            if t["root"] == "task.submit"
        )
        spans = get_trace(trace["trace_id"])
        names = {s["name"] for s in spans}
        assert {"task.submit", "task.queue", "task.dispatch",
                "task.execute", "task.result"} <= names, names
        by_id = {s["span_id"]: s for s in spans}
        execute = next(s for s in spans if s["name"] == "task.execute")
        assert execute["attrs"].get("remote") is True  # ran on the agent
        chain = []
        cur = execute
        while cur["parent_id"] is not None:
            cur = by_id[cur["parent_id"]]
            chain.append(cur["name"])
        assert chain[-1] == "task.submit", chain
        assert all(s["trace_id"] == trace["trace_id"] for s in spans)
        # exportable as valid chrome JSON through the state API
        exported = json.loads(trace_dump(trace_id=trace["trace_id"]))
        assert any(
            e["name"] == "task.execute" for e in exported["traceEvents"]
        )
        # span-derived histograms visible on the scrape
        text = registry().prometheus_text()
        assert "raytpu_task_queue_seconds_count" in text
    finally:
        c.shutdown()
        cfg.reset()


def test_serve_request_spans_yield_ttft_tpot():
    """An engine request span carries token counts and yields TTFT/TPOT
    observations into the serve histograms."""
    import jax

    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine
    from ray_tpu.util.tracing import tracer

    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        tokens = engine.generate([5, 17, 42, 7], max_tokens=8)
        assert len(tokens) == 8
    finally:
        engine.shutdown()
    req = next(
        s for s in reversed(tracer().spans())
        if s["name"] == "engine.request"
    )
    assert req["attrs"]["generated_tokens"] == 8
    assert req["attrs"]["ttft_s"] > 0
    assert req["attrs"]["tpot_s"] > 0
    assert req["attrs"]["queue_s"] >= 0
    text = registry().prometheus_text()
    assert "raytpu_serve_ttft_seconds_count" in text
    assert "raytpu_serve_tpot_seconds_count" in text
    assert any(
        s["name"] == "engine.prefill" for s in tracer().spans()
    )


def test_metric_names_static_check():
    """scripts/check_metrics_names.py is now a shim over the raylint
    metrics-names rule; the repo-wide gate runs ONCE in
    tests/test_raylint.py. Here: the shim's compat API still flags a
    bad package, not just passes everything."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_metrics_names.py"
    import importlib.util

    spec = importlib.util.spec_from_file_location("cmn", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        bad = pathlib.Path(tmp) / "pkg"
        bad.mkdir()
        (bad / "m.py").write_text(
            'c = Counter("unprefixed_total", "x")\n'
            'd = Counter("raytpu_dup_total", "x")\n'
            'h = Histogram("raytpu_nobounds_seconds", "x")\n'
            'h2 = get_or_create_histogram(\n'
            '    "raytpu_bounded_seconds", "x",\n'
            '    boundaries=(0.1, 1.0),\n'
            ')\n'
            'value = some_gauge._fn()\n'
        )
        (bad / "n.py").write_text(
            'e = Counter("raytpu_dup_total", "x")\n'
            'class MyMetric:\n'
            '    def collect(self):\n'
            '        return []\n'
        )
        errors = mod.check(bad)
        assert any("unprefixed_total" in e for e in errors)
        assert any("raytpu_dup_total" in e and "2 sites" in e for e in errors)
        # new rules: histograms need explicit boundaries; sampler-guard
        # bypasses (direct ._fn() calls, collect() overrides) are flagged
        assert any("raytpu_nobounds_seconds" in e and "boundaries" in e
                   for e in errors)
        assert not any("raytpu_bounded_seconds" in e for e in errors)
        assert any("._fn()" in e for e in errors)
        assert any("collect() override" in e for e in errors)


# ------------------------------------------------------------ telemetry plane


def test_node_stats_snapshot_and_gauges(rt):
    """The per-node collector samples process/store/pool/queue stats and
    the node-local gauges ride the scrape."""
    snap = rt.node_stats.snapshot()
    for key in ("cpu_percent", "rss_bytes", "object_store", "worker_pool",
                "task_queues", "scheduler", "health", "pubsub", "tpu", "ts"):
        assert key in snap, key
    assert snap["rss_bytes"] > 0
    assert set(snap["task_queues"]) == {"pending", "blocked", "admission"}
    assert set(snap["worker_pool"]) >= {"busy", "idle"}
    text = registry().prometheus_text()
    for name in ("raytpu_node_cpu_percent", "raytpu_node_rss_bytes",
                 "raytpu_node_worker_pool", "raytpu_node_task_queue_depth"):
        assert f"# TYPE {name} gauge" in text, name
    assert re.search(r'raytpu_node_task_queue_depth\{queue="pending"\} ', text)


def test_status_report_renders():
    """Acceptance: `ray_tpu status` against an in-process runtime shows
    per-node resource usage, object-store bytes and worker-pool
    occupancy (state.status_report backs the CLI)."""
    from ray_tpu.util.state import status_report

    @ray_tpu.remote
    def work(x):
        return x + 1

    ray_tpu.get([work.remote(i) for i in range(3)])
    report = status_report()
    assert "Nodes: 1 (1 ALIVE)" in report
    assert "resources: CPU:" in report
    assert "object store:" in report
    assert "worker pool:" in report and "busy" in report
    assert "Scheduler: dispatched=" in report
    assert "Recent warnings" in report
    # --verbose appends per-node log tails
    assert "Logs (per node):" in status_report(verbose=True)


def test_metrics_cluster_endpoint_node_id_labels():
    """/metrics/cluster returns a parseable merged exposition where
    every sample carries a node_id label (single-node degenerate case)."""
    Counter("raytpu_probe_total", "probe").inc(3)
    port = start_metrics_server()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics/cluster", timeout=10
    ) as r:
        body = r.read().decode()
    local_hex = ray_tpu.api._runtime().scheduler.head_node().node_id.hex()
    samples = [
        l for l in body.strip().splitlines() if not l.startswith("#")
    ]
    assert samples
    for line in samples:
        assert _EXPO_LINE.match(line), f"unparseable merged line: {line!r}"
        assert 'node_id="' in line, f"sample without node_id: {line!r}"
    assert f'node_id="{local_hex}"' in body
    assert re.search(
        rf'raytpu_probe_total\{{node_id="{local_hex}"\}} 3', body
    )


def test_cluster_telemetry_roundtrip_and_federation():
    """Capstone: stats snapshots round-trip through the GCS node table
    via the heartbeat piggyback, and the head federates both nodes'
    expositions with node_id labels over the metrics_snapshot RPC."""
    import time as _time

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.cluster import NODE_NS
    from ray_tpu.core.config import cfg
    from ray_tpu.util.metrics import cluster_prometheus_text
    from ray_tpu.util.state import node_stats, status_report, summary

    ray_tpu.shutdown()  # the autouse fixture runtime is not a cluster head
    c = Cluster(head_node_args={
        "num_cpus": 2,
        "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2,
                           "node_stats_period_s": 0.2},
    })
    try:
        c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2,
                                              "node_stats_period_s": 0.2})
        c.wait_for_nodes(2)
        ctx = c.runtime.cluster
        # (1) snapshot round-trip through the node table
        deadline = _time.monotonic() + 10
        table = {}
        while _time.monotonic() < deadline:
            table = {
                key: ctx.gcs.kv_get(key, namespace=NODE_NS)
                for key in ctx.gcs.kv_keys(namespace=NODE_NS)
            }
            if len(table) == 2 and all(
                (info or {}).get("stats") for info in table.values()
            ):
                break
            _time.sleep(0.1)
        assert len(table) == 2
        for info in table.values():
            stats = info.get("stats")
            assert stats, f"no stats piggybacked for {info.get('node_id')}"
            assert "object_store" in stats and "worker_pool" in stats
            assert "task_queues" in stats and stats["rss_bytes"] > 0
        # (2) state API carries both snapshots
        ns = node_stats()
        assert set(ns) == set(table)
        assert summary()["node_stats"].keys() == ns.keys()
        # (3) federated exposition: every sample labeled, both nodes in
        merged = cluster_prometheus_text()
        samples = [
            l for l in merged.strip().splitlines() if not l.startswith("#")
        ]
        assert samples
        for line in samples:
            assert _EXPO_LINE.match(line), f"unparseable: {line!r}"
            assert 'node_id="' in line, line
        for node_hex in table:
            assert f'node_id="{node_hex}"' in merged, node_hex[:12]
        # TYPE headers are deduplicated across nodes
        assert merged.count("# TYPE raytpu_node_rss_bytes gauge") == 1
        # (4) the status report sees the cluster
        report = status_report()
        assert "Nodes: 2" in report
    finally:
        c.shutdown()
        cfg.reset()


# ---------------------------------------------------------------- watchdogs


def test_stall_watchdog_unit_transitions():
    """Deterministic stall logic: EWMA regression names the straggler,
    the no-progress window catches a dead gang, recovery clears."""
    from ray_tpu.util.events import events
    from ray_tpu.util.watchdog import StallWatchdog

    wd = StallWatchdog("unit_run", 2, window_s=10.0, factor=3.0,
                       alpha=0.5, min_s=0.5)
    t0 = 1000.0
    # both ranks step every 0.2s for a while
    for i in range(6):
        wd.observe_report(0, t0 + 0.2 * i)
        wd.observe_report(1, t0 + 0.2 * i)
    now = t0 + 0.2 * 5
    assert wd.check(now + 0.1) is False
    # rank 1 goes silent: gap blows past factor x EWMA (and min_s)
    for i in range(6, 10):
        wd.observe_report(0, t0 + 0.2 * i)
    assert wd.check(t0 + 0.2 * 9 + 0.8) is True
    assert wd.straggler == 1
    g = registry().get("raytpu_train_stalled")
    assert dict((tuple(sorted(t.items())), v) for t, v in g.collect())[
        (("run", "unit_run"),)
    ] == 1.0
    warned = [
        e for e in events().list(severity="WARNING", source="watchdog",
                                 limit=100)
        if "unit_run" in e["message"]
    ]
    assert warned and "rank 1" in warned[-1]["message"]
    # rank 1 recovers
    wd.observe_report(1, t0 + 0.2 * 9 + 0.9)
    wd.observe_report(0, t0 + 0.2 * 9 + 0.9)
    assert wd.check(t0 + 0.2 * 9 + 1.0) is False
    assert dict((tuple(sorted(t.items())), v) for t, v in g.collect())[
        (("run", "unit_run"),)
    ] == 0.0
    # global no-progress window
    assert wd.check(t0 + 1000.0) is True
    wd.close()
    assert dict((tuple(sorted(t.items())), v) for t, v in g.collect())[
        (("run", "unit_run"),)
    ] == 0.0


def test_stall_watchdog_fires_on_injected_slow_gang_worker():
    """Acceptance: a chaos-injected slow gang worker flips
    raytpu_train_stalled to 1 and emits a WARNING naming the straggler
    rank; the gauge clears when the worker recovers."""
    import threading as _threading
    import time as _time

    from ray_tpu import train
    from ray_tpu.core.config import cfg
    from ray_tpu.train import (
        RunConfig,
        ScalingConfig,
        TrainController,
    )
    from ray_tpu.util.events import events

    cfg.set(train_stall_window_s=60.0,  # global window off the hot path
            train_stall_factor=4.0, train_stall_min_s=0.25,
            train_stall_ewma_alpha=0.3)
    run_name = "stall_drill"

    def train_fn(config):
        ctx = train.get_context()
        for step in range(25):
            train.report({"step": step})
            if ctx.world_rank == 1 and step == 10:
                _time.sleep(1.2)  # injected slow step: the straggler
            else:
                _time.sleep(0.03)

    controller = TrainController(
        train_fn,
        ScalingConfig(num_workers=2,
                      resources_per_worker={"CPU": 1.0}),
        RunConfig(name=run_name),
        train_config={},
        poll_interval=0.02,
    )
    result_box = {}

    def run():
        result_box["result"] = controller.run()

    t = _threading.Thread(target=run, daemon=True)
    t.start()

    def stalled_value():
        g = registry().get("raytpu_train_stalled")
        if g is None:
            return None
        vals = dict(
            (tuple(sorted(tags.items())), v) for tags, v in g.collect()
        )
        return vals.get((("run", run_name),))

    deadline = _time.monotonic() + 30
    fired = False
    while _time.monotonic() < deadline:
        if stalled_value() == 1.0:
            fired = True
            break
        _time.sleep(0.02)
    assert fired, "stall watchdog never fired on the injected slow worker"
    warned = [
        e for e in events().list(severity="WARNING", source="watchdog",
                                 limit=200)
        if run_name in e["message"] and "STALLED" in e["message"]
    ]
    assert warned, "no WARNING event from the stall watchdog"
    assert "rank 1" in warned[0]["message"], warned[0]["message"]
    assert warned[0].get("extra", {}).get("straggler_rank") == 1
    t.join(timeout=60)
    assert not t.is_alive()
    assert result_box["result"].status.value == "FINISHED", (
        result_box["result"].error
    )
    # run over (watchdog closed): the stalled gauge reads 0 again
    assert stalled_value() == 0.0
    cfg.reset("train_stall_window_s")
    cfg.reset("train_stall_factor")
    cfg.reset("train_stall_min_s")
    cfg.reset("train_stall_ewma_alpha")


def test_serve_slo_monitor_burns_on_p99_violation():
    """The SLO monitor diffs the PR-2 histograms per window and burns
    raytpu_serve_slo_burn_total{slo=ttft_p99} + a WARNING event when the
    window's p99 exceeds the objective."""
    from ray_tpu.core.config import cfg
    from ray_tpu.util.events import events
    from ray_tpu.util.watchdog import ServeSLOMonitor

    from ray_tpu.util.metrics import get_or_create_histogram

    hist = get_or_create_histogram(
        "raytpu_serve_ttft_seconds", "ttft",
        boundaries=(0.005, 0.025, 0.1, 0.5, 2.0, 10.0),
    )
    cfg.set(serve_slo_ttft_p99_s=0.1)
    try:
        monitor = ServeSLOMonitor()
        monitor.check()  # baseline the window cursor
        for _ in range(50):
            hist.observe(1.5)  # way over the 100ms objective
        verdict = monitor.check()
        assert verdict["ttft_p99"] > 0.1
        burn = registry().get("raytpu_serve_slo_burn_total")
        assert burn is not None
        burns = dict(
            (tuple(sorted(t.items())), v) for t, v in burn.collect()
        )
        assert burns[(("slo", "ttft_p99"),)] == 1.0
        warned = events().list(severity="WARNING", source="watchdog",
                               limit=50)
        assert any("serve SLO burn" in e["message"] and "ttft_p99"
                   in e["message"] for e in warned)
        # a healthy window does NOT burn again
        for _ in range(200):
            hist.observe(0.01)
        monitor.check()
        burns = dict(
            (tuple(sorted(t.items())), v) for t, v in burn.collect()
        )
        assert burns[(("slo", "ttft_p99"),)] == 1.0
    finally:
        cfg.reset("serve_slo_ttft_p99_s")


def test_log_lines_carry_node_and_task_attribution():
    """Captured log tails attribute lines with [node:...] and, inside a
    task, [task:...] — so aggregated tails keep their origin."""
    import logging as _logging

    from ray_tpu.util import logs

    _logging.getLogger("ray_tpu.test").warning("outside-any-task")

    @ray_tpu.remote
    def noisy():
        _logging.getLogger("ray_tpu.test").warning("inside-the-task")
        return 1

    assert ray_tpu.get(noisy.remote(), timeout=30) == 1
    tail = logs.tail(200)
    outside = next(l for l in tail if "outside-any-task" in l)
    inside = next(l for l in tail if "inside-the-task" in l)
    assert "[node:" in outside and "[task:" not in outside
    assert "[node:" in inside and "[task:" in inside


def test_device_trace_captures_xla_profile(tmp_path):
    """util.profiling.device_trace writes a TensorBoard-loadable XLA
    profile for work dispatched inside the block (SURVEY §5 tracing)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import annotate, device_trace, step_annotation

    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    with device_trace(logdir):
        with annotate("warmup"):
            f(x).block_until_ready()
        for step in range(2):
            with step_annotation(step):
                f(x).block_until_ready()
    import os

    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(files)
    assert found, "device trace produced no profile files"
    assert any("trace" in name or name.endswith(".pb") for name in found), found
