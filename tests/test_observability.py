"""Metrics registry, /metrics endpoint, state API, distributed tracing."""

import json
import re
import urllib.request

import pytest

import ray_tpu
from ray_tpu.util import (
    Counter,
    Gauge,
    Histogram,
    chrome_tracing_dump,
    get_trace,
    list_nodes,
    list_objects,
    list_tasks,
    list_traces,
    registry,
    start_metrics_server,
    summary,
    trace_dump,
)


@pytest.fixture(autouse=True)
def rt():
    registry().clear()
    runtime = ray_tpu.init(num_cpus=4, detect_accelerators=False)
    yield runtime
    ray_tpu.shutdown()
    registry().clear()


def test_counter_gauge_histogram_collect():
    c = Counter("reqs_total", "requests", tag_keys=("route",))
    c.inc(tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    c.inc(tags={"route": "/b"})
    collected = dict(
        (tuple(sorted(t.items())), v) for t, v in c.collect()
    )
    assert collected[(("route", "/a"),)] == 3.0

    g = Gauge("queue_depth", "depth")
    g.set(7)
    assert g.collect() == [({}, 7.0)]

    h = Histogram("latency_s", "latency", boundaries=[0.1, 1.0])
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    ((_, data),) = h.collect()
    assert data["count"] == 3
    assert data["sum"] == pytest.approx(5.55)
    assert data["buckets"] == [(0.1, 1), (1.0, 1)]


def test_prometheus_text_format():
    Counter("mycount", "a counter").inc(5)
    text = registry().prometheus_text()
    assert "# TYPE mycount counter" in text
    assert "mycount 5.0" in text


def test_metrics_http_endpoint():
    Gauge("live_gauge", "x").set(42)
    port = start_metrics_server()
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
        body = r.read().decode()
    assert "live_gauge 42.0" in body


def test_callback_gauge_samples_at_scrape():
    state = {"v": 1.0}
    Gauge("cb_gauge", "callback", fn=lambda: state["v"])
    assert "cb_gauge 1.0" in registry().prometheus_text()
    state["v"] = 9.0
    assert "cb_gauge 9.0" in registry().prometheus_text()


def test_state_api_lists():
    @ray_tpu.remote
    def work(x):
        return x * 2

    refs = [work.remote(i) for i in range(5)]  # held: dropping them GC's the objects
    ray_tpu.get(refs)
    tasks = list_tasks()
    assert len(tasks) >= 5
    assert all(t["ok"] for t in tasks if t["name"] == "work")
    nodes = list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]
    assert any(o["state"] == "READY" for o in list_objects())
    s = summary()
    assert s["tasks_finished"] >= 5


def test_chrome_tracing_dump(tmp_path):
    @ray_tpu.remote
    def traced():
        import time

        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)])
    path = tmp_path / "trace.json"
    payload = chrome_tracing_dump(str(path))
    trace = json.loads(payload)
    events = [e for e in trace["traceEvents"] if e["name"] == "traced"]
    assert len(events) == 3
    for e in events:
        assert e["ph"] == "X"
        assert e["dur"] >= 10_000  # ≥10ms in microseconds
    assert path.exists()


# ---------------------------------------------------------- exposition format

# one exposition line: name{labels} value  (labels optional)
_EXPO_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{([a-zA-Z_][a-zA-Z0-9_]*="(\\.|[^"\\])*",?)*\})? '
    r"[0-9.eE+-]+(inf|nan)?$"
)


def test_metrics_scrape_parses_with_escaped_labels():
    """Fetch /metrics and validate the exposition format line by line:
    tagged histogram series stay distinct, and backslash/quote/newline in
    label values are escaped instead of corrupting the payload."""
    c = Counter("evil_labels_total", 'desc with "quotes"\nand newline',
                tag_keys=("path",))
    c.inc(tags={"path": 'C:\\tmp\n"quoted"'})
    h = Histogram("lat_seconds", "latency", boundaries=[0.1, 1.0],
                  tag_keys=("route",))
    h.observe(0.05, tags={"route": "a"})
    h.observe(5.0, tags={"route": 'b\\"x\n'})
    port = start_metrics_server()
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics", timeout=10
    ) as r:
        body = r.read().decode()
    for line in body.strip().splitlines():
        if line.startswith("#"):
            continue
        assert _EXPO_LINE.match(line), f"unparseable exposition line: {line!r}"
    # escaped sequences present, raw ones absent
    assert '\\\\tmp' in body and '\\"quoted\\"' in body and "\\n" in body
    # tagged histogram series: labels + le on bucket lines, both routes
    assert re.search(r'lat_seconds_bucket\{route="a",le="0.1"\} 1', body)
    assert re.search(r'lat_seconds_count\{route="a"\} 1', body)
    assert 'route="b' in body


def test_callback_gauge_tagged_samples_and_sampler_warning():
    state = {"fail": False}

    def sample():
        if state["fail"]:
            raise RuntimeError("sampler broke")
        return [({"shard": "a"}, 1.0), ({"shard": "b"}, 2.0)]

    Gauge("cb_tagged", "tagged callback", tag_keys=("shard",), fn=sample)
    text = registry().prometheus_text()
    assert 'cb_tagged{shard="a"} 1.0' in text
    assert 'cb_tagged{shard="b"} 2.0' in text
    # a raising sampler suppresses the series AND emits one WARNING event
    from ray_tpu.util.events import events

    before = len(events().list(severity="WARNING", source="metrics",
                               limit=1000))
    state["fail"] = True
    assert registry().prometheus_text().count("cb_tagged") == 2  # HELP/TYPE only
    registry().prometheus_text()  # second failing scrape: no duplicate event
    warnings = events().list(severity="WARNING", source="metrics", limit=1000)
    mine = [w for w in warnings if "cb_tagged" in w["message"]]
    assert len(mine) == 1 and len(warnings) == before + 1


def test_event_sink_cached_handle(tmp_path):
    from ray_tpu.util.events import EventLog

    path = str(tmp_path / "ev.jsonl")
    log = EventLog()
    log.set_sink(path)
    log.emit("INFO", "test", "one")
    log.emit("INFO", "test", "two")
    lines = [json.loads(l) for l in open(path).read().splitlines()]
    assert [e["message"] for e in lines] == ["one", "two"]
    # the handle is cached (no reopen per event) and swapped on set_sink
    first_handle = log._sink_file
    assert first_handle is not None
    log.emit("INFO", "test", "three")
    assert log._sink_file is first_handle
    other = str(tmp_path / "ev2.jsonl")
    log.set_sink(other)
    assert log._sink_file is not first_handle
    log.emit("INFO", "test", "four")
    assert "four" in open(other).read()
    log.set_sink(None)
    log.emit("INFO", "test", "five")
    assert "five" not in open(other).read()


# ------------------------------------------------------------------- tracing


def test_local_task_trace_spans_and_metrics():
    """submit → queue → execute → result share one trace; queue/exec
    histograms derive from the spans."""

    @ray_tpu.remote
    def traced_work():
        import time

        time.sleep(0.01)
        return 1

    assert ray_tpu.get(traced_work.remote(), timeout=30) == 1
    trace = [t for t in list_traces() if t["root"] == "task.submit"][-1]
    spans = get_trace(trace["trace_id"])
    names = {s["name"] for s in spans}
    assert {"task.submit", "task.queue", "task.execute", "task.result"} <= names
    by_id = {s["span_id"]: s for s in spans}
    for s in spans:
        assert s["trace_id"] == trace["trace_id"]
        if s["parent_id"] is not None:
            assert s["parent_id"] in by_id, f"orphan parent for {s['name']}"
    execute = next(s for s in spans if s["name"] == "task.execute")
    assert execute["duration_s"] >= 0.01
    text = registry().prometheus_text()
    assert "raytpu_task_queue_seconds_count" in text
    assert "raytpu_task_exec_seconds_count" in text


def test_trace_export_valid_chrome_json(tmp_path):
    @ray_tpu.remote
    def exported():
        return 2

    ray_tpu.get(exported.remote(), timeout=30)
    path = tmp_path / "spans.json"
    payload = trace_dump(str(path))
    trace = json.loads(payload)  # must load as valid chrome-trace JSON
    assert path.exists() and json.loads(path.read_text()) == trace
    events = trace["traceEvents"]
    assert events, "no span events exported"
    for e in events:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and e["dur"] >= 0.0
        assert "trace_id" in e["args"]
    assert any(e["name"] == "task.execute" for e in events)
    # CLI path: ray_tpu timeline --trace
    from ray_tpu.cli import main as cli_main

    out = tmp_path / "cli_trace.json"
    assert cli_main(["timeline", "--trace", str(out)]) == 0
    assert json.loads(out.read_text())["traceEvents"]


def test_trace_sampling_knob():
    from ray_tpu.core.config import cfg
    from ray_tpu.util.tracing import tracer

    @ray_tpu.remote
    def unsampled():
        return 3

    cfg.set(trace_sample_ratio=0.0)
    try:
        before = len(tracer().spans())
        ray_tpu.get(unsampled.remote(), timeout=30)
        new = [
            s for s in tracer().spans()[before:]
            if s["attrs"].get("task") == "unsampled"
        ]
        assert new == [], f"unsampled trace still recorded: {new}"
    finally:
        cfg.reset("trace_sample_ratio")


def test_remote_task_span_parents_to_driver_submit_across_rpc():
    """Acceptance: a remote task yields ONE trace whose execute span (on
    the agent process) walks back to the driver's submit span, stitched
    through the state API across the RPC boundary."""
    import time as _time

    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.core.scheduler import NodeAffinitySchedulingStrategy

    ray_tpu.shutdown()  # the autouse fixture runtime is not a cluster head
    from ray_tpu.core.config import cfg

    c = Cluster(head_node_args={
        "num_cpus": 2,
        "_system_config": {"node_stale_s": 5.0, "node_heartbeat_s": 0.2},
    })
    try:
        c.add_node(num_cpus=2, system_config={"node_heartbeat_s": 0.2})
        c.wait_for_nodes(2)
        remote_node = next(
            n for n in c.runtime.scheduler.nodes() if n.is_remote
        )

        @ray_tpu.remote
        def remote_probe():
            import os

            return os.getpid()

        pid = ray_tpu.get(
            remote_probe.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    remote_node.node_id
                )
            ).remote(),
            timeout=60,
        )
        import os

        assert pid != os.getpid(), "task did not land on the agent"
        _time.sleep(0.3)  # let the agent finish recording result spans
        trace = next(
            t for t in reversed(list_traces())
            if t["root"] == "task.submit"
        )
        spans = get_trace(trace["trace_id"])
        names = {s["name"] for s in spans}
        assert {"task.submit", "task.queue", "task.dispatch",
                "task.execute", "task.result"} <= names, names
        by_id = {s["span_id"]: s for s in spans}
        execute = next(s for s in spans if s["name"] == "task.execute")
        assert execute["attrs"].get("remote") is True  # ran on the agent
        chain = []
        cur = execute
        while cur["parent_id"] is not None:
            cur = by_id[cur["parent_id"]]
            chain.append(cur["name"])
        assert chain[-1] == "task.submit", chain
        assert all(s["trace_id"] == trace["trace_id"] for s in spans)
        # exportable as valid chrome JSON through the state API
        exported = json.loads(trace_dump(trace_id=trace["trace_id"]))
        assert any(
            e["name"] == "task.execute" for e in exported["traceEvents"]
        )
        # span-derived histograms visible on the scrape
        text = registry().prometheus_text()
        assert "raytpu_task_queue_seconds_count" in text
    finally:
        c.shutdown()
        cfg.reset()


def test_serve_request_spans_yield_ttft_tpot():
    """An engine request span carries token counts and yields TTFT/TPOT
    observations into the serve histograms."""
    import jax

    from ray_tpu.models import get_config, init_params
    from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine
    from ray_tpu.util.tracing import tracer

    config = get_config("llama-tiny")
    params = init_params(config, jax.random.PRNGKey(0))
    engine = LLMEngine(config, params, EngineConfig(max_slots=2))
    try:
        tokens = engine.generate([5, 17, 42, 7], max_tokens=8)
        assert len(tokens) == 8
    finally:
        engine.shutdown()
    req = next(
        s for s in reversed(tracer().spans())
        if s["name"] == "engine.request"
    )
    assert req["attrs"]["generated_tokens"] == 8
    assert req["attrs"]["ttft_s"] > 0
    assert req["attrs"]["tpot_s"] > 0
    assert req["attrs"]["queue_s"] >= 0
    text = registry().prometheus_text()
    assert "raytpu_serve_ttft_seconds_count" in text
    assert "raytpu_serve_tpot_seconds_count" in text
    assert any(
        s["name"] == "engine.prefill" for s in tracer().spans()
    )


def test_metric_names_static_check():
    """Tier-1 wiring for scripts/check_metrics_names.py: the package obeys
    the raytpu_ prefix + no-duplicate-direct-registration rules, and the
    checker actually catches violations."""
    import pathlib
    import subprocess
    import sys as _sys

    repo = pathlib.Path(__file__).resolve().parent.parent
    script = repo / "scripts" / "check_metrics_names.py"
    proc = subprocess.run(
        [_sys.executable, str(script)], capture_output=True, text=True
    )
    assert proc.returncode == 0, proc.stderr
    # the checker must flag a bad package, not just pass everything
    import importlib.util

    spec = importlib.util.spec_from_file_location("cmn", script)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import tempfile

    with tempfile.TemporaryDirectory() as tmp:
        bad = pathlib.Path(tmp) / "pkg"
        bad.mkdir()
        (bad / "m.py").write_text(
            'c = Counter("unprefixed_total", "x")\n'
            'd = Counter("raytpu_dup_total", "x")\n'
        )
        (bad / "n.py").write_text('e = Counter("raytpu_dup_total", "x")\n')
        errors = mod.check(bad)
        assert any("unprefixed_total" in e for e in errors)
        assert any("raytpu_dup_total" in e and "2 sites" in e for e in errors)


def test_device_trace_captures_xla_profile(tmp_path):
    """util.profiling.device_trace writes a TensorBoard-loadable XLA
    profile for work dispatched inside the block (SURVEY §5 tracing)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import annotate, device_trace, step_annotation

    logdir = str(tmp_path / "trace")
    f = jax.jit(lambda x: (x @ x).sum())
    x = jnp.ones((128, 128))
    with device_trace(logdir):
        with annotate("warmup"):
            f(x).block_until_ready()
        for step in range(2):
            with step_annotation(step):
                f(x).block_until_ready()
    import os

    found = []
    for root, _dirs, files in os.walk(logdir):
        found.extend(files)
    assert found, "device trace produced no profile files"
    assert any("trace" in name or name.endswith(".pb") for name in found), found
