"""Request forensics plane (serve/reqlog.py): per-request token-level
timelines, TTFT attribution, and live engine introspection.

The load-bearing drills:
- every exit path leaves a TERMINAL phase — shed/expired requests never
  read as forever-pending;
- the TTFT decomposition is exact by construction: queue_wait +
  preempt_wait + prefill_compute == TTFT (within the 5% acceptance
  band), with cache_saved as an informational side channel;
- the flagship waterfall: one request whose timeline shows a
  prefix-cache-hit admission, speculative verify rounds with rollback,
  and a lane preemption + resume — causally ordered across phases;
- marks federate into the GCS ``_requests`` table and the state
  queries join them cluster-wide on the shared request id.
"""

import json
import queue as queue_mod
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import cfg
from ray_tpu.core.exceptions import BackPressureError, RequestTimeoutError
from ray_tpu.models import forward, get_config, init_params
from ray_tpu.serve import reqlog, tenancy
from ray_tpu.serve.llm.engine import _Request, _observe_tenant_ttft
from ray_tpu.serve.llm.paged import PagedConfig
from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine


@pytest.fixture(autouse=True)
def _clean_reqlog():
    reqlog.log().clear()
    tenancy.reset()
    yield
    reqlog.log().clear()
    tenancy.reset()
    cfg.reset()


def _greedy_reference(config, params, prompt, n):
    tokens = list(prompt)
    for _ in range(n):
        logits = forward(params, np.asarray([tokens], dtype=np.int32), config)
        tokens.append(int(np.argmax(np.asarray(logits[0, -1]))))
    return tokens[len(prompt):]


def _tiny_engine(model="llama-tiny", seed=0, **over):
    config = get_config(model)
    params = init_params(config, jax.random.PRNGKey(seed))
    paged = dict(
        page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2,
        prefix_cache=True,
    )
    paged.update(over.pop("paged", {}))
    defaults = dict(max_slots=4, paged=PagedConfig(**paged))
    defaults.update(over)
    return config, params, PagedLLMEngine(
        config, params, PagedEngineConfig(**defaults)
    )


def _phases(marks):
    return [m["phase"] for m in marks]


# ------------------------------------------------------------ recorder core


def test_mark_records_both_clocks_and_indexes():
    rl = reqlog.RequestLog()
    rec = rl.mark("req-a", "engine.submitted", tenant="t1", prompt_tokens=3)
    assert rec["rid"] == "req-a" and rec["phase"] == "engine.submitted"
    assert rec["ts"] > 0 and rec["mono"] > 0 and rec["seq"] == 1
    assert rec["attrs"] == {"prompt_tokens": 3}
    rl.mark("req-a", "engine.finished", tenant="t1")
    tl = rl.timeline("req-a")
    assert _phases(tl) == ["engine.submitted", "engine.finished"]
    (summary,) = rl.requests()
    assert summary["request_id"] == "req-a"
    assert summary["tenant"] == "t1"
    assert summary["marks"] == 2
    assert summary["terminal"] == "engine.finished"


def test_terminal_phase_first_wins():
    rl = reqlog.RequestLog()
    rl.mark("req-b", "engine.shed", reason="queue_full")
    rl.mark("req-b", "engine.finished")  # late straggler must not flip it
    (summary,) = rl.requests()
    assert summary["terminal"] == "engine.shed"
    assert reqlog.TERMINAL_PHASES <= set(reqlog.PHASES)


def test_ring_and_index_eviction():
    rl = reqlog.RequestLog(mark_capacity=8, request_capacity=4)
    for i in range(20):
        rl.mark(f"req-{i}", "engine.submitted")
    stats = rl.stats()
    assert stats["buffered_marks"] == 8
    assert stats["indexed_requests"] == 4
    assert stats["seq"] == 20
    # oldest evicted from both views, newest retained
    assert rl.timeline("req-0") == []
    assert rl.timeline("req-19")
    ids = {s["request_id"] for s in rl.requests()}
    assert ids == {f"req-{i}" for i in range(16, 20)}


def test_since_cursor_walks_oldest_first():
    rl = reqlog.RequestLog()
    for i in range(5):
        rl.mark("req-c", "engine.decode_block", steps=i)
    batch = rl.since(0, max_n=3)
    assert [m["seq"] for m in batch] == [1, 2, 3]
    rest = rl.since(batch[-1]["seq"], max_n=10)
    assert [m["seq"] for m in rest] == [4, 5]
    assert rl.since(5) == []


def test_summarize_marks_rebuilds_federated_summaries():
    rl = reqlog.RequestLog()
    rl.mark("req-d", "route.received", tenant="t9")
    rl.mark("req-d", "engine.first_token", ttft_s=0.5, queue_wait_s=0.1,
            preempt_wait_s=0.0, prefill_compute_s=0.4, cache_saved_s=0.0)
    rl.mark("req-d", "engine.finished")
    rl.mark("req-e", "route.shed", reason="parked_queue_full")
    summaries = {s["request_id"]: s
                 for s in reqlog.summarize_marks(rl.since(0))}
    assert summaries["req-d"]["terminal"] == "engine.finished"
    assert summaries["req-d"]["ttft_s"] == 0.5
    assert summaries["req-d"]["buckets"]["queue_wait_s"] == 0.1
    assert summaries["req-e"]["terminal"] == "route.shed"


def test_render_waterfall_orders_and_decomposes():
    rl = reqlog.RequestLog()
    rl.mark("req-w", "route.received", tenant="gold")
    rl.mark("req-w", "route.dispatched", replica="abc123", attempt=1)
    rl.mark("req-w", "engine.admitted", hit_pages=2, cached_tokens=16)
    rl.mark("req-w", "engine.first_token", ttft_s=0.8, queue_wait_s=0.2,
            preempt_wait_s=0.1, prefill_compute_s=0.5, cache_saved_s=0.3,
            cached_tokens=16)
    rl.mark("req-w", "engine.finished")
    text = reqlog.render_waterfall(rl.timeline("req-w"))
    lines = text.splitlines()
    assert "req-w" in lines[0] and "gold" in lines[0]
    positions = [text.index(p) for p in (
        "route.received", "route.dispatched", "engine.admitted",
        "engine.first_token", "engine.finished")]
    assert positions == sorted(positions)  # causal order preserved
    assert any("TTFT 0.8000s = queue_wait 0.2000 + preempt_wait 0.1000 "
               "+ prefill_compute 0.5000" in line for line in lines)
    assert any("cache_saved ~0.3000s" in line for line in lines)
    assert lines[-1].strip() == "terminal: engine.finished"
    assert reqlog.render_waterfall([]) == "(no marks)"


def test_module_mark_is_noop_without_id_or_when_disabled():
    before = reqlog.log().stats()["seq"]
    reqlog.mark(None, "engine.submitted")
    assert reqlog.log().stats()["seq"] == before
    cfg.set(serve_request_log=False)
    try:
        assert not reqlog.enabled()
        reqlog.mark("req-off", "engine.submitted")
        assert reqlog.log().stats()["seq"] == before
    finally:
        cfg.reset()
    rid = reqlog.new_request_id()
    assert rid.startswith("req-") and len(rid) == 20
    assert rid != reqlog.new_request_id()


def test_register_phase_is_idempotent_and_additive():
    reqlog.register_phase("test.custom", "a drill phase")
    reqlog.register_phase("test.custom", "overwrite attempt ignored")
    assert reqlog.request_phases()["test.custom"] == "a drill phase"
    del reqlog.PHASES["test.custom"]


# -------------------------------------------------------- engine timelines


def test_prefix_hit_admit_timeline():
    """Second request over a warmed prefix records the hit at admission
    (hit_pages/cached_tokens) and a cache_saved estimate at first token."""
    config, params, engine = _tiny_engine()
    try:
        shared = [11, 22, 33, 44, 55, 66, 77, 88,
                  12, 23, 34, 45, 56, 67, 78, 89]  # 2 full pages
        warm = engine.submit(list(shared), max_tokens=2, tenant="warm",
                             request_id="req-warm")
        warm.result(timeout=60)
        hit = engine.submit(list(shared) + [7, 14, 21], max_tokens=2,
                            tenant="hit", request_id="req-hit")
        assert hit.request_id == "req-hit"
        hit.result(timeout=60)
        tl = reqlog.log().timeline("req-hit")
        phases = _phases(tl)
        assert phases[0] == "engine.submitted"
        assert phases[-1] == "engine.finished"
        admitted = next(m for m in tl if m["phase"] == "engine.admitted")
        assert admitted["attrs"]["hit_pages"] == 2
        assert admitted["attrs"]["cached_tokens"] == 16
        first = next(m for m in tl if m["phase"] == "engine.first_token")
        assert first["attrs"]["cache_saved_s"] > 0
        assert first["attrs"]["cached_tokens"] == 16
        assert "engine.prefill_chunk" in phases
    finally:
        engine.shutdown()


def test_spec_rollback_timeline():
    """Speculative rounds with an adversarial proposer record
    engine.spec_round marks whose rollback trail is visible (accepted <
    proposed, rolled-back pages accounted)."""
    from tests.test_speculative import WrongProposer

    vocab = get_config("llama-tiny").vocab_size
    config, params, engine = _tiny_engine(
        speculative_tokens=3, speculative_proposer=WrongProposer(vocab)
    )
    try:
        prompt = [5, 17, 42, 7, 9, 2]
        stream = engine.submit(prompt, max_tokens=10, request_id="req-spec")
        got = stream.result(timeout=120)
        assert got == _greedy_reference(config, params, prompt, 10)
        tl = reqlog.log().timeline("req-spec")
        rounds = [m for m in tl if m["phase"] == "engine.spec_round"]
        assert rounds, _phases(tl)
        assert all(m["attrs"]["accepted"] <= m["attrs"]["proposed"]
                   for m in rounds)
        # the wrong proposer rejects nearly everything: rollback visible
        assert any(m["attrs"]["accepted"] < m["attrs"]["proposed"]
                   for m in rounds)
    finally:
        engine.shutdown()


def test_flagship_waterfall_prefix_spec_preempt_resume():
    """THE acceptance drill: one request's waterfall shows a prefix-hit
    admission, speculative rounds, a lane preemption AND the resume —
    causally ordered — and the TTFT buckets sum within 5%."""
    from tests.test_speculative import WrongProposer

    config, params, engine = _tiny_engine(
        max_slots=1, decode_block_steps=2,
        speculative_tokens=3,
        speculative_proposer=WrongProposer(
            get_config("llama-tiny").vocab_size),
    )
    try:
        shared = [11, 22, 33, 44, 55, 66, 77, 88,
                  12, 23, 34, 45, 56, 67, 78, 89]
        warm = engine.submit(list(shared), max_tokens=2, tenant="warm",
                             request_id="req-fw-warm")
        warm.result(timeout=120)

        victim_prompt = list(shared) + [7, 14, 21, 28, 35, 42, 49, 56]
        victim = engine.submit(victim_prompt, max_tokens=24, tenant="bulk",
                               priority=0, request_id="req-fw-victim")
        victim_iter = iter(victim)
        first = next(victim_iter)

        high = engine.submit([101, 102, 103, 104, 105, 106, 107, 108],
                             max_tokens=4, tenant="paid", priority=1,
                             request_id="req-fw-high")
        high.result(timeout=120)
        victim_tokens = [first] + list(victim_iter)
        assert victim_tokens == _greedy_reference(
            config, params, victim_prompt, 24)
        assert engine.metrics["lane_preemptions"] >= 1

        tl = reqlog.log().timeline("req-fw-victim")
        phases = _phases(tl)
        for needed in ("engine.submitted", "engine.admitted",
                       "engine.first_token", "engine.spec_round",
                       "engine.preempted", "engine.resumed",
                       "engine.finished"):
            assert needed in phases, phases
        # causal order along the mono clock
        def at(phase):
            return next(m["mono"] for m in tl if m["phase"] == phase)
        assert (at("engine.submitted") <= at("engine.admitted")
                <= at("engine.first_token"))
        assert at("engine.preempted") <= at("engine.resumed")
        assert at("engine.resumed") <= at("engine.finished")
        admitted = next(m for m in tl if m["phase"] == "engine.admitted")
        assert admitted["attrs"]["hit_pages"] >= 1  # prefix hit
        # park charged into the preempt bucket at resume
        resumed = next(m for m in tl if m["phase"] == "engine.resumed")
        assert resumed["attrs"]["wait_s"] >= 0

        # TTFT buckets sum within the 5% acceptance band (exact by
        # construction; the band covers float noise)
        d = reqlog.decompose(tl)
        total = (d["queue_wait_s"] + d["preempt_wait_s"]
                 + d["prefill_compute_s"])
        assert abs(total - d["ttft_s"]) <= max(0.05 * d["ttft_s"], 1e-6)

        text = reqlog.render_waterfall(tl)
        for needed in ("engine.spec_round", "engine.preempted",
                       "engine.resumed", "TTFT",
                       "terminal: engine.finished"):
            assert needed in text, text
    finally:
        engine.shutdown()


def test_shed_and_expiry_record_terminal_phases():
    """The satellite fix: EVERY shed/expiry exit leaves a terminal mark
    — with the honest Retry-After on quota sheds."""
    tenancy.set_tenant("free", quota_rps=0.05, quota_burst=1.0)
    config, params, engine = _tiny_engine(max_slots=1)
    try:
        ok = engine.submit([3, 1, 4], max_tokens=16, tenant="free",
                           request_id="req-ok")
        with pytest.raises(BackPressureError):
            engine.submit([3, 1, 4], max_tokens=2, tenant="free",
                          request_id="req-quota")
        tl = reqlog.log().timeline("req-quota")
        assert _phases(tl) == ["engine.shed"]
        assert tl[0]["attrs"]["reason"] == "quota"
        assert tl[0]["attrs"]["retry_after_s"] > 0

        # expiry while queued behind the busy lane → engine.timeout
        doomed = engine.submit([4, 5, 6], max_tokens=4, tenant="other",
                               deadline_ts=time.time() + 0.15,
                               request_id="req-doomed")
        time.sleep(0.25)
        with pytest.raises(RequestTimeoutError):
            doomed.result(timeout=60)
        ok.result(timeout=120)
        doomed_tl = reqlog.log().timeline("req-doomed")
        assert doomed_tl[-1]["phase"] == "engine.timeout"
        summaries = {s["request_id"]: s for s in reqlog.log().requests()}
        assert summaries["req-quota"]["terminal"] == "engine.shed"
        assert summaries["req-doomed"]["terminal"] == "engine.timeout"
        # terminal requests surface on the slow_only worklist
        slow = reqlog.log().requests(slow_only=True)
        assert any(s["request_id"] == "req-doomed" for s in slow)
        assert not any(s["request_id"] == "req-quota" for s in slow)
    finally:
        engine.shutdown()


def test_observe_tenant_ttft_never_fires_for_tokenless_requests():
    """A request that died before its first token must not contribute a
    TTFT sample (the pre-fix bug polluted tenant windows with zeros)."""
    r = _Request(rid=1, prompt=[1, 2], max_tokens=2, temperature=0.0,
                 out=queue_mod.Queue(), tenant="t-ghost")
    assert r.first_token_at is None
    assert _observe_tenant_ttft(r) == {}
    assert tenancy.drain_ttft_window() == {}
    assert tenancy.drain_ttft_breakdown() == {}


# --------------------------------------------- tenancy breakdown + watchdog


def test_ttft_breakdown_windows_and_queue_wait_p99_ledger():
    from ray_tpu.util.watchdog import ServeSLOMonitor, _dominant_ttft_bucket

    for _ in range(10):
        tenancy.observe_ttft("t-slow", 5.0)
        tenancy.observe_ttft_breakdown("t-slow", {
            "ttft_s": 5.0, "queue_wait_s": 4.0, "preempt_wait_s": 0.5,
            "prefill_compute_s": 0.5,
        })
    assert _dominant_ttft_bucket(
        [{"queue_wait_s": 4.0, "preempt_wait_s": 0.5,
          "prefill_compute_s": 0.5}]
    ) == ("queue_wait", pytest.approx(0.8))
    assert _dominant_ttft_bucket([]) is None

    cfg.set(serve_slo_ttft_p99_s=0.1, serve_slo_queue_p99_s=0.2)
    mon = ServeSLOMonitor()
    out = mon.check()
    assert out["ttft_p99:t-slow"] == 5.0
    assert out["queue_wait_p99:t-slow"] == 4.0
    report = mon.attainment_report()
    led = report["queue_wait_p99:t-slow"]
    assert led["last_p99_s"] == 4.0
    assert led["violated"] == 1 and led["attainment"] == 0.0
    # the burn warning names the dominant bucket
    from ray_tpu.util.events import events
    burns = [e for e in events().list(limit=100)
             if e.get("kind") == "watchdog.slo_burn"
             and "t-slow" in e.get("message", "")]
    assert burns, "no tenant burn event"
    assert "dominant bucket: queue_wait (80% of TTFT)" in burns[-1]["message"]
    # windows drained: a second check has nothing tenant-scoped
    assert "ttft_p99:t-slow" not in mon.check()


# ----------------------------------------------------------- engine snapshot


def test_engine_snapshot_lanes_pages_and_fair_depths():
    from ray_tpu.util import state

    config, params, engine = _tiny_engine(max_slots=2, decode_block_steps=1)
    try:
        stream = engine.submit([1, 2, 3, 4, 5, 6, 7, 8, 9], max_tokens=48,
                               tenant="snap", request_id="req-snap")
        next(iter(stream))  # engine is mid-request
        # single-step decode blocks keep the lane busy for ~63 more
        # dispatches; poll a few point-in-time snapshots to catch one
        busy, snap = [], {}
        for _ in range(200):
            snap = engine.snapshot()
            busy = [l for l in snap["lanes"] if not l["free"]]
            if busy:
                break
        assert snap["kind"] == "paged"
        assert len(snap["lanes"]) == 2
        assert busy and busy[0]["request_id"] == "req-snap"
        assert busy[0]["tenant"] == "snap"
        assert snap["pages"]["in_use"] >= 1
        assert snap["pages"]["total"] == 63  # page 0 reserved
        assert isinstance(snap["fair_depths"], list)
        assert "prefix_cache" in snap and "chains" in snap["prefix_cache"]
        # the state view finds it through the weak engine registry
        all_snaps = state.engine_snapshot()
        assert any(s.get("kind") == "paged" and any(
            l.get("request_id") == "req-snap" for l in s.get("lanes", []))
            for s in all_snaps.values())
        stream.result(timeout=120)
        assert engine.prefix_cache is not None
        heads = engine.prefix_cache.chain_heads()
        assert all({"digest", "page", "refcount"} <= set(h) for h in heads)
    finally:
        engine.shutdown()


# ----------------------------------------------------- router + HTTP drills


@pytest.fixture()
def rt():
    runtime = ray_tpu.init(num_cpus=8, detect_accelerators=False)
    yield runtime
    serve.shutdown()
    ray_tpu.shutdown()


def test_request_id_threads_handle_to_replica_context(rt):
    @serve.deployment
    class Echo:
        def __call__(self, payload):
            from ray_tpu.serve.context import get_request_id

            return get_request_id()

    handle = serve.run(Echo.options(name="rid-echo").bind())
    got = ray_tpu.get(handle.options(request_id="req-explicit").remote(None),
                      timeout=30)
    assert got == "req-explicit"
    # recorder on: an id is minted for the caller when none was passed
    auto = ray_tpu.get(handle.remote(None), timeout=30)
    assert auto and auto.startswith("req-")
    tl = reqlog.log().timeline("req-explicit")
    phases = _phases(tl)
    assert "route.received" in phases and "route.dispatched" in phases


def test_router_failover_marks_both_hops(rt):
    @serve.deployment(num_replicas=2, max_ongoing_requests=4)
    class Slow:
        def __call__(self, payload):
            time.sleep(0.3)
            return f"ok-{payload}"

    handle = serve.run(Slow.options(name="ff").bind())
    rids = [f"req-ff-{i}" for i in range(8)]
    refs = [handle.options(timeout_s=30, request_id=rid).remote(i)
            for i, rid in enumerate(rids)]
    from ray_tpu.serve import api as serve_api

    state = serve_api._controller._states["ff"]
    time.sleep(0.05)
    ray_tpu.kill(state.replicas[0])
    assert ray_tpu.get(refs, timeout=60) == [f"ok-{i}" for i in range(8)]
    # at least one request failed over: its timeline records BOTH hops
    # (dispatch to the dead replica, failover, re-dispatch to a survivor)
    failed_over = [
        rid for rid in rids
        if "route.failover" in _phases(reqlog.log().timeline(rid))
    ]
    assert failed_over, "no request recorded a failover hop"
    tl = reqlog.log().timeline(failed_over[0])
    dispatches = [m for m in tl if m["phase"] == "route.dispatched"]
    assert len(dispatches) >= 2
    assert dispatches[0]["attrs"]["attempt"] < dispatches[-1]["attrs"]["attempt"]
    fo = next(m for m in tl if m["phase"] == "route.failover")
    assert fo["attrs"]["attempt"] >= 1


def test_http_429_body_carries_request_id_next_to_retry_after(rt):
    gate = threading.Event()

    @serve.deployment(num_replicas=1, max_ongoing_requests=1,
                      max_queued_requests=0)
    class Busy:
        def __call__(self, payload):
            gate.wait(timeout=30)
            return "ok"

    serve.run(Busy.options(name="busy-rid").bind())
    port = serve.start_http()
    blocked = serve.get_handle("busy-rid").options(timeout_s=30).remote("x")
    time.sleep(0.1)
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/busy-rid", data=b'"y"',
        headers={"Content-Type": "application/json",
                 "x-request-id": "req-shed-drill"},
    )
    with pytest.raises(urllib.error.HTTPError) as e:
        urllib.request.urlopen(req, timeout=30)
    assert e.value.code == 429
    assert e.value.headers.get("Retry-After")
    assert e.value.headers.get("x-request-id") == "req-shed-drill"
    body = json.loads(e.value.read())
    assert body["request_id"] == "req-shed-drill"
    tl = reqlog.log().timeline("req-shed-drill")
    phases = _phases(tl)
    assert phases[0] == "http.received"
    terminal = [p for p in phases if p in reqlog.TERMINAL_PHASES]
    assert terminal, phases
    gate.set()
    assert ray_tpu.get(blocked, timeout=30) == "ok"
    # a successful proxy call echoes the id in the 200 body too
    ok = urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{port}/busy-rid", data=b'"z"',
        headers={"Content-Type": "application/json",
                 "x-request-id": "req-ok-drill"},
    ), timeout=30)
    payload = json.loads(ok.read())
    assert payload["request_id"] == "req-ok-drill"
    assert ok.headers.get("x-request-id") == "req-ok-drill"


# ---------------------------------------------------------------- federation


def test_request_marks_federate_and_state_queries():
    from ray_tpu.core.gcs import REQLOG_NS
    from ray_tpu.util import state

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    try:
        ctx = rt.cluster
        my_hex = ctx.node_id.hex()
        reqlog.mark("req-fed", "route.received", tenant="fed")
        reqlog.mark("req-fed", "engine.first_token", tenant="fed",
                    ttft_s=9.0, queue_wait_s=8.0, preempt_wait_s=0.0,
                    prefill_compute_s=1.0)
        reqlog.mark("req-fed", "engine.finished", tenant="fed")
        reqlog.mark("req-other", "route.shed", reason="parked_queue_full")
        prev, tail = -1, []
        while len(tail) != prev:
            prev = len(tail)
            ctx._last_stats_ts = 0.0
            ctx._report_stats()
            tail = ctx.gcs.kv_get(my_hex, namespace=REQLOG_NS) or []
        assert tail, "no marks federated into the _requests table"
        assert all(m.get("node") for m in tail)
        # cursor advanced: another pass without new marks is a no-op
        before = len(tail)
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        assert len(ctx.gcs.kv_get(my_hex, namespace=REQLOG_NS)) == before
        # the state queries join + dedup (local ring ∪ federated table)
        tl = state.request_timeline("req-fed")
        assert _phases(tl) == ["route.received", "engine.first_token",
                               "engine.finished"]
        keys = [(m.get("node"), m.get("seq")) for m in tl]
        assert len(keys) == len(set(keys)), "duplicate (node, seq)"
        rows = {s["request_id"]: s for s in state.list_requests()}
        assert rows["req-fed"]["terminal"] == "engine.finished"
        assert rows["req-other"]["terminal"] == "route.shed"
        assert [s["request_id"] for s in state.list_requests(tenant="fed")] \
            == ["req-fed"]
        slow = state.list_requests(slow_only=True)
        assert any(s["request_id"] == "req-fed" for s in slow)  # 9s TTFT
        # a federated recorder off-switch: no new marks ship
        cfg.set(serve_request_log=False)
        reqlog.log().mark("req-dark", "route.received")
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        assert not any(m["rid"] == "req-dark" for m in
                       ctx.gcs.kv_get(my_hex, namespace=REQLOG_NS))
    finally:
        cfg.reset()
        ray_tpu.shutdown()


def test_reqlog_table_is_bounded():
    from ray_tpu.core.gcs import REQLOG_NS

    rt = ray_tpu.init(num_cpus=1, head=True, detect_accelerators=False)
    cfg.set(reqlog_table_cap=20, reqlog_federate_batch=500)
    try:
        ctx = rt.cluster
        for i in range(80):
            reqlog.mark(f"req-burst-{i}", "engine.submitted")
        ctx._last_stats_ts = 0.0
        ctx._report_stats()
        tail = ctx.gcs.kv_get(ctx.node_id.hex(), namespace=REQLOG_NS)
        assert len(tail) <= 20
        assert tail[-1]["rid"] == "req-burst-79"  # newest survive
    finally:
        cfg.reset()
        ray_tpu.shutdown()
