"""Speculative decoding: draft proposers, the exact accept/resample
step, and the engine's draft-and-verify rounds with KV/page rollback.

The load-bearing invariants:
- output EXACTNESS: at temperature 0 the speculative engine is
  token-for-token identical to the non-speculative engine (whatever the
  proposer does, including always-wrong drafts that reject every round);
  at temperature > 0 the per-step output DISTRIBUTION matches plain
  filtered sampling (standard speculative-sampling argument);
- rollback safety: pages a round speculates past the accepted frontier
  come back to the pool, never touching a prefix-cache-shared page, and
  a shared page the round must write gets COW-copied first.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import get_config, init_params
from ray_tpu.serve.llm.paged import PagedConfig
from ray_tpu.serve.llm.paged_engine import PagedEngineConfig, PagedLLMEngine
from ray_tpu.serve.llm.speculative import (
    NgramProposer,
    ReplayProposer,
    accept_speculative,
    filtered_scores,
)
from ray_tpu.util.metrics import registry

from tests.test_paged_engine import _greedy_reference


class WrongProposer:
    """Adversarial drill: drafts walk a +1 ring the greedy chain almost
    never follows, so nearly every round rejects at the first draft and
    rolls back its speculated pages."""

    def __init__(self, vocab: int, k: int = None):
        self.vocab = vocab
        self.k = k

    def propose(self, context, k):
        k = min(k, self.k) if self.k is not None else k
        return [(context[-1] + 1 + i) % self.vocab for i in range(k)]


def _spec_engine(model="llama-tiny", seed=0, spec=3, proposer=None, **over):
    config = get_config(model)
    params = init_params(config, jax.random.PRNGKey(seed))
    defaults = dict(
        max_slots=4,
        speculative_tokens=spec,
        speculative_proposer=proposer,
        paged=PagedConfig(
            page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2
        ),
    )
    defaults.update(over)
    return config, params, PagedLLMEngine(
        config, params, PagedEngineConfig(**defaults)
    )


# ------------------------------------------------------------------ proposers


def test_ngram_proposer_prefers_longest_and_newest_match():
    p = NgramProposer(max_ngram=3)
    # suffix [7, 8] occurs twice; the newest occurrence's continuation wins
    ctx = [7, 8, 1, 2, 7, 8, 9, 5, 7, 8]
    assert p.propose(ctx, 2) == [9, 5]
    # novel suffix: no proposal, the round degrades to plain decode
    assert p.propose([1, 2, 3, 4], 3) == []
    assert p.propose(ctx, 0) == []


def test_replay_proposer_stops_on_divergence():
    p = ReplayProposer({(1, 2): [10, 11, 12, 13]})
    assert p.propose([1, 2], 3) == [10, 11, 12]
    assert p.propose([1, 2, 10, 11], 3) == [12, 13]
    # context diverged from the recorded run: no more drafts
    assert p.propose([1, 2, 10, 99], 3) == []
    assert p.propose([5, 6], 3) == []


# ---------------------------------------------------------------- accept step


def test_accept_greedy_exact_prefix_and_bonus():
    """Greedy semantics: accept drafts while they match the argmax chain;
    first mismatch emits the argmax; a full match adds the bonus token."""
    b, kd, v = 3, 4, 11
    logits = np.full((b, kd, v), -10.0, np.float32)
    # lane 0: argmax chain 3, 4, 5, 6 — drafts [3, 4, 9]: accept 2, correct
    for j, t in enumerate([3, 4, 5, 6]):
        logits[0, j, t] = 10.0
    # lane 1: drafts all match -> all accepted plus the bonus from row 3
    for j, t in enumerate([1, 2, 3, 7]):
        logits[1, j, t] = 10.0
    tokens = np.zeros((b, kd), np.int32)
    tokens[0] = [0, 3, 4, 9]
    tokens[1] = [0, 1, 2, 3]
    counts = np.array([4, 4, 0], np.int32)  # lane 2 inactive
    out, n = accept_speculative(
        jnp.asarray(logits), jnp.asarray(tokens), jnp.asarray(counts),
        jax.random.PRNGKey(0),
        jnp.zeros((b,), jnp.float32),  # temperature 0 everywhere
        jnp.zeros((b,), jnp.int32), jnp.ones((b,), jnp.float32),
    )
    out, n = np.asarray(out), np.asarray(n)
    assert n.tolist() == [3, 4, 0]
    assert out[0, :3].tolist() == [3, 4, 5]   # 2 accepted + correction
    assert out[1, :4].tolist() == [1, 2, 3, 7]  # 3 accepted + bonus


def test_accept_rejection_sampling_marginal_is_exact():
    """temp > 0 with a point-mass draft: the FIRST emitted token's
    marginal must equal the filtered target distribution exactly
    (accept w.p. p(draft), else the renormalized residual)."""
    v, draft = 8, 2
    logits_row = jnp.asarray(
        np.linspace(-1.0, 1.0, v, dtype=np.float32)[None, :]
    )
    temps = jnp.asarray([0.7], jnp.float32)
    tks = jnp.asarray([5], jnp.int32)
    tps = jnp.asarray([0.9], jnp.float32)
    target = np.asarray(
        jax.nn.softmax(filtered_scores(logits_row, temps, tks, tps))
    )[0]
    logits = jnp.broadcast_to(logits_row[:, None, :], (1, 2, v))
    tokens = jnp.asarray([[0, draft]], jnp.int32)
    counts = jnp.asarray([2], jnp.int32)

    def first_token(key):
        out, _ = accept_speculative(
            logits, tokens, counts, key, temps, tks, tps
        )
        return out[0, 0]

    n = 20000
    toks = np.asarray(
        jax.vmap(first_token)(jax.random.split(jax.random.PRNGKey(7), n))
    )
    emp = np.bincount(toks, minlength=v) / n
    tv = 0.5 * np.abs(emp - target).sum()
    assert tv < 0.02, (tv, emp, target)


# --------------------------------------------------------- engine: exactness


def test_spec_ngram_greedy_parity_and_acceptance():
    """A repetitive prompt lets the n-gram proposer draft real spans:
    output stays exactly greedy and some drafts are accepted."""
    config, params, engine = _spec_engine()
    try:
        prompt = [5, 17, 42, 7, 5, 17, 42, 7, 5, 17, 42, 7]
        got = engine.generate(prompt, max_tokens=16)
        assert got == _greedy_reference(config, params, prompt, 16)
        m = engine.metrics
        assert m["spec_proposed"] > 0
        # one verify launch per round emits >= 1 token: launches/token <= 1
        assert m["decode_steps"] <= m["decode_tokens"]
    finally:
        engine.shutdown()


def test_spec_all_reject_parity_with_page_boundary_rollbacks():
    """Always-wrong drafts: every round rejects at draft 1, speculated
    pages roll back (across page boundaries), and the output is STILL
    exactly greedy. Afterwards every page returns to the pool."""
    config = get_config("llama-tiny")
    config2, params, engine = _spec_engine(
        proposer=WrongProposer(config.vocab_size)
    )
    try:
        prompt = [3, 1, 4, 1, 5]
        # 24 tokens from position 5: crosses pages at 8, 16, 24 (ps=8)
        got = engine.generate(prompt, max_tokens=24)
        assert got == _greedy_reference(config2, params, prompt, 24)
        m = engine.metrics
        assert m["spec_proposed"] > 0
        assert m["spec_acceptance_rate"] < 0.25
        assert m["spec_rollback_pages"] > 0
        deadline = time.time() + 10
        total = engine.paged.num_pages - 1  # page 0 reserved
        while engine.allocator.available < total:
            assert time.time() < deadline, "speculated pages leaked"
            time.sleep(0.01)
    finally:
        engine.shutdown()


def test_spec_staggered_batch_parity():
    config, params, engine = _spec_engine(model="gpt2-tiny", seed=1)
    try:
        prompts = [[1, 2, 3, 1, 2, 3], [9, 8, 9, 8], [30, 31, 30, 31], [4, 4, 4]]
        streams = []
        for p in prompts:
            streams.append((p, engine.submit(p, max_tokens=6)))
            time.sleep(0.02)
        for p, s in streams:
            got = s.result(timeout=60)
            assert got == _greedy_reference(engine.model_config, params, p, 6)
    finally:
        engine.shutdown()


def test_spec_replay_acceptance_reduces_launches():
    """Replaying a recorded greedy run makes every draft accept: the
    acceptance-rate gauge pins near 1 and verify launches per generated
    token drop well below 1 (the whole point of speculation)."""
    config, params, base = _spec_engine(spec=0)
    prompt = [11, 3, 11, 3, 7, 2]
    try:
        recorded = base.generate(prompt, max_tokens=16)
    finally:
        base.shutdown()
    _, _, engine = _spec_engine(
        proposer=ReplayProposer({tuple(prompt): recorded})
    )
    try:
        got = engine.generate(prompt, max_tokens=16)
        assert got == recorded
        m = engine.metrics
        assert m["spec_acceptance_rate"] >= 0.6
        assert m["decode_steps"] / m["decode_tokens"] <= 1 / 1.8
    finally:
        engine.shutdown()


# ----------------------------------------------- engine: rollback vs sharing


def _manual_spec_engine(monkeypatch, proposer, **over):
    monkeypatch.setattr(PagedLLMEngine, "_loop", lambda self: None)
    return _spec_engine(
        proposer=proposer,
        paged=PagedConfig(
            page_size=8, num_pages=64, max_pages_per_slot=8, chunk_pages=2,
            prefix_cache=True,
        ),
        **over,
    )


def _prefill_and_seed(engine):
    """Drive one request to the speculative steady state by hand:
    admit, prefill every chunk, then pump the 'first' fetch that seeds
    the host-side draft context."""
    engine._admit()
    slot = engine.slots[0]
    while slot.prefilling:
        assert engine._prefill_tick()
    deadline = time.time() + 30
    while slot.spec_ctx is None:
        engine._pump_completed(wait=True)
        assert time.time() < deadline, "first token never arrived"
    return slot


def _run_one_round(engine, slot):
    assert engine._dispatch_spec_verify()
    deadline = time.time() + 30
    while slot.spec_inflight:
        engine._pump_completed(wait=True)
        assert time.time() < deadline, "verify round never drained"


def test_spec_rollback_never_touches_prefix_shared_page(monkeypatch):
    """A fully-rejected round that grew a fresh page trims exactly that
    page; the prompt page pinned by the prefix cache (and shared with a
    manufactured second holder) keeps every ref."""
    config = get_config("llama-tiny")
    config, params, engine = _manual_spec_engine(
        monkeypatch, WrongProposer(config.vocab_size)
    )
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(5).integers(1, 200, size=14)]
        engine.submit(prompt, max_tokens=8)
        slot = _prefill_and_seed(engine)
        assert slot.position == 14 and len(slot.pages) == 2
        shared = slot.pages[0]  # full prompt page, cache-pinned
        assert engine.allocator.refcount(shared) == 2
        engine.allocator.share([shared])  # simulate another slot's hold
        free_before = engine.allocator.available
        # round writes positions 14..17 -> grows page 2, rejects, trims it
        _run_one_round(engine, slot)
        assert engine.metrics["spec_rollback_pages"] == 1.0
        assert slot.position == 15 and len(slot.pages) == 2
        assert engine.allocator.available == free_before
        assert engine.allocator.refcount(shared) == 3  # untouched
        assert engine.block_tables[0, 2] == 0
        engine.allocator.free([shared])
    finally:
        engine.shutdown()


def test_spec_round_cow_copies_shared_write_page_then_rolls_back(monkeypatch):
    """The round's write range includes a SHARED partial page: the engine
    COW-copies it before dispatch (shared original keeps its other
    holder), then rollback frees only the round's fresh growth — the
    original is never double-freed."""
    config = get_config("llama-tiny")
    config, params, engine = _manual_spec_engine(
        monkeypatch, WrongProposer(config.vocab_size)
    )
    try:
        prompt = [int(t) for t in
                  np.random.default_rng(6).integers(1, 200, size=14)]
        engine.submit(prompt, max_tokens=8)
        slot = _prefill_and_seed(engine)
        victim = slot.pages[1]  # partial page the round writes first
        assert engine.allocator.refcount(victim) == 1
        engine.allocator.share([victim])
        _run_one_round(engine, slot)
        assert engine.metrics["prefix_cache_cow"] == 1.0
        assert slot.pages[1] != victim
        assert engine.allocator.refcount(victim) == 1  # slot's ref dropped
        assert engine.allocator.refcount(slot.pages[1]) == 1
        assert engine.metrics["spec_rollback_pages"] == 1.0
        assert engine.block_tables[0, 1] == slot.pages[1]
        engine.allocator.free([victim])  # last holder: recycles cleanly
        assert engine.allocator.refcount(victim) == 0
    finally:
        engine.shutdown()


# ------------------------------------------------------------------- gauges


@pytest.fixture
def clean_registry():
    registry().clear()
    yield
    registry().clear()


def test_spec_metrics_and_gauges_exported(clean_registry):
    config, params, engine = _spec_engine()
    try:
        prompt = [5, 17, 42, 7, 5, 17, 42, 7]
        engine.generate(prompt, max_tokens=12)
        stats = engine.stats()
        for key in ("spec_proposed", "spec_accepted",
                    "spec_acceptance_rate", "spec_rollback_pages"):
            assert key in stats, key
        assert stats["spec_proposed"] > 0
        assert 0.0 <= stats["spec_acceptance_rate"] <= 1.0
        text = registry().prometheus_text()
        for gauge in ("raytpu_engine_spec_proposed",
                      "raytpu_engine_spec_accepted",
                      "raytpu_engine_spec_acceptance_rate",
                      "raytpu_engine_spec_rollback_pages"):
            assert '%s{engine="%s"}' % (gauge, engine.metrics_label) in text
    finally:
        engine.shutdown()
